"""Library quick start: build a DCOP in code, solve on the device
engine and on the reference-semantics threaded runtime, compare.

Run: python examples/api_quickstart.py
(mirrors the reference's tests/integration standalone-script style)
"""

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str


def build():
    colors = Domain("colors", "color", ["R", "G", "B"])
    dcop = DCOP("quickstart", objective="min")
    v1, v2, v3 = (Variable(n, colors) for n in ("v1", "v2", "v3"))
    for v in (v1, v2, v3):
        dcop.add_variable(v)
    # Soft graph coloring: conflict costs 1, v1 prefers R (cost -0.1).
    dcop.add_constraint(constraint_from_str(
        "diff12", "1 if v1 == v2 else 0", [v1, v2]))
    dcop.add_constraint(constraint_from_str(
        "diff23", "1 if v2 == v3 else 0", [v2, v3]))
    dcop.add_constraint(constraint_from_str(
        "pref1", "-0.1 if v1 == 'R' else 0", [v1]))
    dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
    return dcop


def main():
    dcop = build()
    device = solve(dcop, "maxsum", max_cycles=200)
    print("device :", device["assignment"], "cost", device["cost"])

    thread = solve(build(), "maxsum", backend="thread",
                   distribution="adhoc", timeout=3)
    print("thread :", thread["assignment"], "cost", thread["cost"])

    assert device["cost"] == thread["cost"] == -0.1
    print("identical optimal cost on both backends")


if __name__ == "__main__":
    main()

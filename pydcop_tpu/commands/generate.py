"""``pydcop generate``: benchmark problem generators.

Reference parity: pydcop/commands/generate.py — subcommands
graph_coloring, ising, meetings, secp, agents, scenario, iot,
small_world with the reference's argument names, plus an added --seed on
every generator (deterministic output).
"""

import sys


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "generate", help="generate random problems")
    gen_sub = parser.add_subparsers(
        title="problems", dest="problem",
        description="type of problem to generate")
    parser.set_defaults(func=lambda args: (parser.print_help(), 2)[1])

    p = gen_sub.add_parser(
        "graph_coloring", help="graph coloring benchmark")
    p.add_argument("-v", "--variables_count", type=int, required=True)
    p.add_argument("-c", "--colors_count", type=int, required=True)
    p.add_argument("-g", "--graph", required=True,
                   choices=["random", "grid", "scalefree"])
    p.add_argument("--allow_subgraph", action="store_true")
    p.add_argument("--soft", action="store_true")
    p.add_argument("--intentional", action="store_true")
    p.add_argument("--noagents", action="store_true")
    p.add_argument("-p", "--p_edge", type=float, default=None)
    p.add_argument("-m", "--m_edge", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.set_defaults(func=_gen_graph_coloring)

    p = gen_sub.add_parser("ising", help="ising benchmark")
    p.add_argument("--row_count", type=int, required=True)
    p.add_argument("--col_count", type=int, default=None)
    p.add_argument("--bin_range", type=float, default=1.6)
    p.add_argument("--un_range", type=float, default=0.05)
    p.add_argument("--intentional", action="store_true")
    p.add_argument("--no_agents", action="store_true")
    p.add_argument("--fg_dist", action="store_true")
    p.add_argument("--var_dist", action="store_true")
    p.add_argument("--dist_dir", default=".",
                   help="directory for distribution files")
    p.add_argument("--seed", type=int, default=None)
    p.set_defaults(func=_gen_ising)

    p = gen_sub.add_parser(
        "meetings", help="meeting scheduling benchmark (PEAV)")
    p.add_argument("--slots_count", type=int, required=True)
    p.add_argument("--events_count", type=int, required=True)
    p.add_argument("--resources_count", type=int, required=True)
    p.add_argument("--max_resources_event", type=int, required=True)
    p.add_argument("--max_length_event", type=int, default=1)
    p.add_argument("--max_resource_value", type=int, default=10)
    p.add_argument("--no_agents", action="store_true")
    p.add_argument("--capacity", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.set_defaults(func=_gen_meetings)

    p = gen_sub.add_parser("secp", help="smart-lighting SECP")
    p.add_argument("-l", "--lights", type=int, required=True)
    p.add_argument("-m", "--models", type=int, required=True)
    p.add_argument("-r", "--rules", type=int, required=True)
    p.add_argument("-c", "--capacity", type=int, default=None)
    p.add_argument("--max_model_size", type=int, default=3)
    p.add_argument("--max_rule_size", type=int, default=3)
    p.add_argument("--seed", type=int, default=None)
    p.set_defaults(func=_gen_secp)

    p = gen_sub.add_parser("agents", help="agent definitions")
    p.add_argument("--mode", required=True,
                   choices=["variables", "count"])
    p.add_argument("--dcop_files", type=str, nargs="+", default=None)
    p.add_argument("--count", type=int, default=None)
    p.add_argument("--agent_prefix", type=str, default="a")
    p.add_argument("--capacity", type=int, required=True)
    p.add_argument("--hosting", default="None",
                   choices=["None", "name_mapping", "var_startswith"])
    p.add_argument("--hosting_default", type=int, default=None)
    p.add_argument("--routes", default="None",
                   choices=["None", "uniform", "graph"])
    p.add_argument("--routes_default", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("dcop_files_end", type=str, nargs="*", default=None)
    p.set_defaults(func=_gen_agents)

    p = gen_sub.add_parser("scenario", help="dynamic DCOP scenario")
    p.add_argument("--evts_count", type=int, required=True)
    p.add_argument("--actions_count", type=int, required=True)
    p.add_argument("--delay", type=float, required=True)
    p.add_argument("--initial_delay", type=float, default=20)
    p.add_argument("--end_delay", type=float, default=20)
    p.add_argument("--dcop_files", type=str, nargs="+", default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("dcop_files_end", type=str, nargs="*", default=None)
    p.set_defaults(func=_gen_scenario)

    p = gen_sub.add_parser("iot", help="IoT benchmark (scale-free)")
    p.add_argument("-n", "--num_devices", type=int, required=True)
    p.add_argument("-d", "--domain_size", type=int, default=3)
    p.add_argument("-m", "--m_edge", type=int, default=2)
    p.add_argument("-r", "--range_cost", type=int, default=10)
    p.add_argument("--seed", type=int, default=None)
    p.set_defaults(func=_gen_iot)

    p = gen_sub.add_parser(
        "small_world", help="small-world benchmark")
    p.add_argument("-n", "--num_variables", type=int, required=True)
    p.add_argument("-d", "--domain_range", type=int, default=10)
    p.add_argument("-k", "--degree", type=int, default=4)
    p.add_argument("-p", "--p_rewire", type=float, default=0.1)
    p.add_argument("-r", "--range_cost", type=int, default=10)
    p.add_argument("--seed", type=int, default=None)
    p.set_defaults(func=_gen_small_world)


def _output(args, text: str) -> int:
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def _gen_graph_coloring(args) -> int:
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.generators.graphcoloring import generate_graph_coloring

    dcop = generate_graph_coloring(
        args.variables_count, args.colors_count, args.graph,
        soft=args.soft, intentional=args.intentional,
        p_edge=args.p_edge, m_edge=args.m_edge,
        allow_subgraph=args.allow_subgraph, noagents=args.noagents,
        seed=args.seed,
    )
    return _output(args, dcop_yaml(dcop))


def _gen_ising(args) -> int:
    import os

    from pydcop_tpu.dcop.yamldcop import dcop_yaml, yaml_dist
    from pydcop_tpu.distribution.objects import Distribution
    from pydcop_tpu.generators.ising import generate_ising

    dcop, var_mapping, fg_mapping = generate_ising(
        args.row_count, args.col_count, args.bin_range, args.un_range,
        extensive=not args.intentional, no_agents=args.no_agents,
        fg_dist=args.fg_dist, var_dist=args.var_dist, seed=args.seed,
    )
    if var_mapping:
        path = os.path.join(args.dist_dir, f"{dcop.name}_vardist.yaml")
        with open(path, "w", encoding="utf-8") as f:
            f.write(yaml_dist(Distribution(var_mapping)))
    if fg_mapping:
        path = os.path.join(args.dist_dir, f"{dcop.name}_fgdist.yaml")
        with open(path, "w", encoding="utf-8") as f:
            f.write(yaml_dist(Distribution(fg_mapping)))
    return _output(args, dcop_yaml(dcop))


def _gen_meetings(args) -> int:
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.generators.meetingscheduling import generate_meetings

    dcop = generate_meetings(
        args.slots_count, args.events_count, args.resources_count,
        args.max_resources_event, args.max_length_event,
        args.max_resource_value, no_agents=args.no_agents,
        capacity=args.capacity, seed=args.seed,
    )
    return _output(args, dcop_yaml(dcop))


def _gen_secp(args) -> int:
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.generators.secp import generate_secp

    dcop = generate_secp(
        args.lights, args.models, args.rules, capacity=args.capacity,
        max_model_size=args.max_model_size,
        max_rule_size=args.max_rule_size, seed=args.seed,
    )
    return _output(args, dcop_yaml(dcop))


def _gen_agents(args) -> int:
    from pydcop_tpu.dcop.yamldcop import yaml_agents
    from pydcop_tpu.generators.agents_gen import generate_agents

    dcop_files = args.dcop_files or args.dcop_files_end
    variables, adjacency = None, None
    if dcop_files:
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

        dcop = load_dcop_from_file(dcop_files)
        variables = list(dcop.variables)
        adjacency = [
            (a, b)
            for c in dcop.constraints.values()
            for i, a in enumerate(c.scope_names)
            for b in c.scope_names[i + 1:]
        ]
    agents = generate_agents(
        mode=args.mode, count=args.count, variables=variables,
        agent_prefix=args.agent_prefix, capacity=args.capacity,
        hosting=args.hosting, hosting_default=args.hosting_default,
        routes=args.routes, routes_default=args.routes_default,
        adjacency=adjacency, seed=args.seed,
    )
    return _output(args, yaml_agents(agents))


def _gen_scenario(args) -> int:
    from pydcop_tpu.dcop.yamldcop import (
        load_dcop_from_file,
        yaml_scenario,
    )
    from pydcop_tpu.generators.scenario_gen import generate_scenario

    dcop_files = args.dcop_files or args.dcop_files_end
    if not dcop_files:
        print("Error: scenario generation requires dcop file(s)")
        return 2
    dcop = load_dcop_from_file(dcop_files)
    scenario = generate_scenario(
        args.evts_count, args.actions_count, args.delay,
        list(dcop.agents), initial_delay=args.initial_delay,
        end_delay=args.end_delay, seed=args.seed,
    )
    return _output(args, yaml_scenario(scenario))


def _gen_iot(args) -> int:
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.generators.iot import generate_iot

    dcop = generate_iot(
        args.num_devices, args.domain_size, args.m_edge,
        args.range_cost, seed=args.seed,
    )
    return _output(args, dcop_yaml(dcop))


def _gen_small_world(args) -> int:
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.generators.smallworld import generate_small_world

    dcop = generate_small_world(
        args.num_variables, args.domain_range, args.degree,
        args.p_rewire, args.range_cost, seed=args.seed,
    )
    return _output(args, dcop_yaml(dcop))

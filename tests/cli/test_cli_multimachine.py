"""CLI tests for process mode + standalone orchestrator/agent commands.

This is how multi-node behavior is tested without a cluster (reference
strategy, tests/dcop_cli/test_solve.py:55-58): HTTP transports on
localhost ports.
"""

import json
import os
import socket
import subprocess
import sys
import time

from fixtures_paths import LOCAL_INSTANCES as INSTANCES
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}
FIXTURE = os.path.join(INSTANCES, "coloring_4agents_10vars.yaml")

# The orchestrator binds ``port`` and the agent process binds
# ``port+1 .. port+n_agents`` — a CONTIGUOUS block.  Fixed ports
# (19340/19480 historically) flake on warm reruns: the previous run's
# sockets linger in TIME_WAIT, the agent process dies with
# EADDRINUSE, and the orchestrator then times out on an empty
# directory.  ``_free_port_block`` probes OS-chosen candidates until a
# whole block binds, and ``_run_orchestrated`` retries the spawn when
# the (tiny) pick-to-bind race still loses.
PORT_BLOCK = 5


def _free_port_block(n: int = PORT_BLOCK, attempts: int = 50) -> int:
    """A base port p such that p..p+n-1 all bind right now."""
    for _ in range(attempts):
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            base = probe.getsockname()[1]
        if base + n >= 65536:
            continue
        held = []
        try:
            for offset in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + offset))
                held.append(s)
        except OSError:
            continue
        finally:
            for s in held:
                s.close()
        return base
    raise RuntimeError(f"no free block of {n} ports found")


def _run_orchestrated(agent_args, orch_args, orch_timeout,
                      agent_wait, attempts: int = 3):
    """Spawn the agent process on a fresh port block, run the
    orchestrator against it, retry both ONLY on an EADDRINUSE loser
    (the agent dying on startup, or the orchestrator reporting the
    bind error) — any other orchestrator failure is a real failure
    and raises immediately, stderr attached."""
    last_error = None
    for _ in range(attempts):
        port = _free_port_block()
        agent_proc = subprocess.Popen(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli",
             *agent_args(port)],
            env=ENV, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            time.sleep(0.5)
            if agent_proc.poll() is not None:
                # Lost the pick-to-bind race: a fresh block, again.
                last_error = RuntimeError(
                    f"agent process died on startup (exit "
                    f"{agent_proc.returncode}, base port {port})")
                continue
            proc = subprocess.run(
                [sys.executable, "-m", "pydcop_tpu.dcop_cli",
                 *orch_args(port)],
                timeout=orch_timeout, env=ENV,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            )
            if proc.returncode != 0:
                stderr = proc.stderr.decode(errors="replace")
                if "Address already in use" not in stderr:
                    raise AssertionError(
                        f"orchestrator failed (exit "
                        f"{proc.returncode}), not a port race:\n"
                        f"{stderr[-1500:]}")
                last_error = RuntimeError(
                    f"orchestrator lost the port race on {port}")
                continue
            result = json.loads(proc.stdout)
            # Agents exit once the orchestrator stops them.
            assert agent_proc.wait(timeout=agent_wait) == 0
            return result
        finally:
            if agent_proc.poll() is None:
                agent_proc.kill()
    raise last_error


def test_solve_mode_process():
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "5",
         "solve", "-a", "dsa", "-d", "adhoc", "-m", "process",
         FIXTURE],
        timeout=180, env=ENV,
    )
    result = json.loads(out)
    assert result["backend"] == "process"
    assert len(result["assignment"]) == 10
    assert result["msg_count"] > 0


def test_orchestrator_and_agent_commands(tmp_path):
    result = _run_orchestrated(
        agent_args=lambda port: [
            "-t", "40", "agent", "-n", "a1", "a2", "a3", "a4",
            "-o", f"127.0.0.1:{port}", "-p", str(port + 1),
            "--capacity", "100"],
        orch_args=lambda port: [
            "-t", "4", "orchestrator", "-a", "dsa", "-d", "adhoc",
            "--port", str(port), FIXTURE],
        orch_timeout=120, agent_wait=30,
    )
    assert result["backend"] == "multi-machine"
    assert len(result["assignment"]) == 10


def test_solve_mode_process_maxsum():
    """MaxSum over HTTP: factor/variable computations and their custom
    wire format (MaxSumMessage costs dict) cross real process + JSON
    boundaries.  MaxSum has no stop condition, so the run always lasts
    the full -t: large enough to converge under machine load (8 s was
    flaky during parallel benches), small enough to keep the suite
    quick."""
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "12",
         "solve", "-a", "maxsum", "-d", "adhoc", "-m", "process",
         os.path.join(INSTANCES, "coloring_chain.yaml")],
        timeout=180, env=ENV,
    )
    result = json.loads(out)
    assert result["backend"] == "process"
    assert set(result["assignment"]) == {"w1", "w2", "w3", "w4"}
    # Converged to a feasible coloring of the 4-chain (maxsum folds the
    # unary preferences in, so any proper coloring costs <= 0.6).
    assert result["cost"] <= 0.6 + 1e-6


def test_solve_mode_process_mgm2():
    """MGM2's 5-phase protocol (value/offer/response/gain/go) over the
    HTTP transport: offers are tuple-triples that JSON converts to
    lists, so this exercises sequence-robust message handling."""
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "10",
         "solve", "-a", "mgm2", "-d", "adhoc", "-m", "process",
         "-p", "stop_cycle:20",
         os.path.join(INSTANCES, "coloring_chain.yaml")],
        timeout=180, env=ENV,
    )
    result = json.loads(out)
    assert result["backend"] == "process"
    assert set(result["assignment"]) == {"w1", "w2", "w3", "w4"}


def test_orchestrator_scenario_repair_over_http(tmp_path):
    """Dynamic multi-machine run: standalone orchestrator with a
    scenario that removes agent a1 mid-run, 2-replication, repair over
    real HTTP transports — the full reference resilience flow
    (orchestrator.py:955-1178) end to end."""
    scenario = os.path.join(
        os.path.dirname(__file__), "..", "instances",
        "scenario_remove_a1.yaml")
    result = _run_orchestrated(
        agent_args=lambda port: [
            "-t", "90", "agent", "-n", "a1", "a2", "a3", "a4",
            "-o", f"127.0.0.1:{port}", "-p", str(port + 1),
            "--capacity", "100", "--replication"],
        orch_args=lambda port: [
            "-t", "15", "orchestrator", "-a", "dsa", "-d", "adhoc",
            "-k", "2", "-s", scenario, "--port", str(port), FIXTURE],
        orch_timeout=120, agent_wait=45,
    )
    assert result["backend"] == "multi-machine"
    # All 10 variables still assigned despite a1's departure.
    assert len(result["assignment"]) == 10
    replication = result["replication"]
    assert replication["ktarget"] == 2
    # a1 hosted computations; they must have been repaired onto
    # surviving agents.
    assert replication["repaired"]

"""Dynamic DCOP on the device engine: warm-started trajectory across
factor edits, with checkpoint/resume.

Run: python examples/dynamic_dcop.py
"""

import numpy as np

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.dynamic import DynamicMaxSumEngine


def main():
    d = Domain("d", "", [0, 1, 2])
    vs = [Variable(f"v{i}", d) for i in range(12)]
    eq = np.eye(3)
    ring = [
        NAryMatrixRelation([vs[i], vs[(i + 1) % 12]], eq, f"c{i}")
        for i in range(12)
    ]
    engine = DynamicMaxSumEngine(vs, ring, mode="min")

    res = engine.run(60)
    print("initial ring :", "cost", engine.cost(res.assignment),
          "after", res.cycles, "cycles")

    # Live edits: drop one factor, add a chord — array surgery inside
    # padding slack, message state warm-starts (no recompile).
    engine.remove_factor("c0")
    engine.add_factor(NAryMatrixRelation([vs[0], vs[6]], eq, "chord"))
    res = engine.run(60)
    print("after edits  :", "cost", engine.cost(res.assignment),
          "recompiles", res.metrics["recompiles"])

    # Device state is a handful of arrays: checkpoint to disk, then
    # resume in a fresh engine bit-exactly.
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        engine.checkpoint(f.name)
        engine2 = DynamicMaxSumEngine(
            vs, list(engine.factors.values()), mode="min")
        engine2.restore(f.name)
    r1 = engine.run(30)
    r2 = engine2.run(30)
    assert r1.assignment == r2.assignment
    print("checkpoint/resume: identical trajectory after restore")


if __name__ == "__main__":
    main()

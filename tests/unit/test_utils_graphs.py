"""Tests for utils.graphs + utils.various (reference parity:
pydcop/utils/graphs.py, various.py)."""

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.utils.graphs import (
    all_pairs,
    as_networkx_bipartite_graph,
    as_networkx_graph,
    calc_diameter,
    constraint_adjacency,
    cycles_count,
    graph_diameter,
)
from pydcop_tpu.utils.various import func_args

d = Domain("d", "", [0, 1])


def _chain(n):
    """v0 - v1 - ... - v(n-1)."""
    variables = [Variable(f"v{i}", d) for i in range(n)]
    constraints = [
        constraint_from_str(
            f"c{i}", f"v{i} + v{i + 1}",
            [variables[i], variables[i + 1]],
        )
        for i in range(n - 1)
    ]
    return variables, constraints


def test_adjacency():
    variables, constraints = _chain(3)
    adj = constraint_adjacency(variables, constraints)
    assert adj["v0"] == {"v1"}
    assert adj["v1"] == {"v0", "v2"}


def test_diameter_chain():
    variables, constraints = _chain(4)
    adj = constraint_adjacency(variables, constraints)
    assert calc_diameter(adj) == 3
    assert graph_diameter(variables, constraints) == [3]


def test_diameter_components():
    variables, constraints = _chain(3)
    lone = Variable("w0", d)
    lone2 = Variable("w1", d)
    extra = constraint_from_str("cw", "w0 + w1", [lone, lone2])
    diameters = graph_diameter(
        variables + [lone, lone2], constraints + [extra]
    )
    assert sorted(diameters) == [1, 2]


def test_cycles_count():
    variables, constraints = _chain(3)
    assert cycles_count(variables, constraints) == 0
    closing = constraint_from_str(
        "c_close", "v0 + v2", [variables[0], variables[2]]
    )
    assert cycles_count(variables, constraints + [closing]) == 1


def test_all_pairs():
    assert list(all_pairs([1, 2, 3])) == [(1, 2), (1, 3), (2, 3)]


def test_networkx_bridges():
    variables, constraints = _chain(3)
    g = as_networkx_graph(variables, constraints)
    assert set(g.nodes) == {"v0", "v1", "v2"}
    assert g.number_of_edges() == 2
    b = as_networkx_bipartite_graph(variables, constraints)
    assert set(b.nodes) == {"v0", "v1", "v2", "c0", "c1"}
    assert b.number_of_edges() == 4


def test_func_args():
    assert func_args(lambda x, y: x) == ["x", "y"]

    def f(a, b, *, c):
        return a

    assert func_args(f) == ["a", "b"]
    assert func_args(len) in ([], ["obj"])

"""Plugin-contract conformance for every algorithm module (reference
contract: docs/implementation/algorithms.rst:18-241 + default injection
at algorithms/__init__.py:528-566): GRAPH_TYPE, typed params with
defaults, computation_memory / communication_load hooks usable on real
graph nodes, and solve entry points."""

import pytest

from pydcop_tpu.algorithms import (
    AlgorithmDef,
    list_available_algorithms,
    load_algorithm_module,
)
from pydcop_tpu.computations_graph import load_graph_module
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str

ALGOS = list_available_algorithms()
GRAPH_TYPES = {"factor_graph", "constraints_hypergraph", "pseudotree",
               "ordered_graph"}


def _dcop():
    d = Domain("colors", "color", ["R", "G", "B"])
    dcop = DCOP("contract", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    dcop.add_constraint(constraint_from_str(
        "c1", "1 if v0 == v1 else 0", [vs[0], vs[1]]))
    dcop.add_constraint(constraint_from_str(
        "c2", "1 if v1 == v2 else 0", [vs[1], vs[2]]))
    return dcop


def test_all_fourteen_algorithms_discoverable():
    assert set(ALGOS) == {
        "adsa", "amaxsum", "dba", "dpop", "dsa", "dsatuto", "gdba",
        "maxsum", "maxsum_dynamic", "mgm", "mgm2", "mixeddsa", "ncbb",
        "syncbb",
    }


@pytest.mark.parametrize("algo", ALGOS)
def test_graph_type_is_a_known_model(algo):
    module = load_algorithm_module(algo)
    assert module.GRAPH_TYPE in GRAPH_TYPES
    # and the model actually loads + builds on a real DCOP
    cg = load_graph_module(
        module.GRAPH_TYPE).build_computation_graph(_dcop())
    assert len(list(cg.nodes)) >= 3


@pytest.mark.parametrize("algo", ALGOS)
def test_params_have_types_and_valid_defaults(algo):
    module = load_algorithm_module(algo)
    for p in module.algo_params:
        assert p.type in ("int", "float", "str", "bool"), \
            f"{algo}.{p.name}: {p.type}"
        if p.values is not None and p.default_value is not None:
            assert p.default_value in p.values, f"{algo}.{p.name}"
    # build_with_default_param accepts every declared default
    algo_def = AlgorithmDef.build_with_default_param(algo, mode="min")
    for p in module.algo_params:
        assert algo_def.params[p.name] == p.default_value


@pytest.mark.parametrize("algo", ALGOS)
def test_memory_and_load_hooks_run_on_real_nodes(algo):
    """Every module exposes the footprint/comm-cost hooks (own or
    injected default) and they return finite non-negative numbers on
    nodes of the module's own graph model — what the distribution
    layer feeds them."""
    module = load_algorithm_module(algo)
    cg = load_graph_module(
        module.GRAPH_TYPE).build_computation_graph(_dcop())
    nodes = list(cg.nodes)
    checked_load = 0
    for node in nodes:
        mem = module.computation_memory(node)
        assert mem >= 0 and mem == mem  # finite, non-negative
        for target in node.neighbors:
            load = module.communication_load(node, target)
            assert load >= 0 and load == load
            checked_load += 1
    assert checked_load > 0


@pytest.mark.parametrize("algo", ALGOS)
def test_solve_entry_point_present(algo):
    module = load_algorithm_module(algo)
    assert hasattr(module, "solve_on_device") or hasattr(
        module, "solve"), algo


def test_unknown_algorithm_raises():
    with pytest.raises(Exception):
        load_algorithm_module("definitely_not_an_algorithm")


@pytest.mark.parametrize("algo", ["maxsum", "dsa", "mgm"])
def test_param_value_validation_rejects_bad_choice(algo):
    module = load_algorithm_module(algo)
    constrained = [p for p in module.algo_params if p.values]
    if not constrained:
        pytest.skip("no choice-constrained params")
    p = constrained[0]
    with pytest.raises(Exception):
        AlgorithmDef.build_with_default_param(
            algo, mode="min", params={p.name: "no_such_choice"})

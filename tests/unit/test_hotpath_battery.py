"""Battery for the hot-path overhaul: vectorized compile + structure
cache, buffer donation, async checkpointing, and the aggregation
autotuner (ISSUE 3).

Contracts pinned here:

- the vectorized cost-table evaluation is bit-equal to the reference
  per-assignment loop, and falls back (never fails) on expressions it
  cannot vectorize;
- the structure-keyed compile cache returns identical layouts and
  skips layout/agg-array construction (counter-asserted), and never
  confuses different structures;
- segment/superstep buffer donation changes WHERE buffers live, never
  the trajectory (bit-identical states vs the undonated run);
- async checkpointing writes the same snapshots, overlaps device
  compute (trace-asserted), flushes before returning, and surfaces
  writer errors instead of swallowing them;
- ``aggregation='auto'`` only ever selects a valid strategy and
  records its decision in result metrics.
"""

import os

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import (
    Constraint,
    NAryMatrixRelation,
    constraint_from_str,
)
from pydcop_tpu.engine.compile import (
    AGGREGATIONS,
    compile_cache,
    compile_dcop,
    compile_factor_graph,
    validated_aggregation,
)


@pytest.fixture(autouse=True)
def _fresh_compile_cache():
    compile_cache.clear()
    yield
    compile_cache.clear()


def _domain(values=(0, 1, 2)):
    return Domain("colors", "", list(values))


def _ring_dcop(n=12, penalty=1):
    d = _domain()
    vs = [Variable(f"v{i}", d) for i in range(n)]
    dcop = DCOP("ring", objective="min")
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        a, b = vs[i], vs[(i + 1) % n]
        dcop.add_constraint(constraint_from_str(
            f"c{i}", f"{penalty} if {a.name} == {b.name} else 0",
            [a, b]))
    return dcop


# ------------------------------------------------------------------ #
# Vectorized cost-table evaluation
# ------------------------------------------------------------------ #


class TestVectorizedToArray:

    @pytest.mark.parametrize("expr", [
        "10000 if v1 == v2 else 0",
        "(v1 + v2 - 1)**2",
        "math.sqrt(abs(v1 - v2)) + v2",
        "1 if 0 < v1 < 2 and v2 != 1 else -1",
        "min(v1, v2) + max(v1, 1)",
        "v1 * v2 + (3 if v1 >= v2 or not v2 else 7)",
    ])
    def test_matches_scalar_reference(self, expr):
        d = _domain()
        x, y = Variable("v1", d), Variable("v2", d)
        c = constraint_from_str("c", expr, [x, y])
        np.testing.assert_array_equal(
            c.to_array(), Constraint.to_array(c))

    def test_string_domains(self):
        d = _domain(["R", "G", "B"])
        a, b = Variable("a", d), Variable("b", d)
        c = constraint_from_str("c", "1 if a == b else 0", [a, b])
        np.testing.assert_array_equal(
            c.to_array(), Constraint.to_array(c))

    def test_random_expression_falls_back(self):
        d = _domain()
        x = Variable("v1", d)
        c = constraint_from_str("c", "v1 + 0 * random.random()", [x])
        arr = c.to_array()  # must not crash
        assert arr.shape == (3,)
        assert c.table_signature() is None

    def test_signature_shared_across_renamed_scopes(self):
        d = _domain()
        c1 = constraint_from_str(
            "c1", "7 if v12 == v37 else 0",
            [Variable("v12", d), Variable("v37", d)])
        c2 = constraint_from_str(
            "c2", "7 if a == b else 0",
            [Variable("a", d), Variable("b", d)])
        assert c1.table_signature() == c2.table_signature()
        np.testing.assert_array_equal(c1.to_array(), c2.to_array())

    def test_signature_distinguishes_constants_and_domains(self):
        d = _domain()
        c1 = constraint_from_str(
            "c1", "7 if a == b else 0",
            [Variable("a", d), Variable("b", d)])
        c2 = constraint_from_str(
            "c2", "8 if a == b else 0",
            [Variable("a", d), Variable("b", d)])
        assert c1.table_signature() != c2.table_signature()
        d2 = _domain((0, 1))
        c3 = constraint_from_str(
            "c3", "7 if a == b else 0",
            [Variable("a", d2), Variable("b", d2)])
        assert c1.table_signature() != c3.table_signature()

    def test_signature_immune_to_string_literals(self):
        """A variable name inside a string literal must NOT normalize
        like a variable reference — merging these two would silently
        swap cost tables."""
        d = _domain(["v1", "x"])
        c1 = constraint_from_str(
            "c1", "1 if v1 == 'v1' else 0", [Variable("v1", d)])
        c2 = constraint_from_str(
            "c2", "1 if x == 'x' else 0", [Variable("x", d)])
        assert c1.table_signature() != c2.table_signature()
        assert not np.array_equal(c1.to_array(), c2.to_array())


# ------------------------------------------------------------------ #
# Compile: vectorized path equals reference, cache semantics
# ------------------------------------------------------------------ #


def _mixed_problem(seed=0, penalty=9):
    rng = np.random.default_rng(seed)
    d = _domain()
    vs = [Variable(f"v{i}", d) for i in range(10)]
    cons = []
    for i in range(14):
        a, b = rng.choice(10, size=2, replace=False)
        cons.append(constraint_from_str(
            f"e{i}", f"{penalty} if v{a} == v{b} else 0",
            [vs[a], vs[b]]))
    cons.append(NAryMatrixRelation(
        [vs[0], vs[1]], rng.random((3, 3)), "m0"))
    cons.append(constraint_from_str("u0", "v3 * 2 + 1", [vs[3]]))
    cons.append(constraint_from_str(
        "t0", "v1 + v2 + v4", [vs[1], vs[2], vs[4]]))
    return vs, cons


class TestCompile:

    @pytest.mark.parametrize("mode", ["min", "max"])
    def test_vectorized_compile_equals_reference(self, mode):
        vs, cons = _mixed_problem()
        g_ref, m_ref = compile_factor_graph(
            vs, cons, mode=mode, noise_level=0.01,
            vectorize=False, use_cache=False)
        g_vec, m_vec = compile_factor_graph(
            vs, cons, mode=mode, noise_level=0.01,
            vectorize=True, use_cache=False)
        np.testing.assert_array_equal(g_ref.var_costs, g_vec.var_costs)
        assert m_ref.factor_names == m_vec.factor_names
        for b_ref, b_vec in zip(g_ref.buckets, g_vec.buckets):
            np.testing.assert_array_equal(b_ref.costs, b_vec.costs)
            np.testing.assert_array_equal(b_ref.var_ids, b_vec.var_ids)

    def test_cache_hit_skips_layout_build(self):
        vs, cons = _mixed_problem(penalty=9)
        g1, _ = compile_factor_graph(vs, cons, aggregation="ell")
        assert compile_cache.stats()["layout_builds"] == 1
        assert compile_cache.stats()["misses"] == 1
        # Same structure, different cost tables.
        vs2, cons2 = _mixed_problem(penalty=4)
        g2, _ = compile_factor_graph(vs2, cons2, aggregation="ell")
        stats = compile_cache.stats()
        assert stats["hits"] == 1
        assert stats["layout_builds"] == 1  # NOT rebuilt
        # Layout arrays are the exact cached objects, agg included.
        for b1, b2 in zip(g1.buckets, g2.buckets):
            assert b1.var_ids is b2.var_ids
        assert g1.agg_ell is g2.agg_ell
        # Costs differ (the problem really changed; bucket 1 holds
        # the binary penalty factors).
        assert not np.array_equal(
            g1.buckets[1].costs, g2.buckets[1].costs)

    def test_cached_layout_is_frozen(self):
        vs, cons = _mixed_problem()
        g, _ = compile_factor_graph(vs, cons, aggregation="sorted")
        assert not g.buckets[0].var_ids.flags.writeable
        assert not g.agg_perm.flags.writeable

    def test_cache_distinguishes_structures(self):
        vs, cons = _mixed_problem(seed=0)
        compile_factor_graph(vs, cons)
        # Different edges -> different structure.
        vs2, cons2 = _mixed_problem(seed=1)
        compile_factor_graph(vs2, cons2)
        assert compile_cache.stats()["hits"] == 0
        # Same structure but different aggregation/pad_to -> miss.
        compile_factor_graph(vs, cons, aggregation="sorted")
        compile_factor_graph(vs, cons, pad_to=4)
        assert compile_cache.stats()["hits"] == 0
        # And the true re-compile does hit.
        compile_factor_graph(vs, cons)
        assert compile_cache.stats()["hits"] == 1

    def test_cache_opt_out(self):
        vs, cons = _mixed_problem()
        compile_factor_graph(vs, cons, use_cache=False)
        compile_factor_graph(vs, cons, use_cache=False)
        assert compile_cache.stats()["hits"] == 0
        assert compile_cache.stats()["entries"] == 0

    def test_compiled_solve_unchanged_by_cache(self):
        """A cache-hit compile must solve identically to a cold one."""
        from pydcop_tpu.api import solve

        dcop1 = _ring_dcop(10)
        ref = solve(dcop1, "maxsum", backend="device", max_cycles=60)
        dcop2 = _ring_dcop(10)  # same structure -> layout cache hit
        res = solve(dcop2, "maxsum", backend="device", max_cycles=60)
        assert compile_cache.stats()["hits"] >= 1
        assert res["assignment"] == ref["assignment"]
        assert res["cycles"] == ref["cycles"]


# ------------------------------------------------------------------ #
# Buffer donation
# ------------------------------------------------------------------ #


class TestDonation:

    def _engine(self, donate: bool):
        from pydcop_tpu.algorithms.maxsum import build_engine

        eng = build_engine(_ring_dcop(), {"noise": 0.01})
        eng.donate = donate
        return eng

    def test_trajectory_bit_identical_per_segment(self):
        """Donation relocates buffers; every state leaf must stay
        bit-identical to the undonated run at every segment
        boundary."""
        import jax

        e_d, e_u = self._engine(True), self._engine(False)
        s_d, s_u = e_d.init_state(), e_u.init_state()
        for _ in range(5):
            fn_d = e_d._segment_fn(7, True)
            fn_u = e_u._segment_fn(7, True)
            (s_d, v_d), _, _ = e_d._call(("seg", 7), fn_d,
                                         e_d.graph, s_d)
            (s_u, v_u), _, _ = e_u._call(("seg", 7), fn_u,
                                         e_u.graph, s_u)
            # Host copies BEFORE the next dispatch donates s_d.
            host_d = jax.device_get(s_d)
            host_u = jax.device_get(s_u)
            for leaf_d, leaf_u in zip(
                    jax.tree_util.tree_leaves(host_d),
                    jax.tree_util.tree_leaves(host_u)):
                np.testing.assert_array_equal(
                    np.asarray(leaf_d), np.asarray(leaf_u))
            np.testing.assert_array_equal(
                np.asarray(v_d), np.asarray(v_u))

    def test_donation_is_active(self):
        """The donated input state is actually consumed (buffer
        deleted) — the guarantee the zero-allocation claim rests on."""
        e = self._engine(True)
        state = e.init_state()
        fn = e._segment_fn(5, True)
        (new_state, _), _, _ = e._call(("seg", 5), fn, e.graph, state)
        with pytest.raises(Exception):
            np.asarray(state.v2f[0])  # deleted by donation
        np.asarray(new_state.v2f[0])  # output is live

    def test_run_checkpointed_matches_plain_run(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        ref = build_engine(_ring_dcop(), {"noise": 0.01}).run(
            max_cycles=100)
        seg = self._engine(True).run_checkpointed(
            max_cycles=100, segment_cycles=7)
        assert seg.assignment == ref.assignment
        assert seg.cycles == ref.cycles
        assert seg.converged == ref.converged

    def test_dynamic_engine_donation_roundtrip(self):
        from pydcop_tpu.engine.dynamic import DynamicMaxSumEngine

        d = _domain()
        vs = [Variable(f"v{i}", d) for i in range(6)]
        cons = [constraint_from_str(
            f"c{i}", f"1 if v{i} == v{(i + 1) % 6} else 0",
            [vs[i], vs[(i + 1) % 6]]) for i in range(6)]
        donated = DynamicMaxSumEngine(vs, cons, noise_seed=7,
                                      donate=True)
        plain = DynamicMaxSumEngine(vs, cons, noise_seed=7,
                                    donate=False)
        for _ in range(3):  # repeated warm-started runs
            r_d = donated.run(max_cycles=20)
            r_p = plain.run(max_cycles=20)
            assert r_d.assignment == r_p.assignment
            assert r_d.cycles == r_p.cycles
        # Edits (host array surgery) still compose with donation.
        donated.change_factor("c0", constraint_from_str(
            "c0", "5 if v0 == v1 else 0", [vs[0], vs[1]]))
        plain.change_factor("c0", constraint_from_str(
            "c0", "5 if v0 == v1 else 0", [vs[0], vs[1]]))
        r_d = donated.run(max_cycles=20)
        r_p = plain.run(max_cycles=20)
        assert r_d.assignment == r_p.assignment
        assert r_d.cycles == r_p.cycles


# ------------------------------------------------------------------ #
# Async checkpointing
# ------------------------------------------------------------------ #


class TestAsyncCheckpoint:

    def _engine(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        return build_engine(_ring_dcop(), {"noise": 0.01})

    def test_same_snapshots_as_sync(self, tmp_path):
        from pydcop_tpu.resilience.checkpoint import (
            CheckpointManager,
            read_meta,
        )

        m_async = CheckpointManager(str(tmp_path / "a"), every=5,
                                    keep=10)
        m_sync = CheckpointManager(str(tmp_path / "s"), every=5,
                                   keep=10)
        r_a = self._engine().run_checkpointed(
            max_cycles=40, manager=m_async, checkpoint_async=True,
            stop_on_convergence=False)
        r_s = self._engine().run_checkpointed(
            max_cycles=40, manager=m_sync, checkpoint_async=False,
            stop_on_convergence=False)
        assert r_a.assignment == r_s.assignment
        assert r_a.metrics["checkpoint_async"]
        assert not r_s.metrics["checkpoint_async"]
        cycles_a = [c for c, _ in m_async.checkpoints()]
        assert cycles_a == [c for c, _ in m_sync.checkpoints()]
        # Byte-level: identical snapshot payloads either way.
        for (ca, pa), (cs, ps) in zip(m_async.checkpoints(),
                                      m_sync.checkpoints()):
            assert read_meta(pa)["cycle"] == read_meta(ps)["cycle"]
            da = np.load(pa)
            ds = np.load(ps)
            for k in da.files:
                if k != "__meta__":
                    np.testing.assert_array_equal(da[k], ds[k])

    def test_writes_overlap_device_compute(self, tmp_path):
        """THE overlap criterion: checkpoint_write spans (writer
        thread) run concurrently with engine_segment spans (main
        thread)."""
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.observability.trace import tracer
        from pydcop_tpu.resilience.checkpoint import CheckpointManager

        eng = build_engine(_ring_dcop(800), {"noise": 0.01})
        manager = CheckpointManager(str(tmp_path), every=20, keep=3)
        tracer.enable()
        try:
            eng.run_checkpointed(
                max_cycles=160, manager=manager,
                stop_on_convergence=False)
        finally:
            tracer.disable()
        events = tracer.events()
        segs = [(e["ts"], e["ts"] + e["dur"], e["tid"])
                for e in events if e["name"] == "engine_segment"]
        writes = [(e["ts"], e["ts"] + e["dur"], e["tid"])
                  for e in events if e["name"] == "checkpoint_write"]
        assert len(segs) >= 5 and len(writes) >= 5
        assert {t for _, _, t in writes}.isdisjoint(
            {t for _, _, t in segs})  # different lanes
        overlaps = sum(
            1 for ws, we, _ in writes for ss, se, _ in segs
            if ws < se and ss < we)
        assert overlaps >= 1, (
            "no checkpoint_write span overlapped any engine_segment "
            "span — async writes are serializing with compute")

    def test_flush_guarantee_on_interrupt(self, tmp_path):
        from pydcop_tpu.resilience.checkpoint import CheckpointManager

        manager = CheckpointManager(str(tmp_path), every=5, keep=2)
        res = self._engine().run_checkpointed(
            max_cycles=100, manager=manager, max_segments=1)
        assert res.metrics["interrupted"]
        # The (async) snapshot is on disk the moment the call returns.
        assert manager.latest() is not None
        assert manager.latest().endswith("ckpt_5.npz")

    def test_writer_error_surfaces(self, tmp_path):
        from pydcop_tpu.resilience.checkpoint import (
            AsyncCheckpointWriter,
            CheckpointManager,
        )

        manager = CheckpointManager(str(tmp_path), every=5)
        # Redirect writes into a path that is a FILE, so mkstemp
        # inside the atomic write fails on the writer thread.
        blocker = tmp_path / "blocker"
        blocker.write_text("x")
        manager.directory = str(blocker / "sub")
        writer = AsyncCheckpointWriter(manager)
        state = self._engine().init_state()
        writer.submit(state, 5)
        with pytest.raises(RuntimeError, match="async checkpoint"):
            writer.flush()
        writer.close()

    def test_writer_close_idempotent_and_rejects_after(self, tmp_path):
        from pydcop_tpu.resilience.checkpoint import (
            AsyncCheckpointWriter,
            CheckpointManager,
        )

        manager = CheckpointManager(str(tmp_path), every=5)
        writer = AsyncCheckpointWriter(manager)
        state = self._engine().init_state()
        writer.submit(state, 5)
        writer.close()
        writer.close()  # no-op
        assert manager.latest().endswith("ckpt_5.npz")
        with pytest.raises(RuntimeError, match="closed"):
            writer.submit(state, 10)


# ------------------------------------------------------------------ #
# Aggregation autotuner
# ------------------------------------------------------------------ #


class TestAutotuner:

    def test_choice_valid_and_recorded(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PYDCOP_AGG_AUTOTUNE_CACHE",
                           str(tmp_path / "tune.json"))
        from pydcop_tpu.api import solve

        res = solve(_ring_dcop(), "maxsum", backend="device",
                    max_cycles=50, algo_params={"aggregation": "auto"})
        assert res["metrics"]["aggregation"] in AGGREGATIONS
        assert res["metrics"]["aggregation"] != "boundary"
        assert res["metrics"]["aggregation_source"] == "measured"
        timings = res["metrics"]["aggregation_timings_ms"]
        assert set(timings) == set(AGGREGATIONS)
        measured = {s for s, t in timings.items() if t is not None}
        assert {"scatter", "sorted", "ell"} <= measured

    def test_cache_roundtrip(self, tmp_path):
        from pydcop_tpu.engine.autotune import autotune_aggregation

        graph, _ = compile_dcop(_ring_dcop())
        cache = str(tmp_path / "tune.json")
        first = autotune_aggregation(graph, cache_file=cache)
        assert first["aggregation_source"] == "measured"
        second = autotune_aggregation(graph, cache_file=cache)
        assert second["aggregation_source"] == "cache"
        assert second["aggregation"] == first["aggregation"]

    def test_mesh_resolves_to_scatter_without_measuring(self):
        from pydcop_tpu.engine.autotune import autotune_aggregation

        assert validated_aggregation(
            {"aggregation": "auto"}, pad_to=4) == "scatter"
        graph, _ = compile_dcop(_ring_dcop(), pad_to=4)
        info = autotune_aggregation(graph, pad_to=4)
        assert info["aggregation"] == "scatter"
        assert info["aggregation_source"] == "mesh"
        assert all(t is None
                   for t in info["aggregation_timings_ms"].values())

    def test_hub_guard_excludes_ell(self, tmp_path, monkeypatch):
        """A hub-guard refusal (ell would OOM) must drop ell from the
        candidates, never crash or select it."""
        import pydcop_tpu.engine.autotune as autotune_mod

        real = autotune_mod.build_aggregation_arrays

        def guarded(buckets, n_segments, aggregation):
            if aggregation == "ell":
                raise ValueError(
                    "aggregation='ell' would allocate a huge array")
            return real(buckets, n_segments, aggregation)

        monkeypatch.setattr(
            autotune_mod, "build_aggregation_arrays", guarded)
        graph, _ = compile_dcop(_ring_dcop())
        info = autotune_mod.autotune_aggregation(
            graph, cache_file=str(tmp_path / "t.json"),
            use_cache=False)
        assert info["aggregation"] in ("scatter", "sorted")
        assert info["aggregation_timings_ms"]["ell"] is None
        assert "ell" in info["aggregation_notes"]

    def test_edge_free_graph(self):
        from pydcop_tpu.engine.autotune import autotune_aggregation

        d = _domain()
        dcop = DCOP("empty", objective="min")
        dcop.add_variable(Variable("x", d))
        graph, _ = compile_dcop(dcop)
        info = autotune_aggregation(graph, use_cache=False)
        assert info["aggregation"] == "scatter"
        assert info["aggregation_source"] == "empty"

    def test_corrupt_cache_ignored(self, tmp_path):
        from pydcop_tpu.engine.autotune import autotune_aggregation

        cache = tmp_path / "tune.json"
        cache.write_text("{not json")
        graph, _ = compile_dcop(_ring_dcop())
        info = autotune_aggregation(graph, cache_file=str(cache))
        assert info["aggregation_source"] == "measured"


# ------------------------------------------------------------------ #
# Satellites: edge-free aggregation crash, bench flags, sync debug
# ------------------------------------------------------------------ #


class TestEdgeFreeAggregation:

    @pytest.mark.parametrize("aggregation", list(AGGREGATIONS))
    def test_aggregate_beliefs_no_buckets(self, aggregation):
        import jax.numpy as jnp

        from pydcop_tpu.ops.maxsum import aggregate_beliefs

        d = _domain()
        dcop = DCOP("empty", objective="min")
        for name in ("x", "y"):
            dcop.add_variable(Variable(name, d))
        graph, _ = compile_dcop(dcop, aggregation=aggregation)
        beliefs, sums = aggregate_beliefs(graph, ())
        np.testing.assert_array_equal(
            np.asarray(beliefs), np.asarray(graph.var_costs))
        assert not np.asarray(jnp.any(sums != 0))

    @pytest.mark.parametrize(
        "aggregation", ["scatter", "sorted", "ell", "auto"])
    def test_solve_edge_free(self, aggregation, tmp_path, monkeypatch):
        monkeypatch.setenv("PYDCOP_AGG_AUTOTUNE_CACHE",
                           str(tmp_path / "t.json"))
        from pydcop_tpu.api import solve

        d = _domain()
        dcop = DCOP("empty", objective="min")
        for name in ("x", "y"):
            dcop.add_variable(Variable(name, d))
        res = solve(dcop, "maxsum", backend="device", max_cycles=10,
                    algo_params={"aggregation": aggregation})
        assert res["status"] == "FINISHED"
        assert res["cost"] == 0.0


class TestBenchScaleFlags:

    def _run(self, **flags):
        import bench

        return bench.bench_scale(n_vars=64, edge_factor=1.0,
                                 cycles=3, **flags)

    def test_flags_compose(self):
        out = self._run(return_values=True, detail=True)
        assert len(out) == 4
        cps, graph, values, info = out
        assert values.shape == (64,)
        assert set(info) == {"sec_per_cycle", "fixed_overhead_s"}

    def test_single_flag_shapes_preserved(self):
        cps, graph, values = self._run(return_values=True)
        assert values.shape == (64,)
        cps, graph, info = self._run(detail=True)
        assert "sec_per_cycle" in info
        assert len(self._run()) == 2


class TestSyncDebug:

    def test_debug_path_fetches_every_leaf(self, monkeypatch):
        import types

        import jax

        from pydcop_tpu.engine import timing

        fetched = []

        def counting_get(x):
            fetched.append(x)
            return jax.device_get(x)

        proxy = types.SimpleNamespace(
            tree_util=jax.tree_util, device_get=counting_get)
        monkeypatch.setattr(timing, "jax", proxy)
        import jax.numpy as jnp

        tree = (jnp.zeros(4), jnp.zeros(8), jnp.zeros((2, 2)))
        monkeypatch.delenv("PYDCOP_SYNC_DEBUG", raising=False)
        timing.sync(tree)
        assert len(fetched) == 1  # smallest-leaf contract
        fetched.clear()
        monkeypatch.setenv("PYDCOP_SYNC_DEBUG", "1")
        out = timing.sync(tree)
        assert out is tree
        assert len(fetched) == 3  # one barrier per leaf

    def test_empty_tree_noop(self):
        from pydcop_tpu.engine.timing import sync

        assert sync({"a": 1}) == {"a": 1}

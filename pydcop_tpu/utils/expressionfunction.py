"""Compile python expression strings into callables — powers YAML
``intention:`` constraints.

Reference parity: pydcop/utils/expressionfunction.py:40 (``ExpressionFunction``:
AST variable-name scan :218, partial application, external source files).

Two forms are accepted (matching the DCOP YAML format spec,
docs/usage/file_formats/dcop_format.yml in the reference):

- a single python expression: ``"1 if v1 == v2 else 0"``;
- a function body containing ``return`` statements (multi-line YAML string),
  which is wrapped into a generated ``def``.

The names the function depends on are discovered by scanning the AST for
loaded-but-never-assigned names, excluding builtins and the modules made
available in the evaluation scope (``math``, ``random``, and — for external
source files — ``source``).
"""

import ast
import builtins
import importlib.util
import math
import random
import textwrap
from typing import Iterable, Optional

import numpy as np

_SCOPE_MODULES = {"math": math, "random": random}


class _NotVectorizable(Exception):
    """Raised by the AST transform when an expression cannot be
    rewritten into numpy elementwise form."""


class _VectorizeTransform(ast.NodeTransformer):
    """Rewrite a scalar python expression into a numpy-elementwise one.

    The scalar and vectorized forms must agree at every grid point
    (spot-checked by the caller); constructs whose array semantics
    differ from their scalar semantics are rewritten, and constructs
    with no elementwise equivalent abort the transform:

    - ``a if c else b``      -> ``np.where(c, a, b)``
    - ``a and b`` / ``or``   -> ``np.logical_and/or(a, b)``
    - ``not a``              -> ``np.logical_not(a)``
    - ``a < b < c``          -> ``np.logical_and(a < b, b < c)``
    - ``math.<fn>``          -> ``np.<fn>`` (math functions reject
      arrays; numpy carries elementwise versions of the common ones —
      a missing attribute surfaces at eval time and falls back)
    - ``min(a, b)``/``max``  -> ``np.minimum/np.maximum`` (two-arg
      only: the scalar builtins reduce, which is not elementwise)
    - ``random.*`` / ``source.*`` / ``in`` -> not vectorizable
      (per-call randomness and external python have per-assignment
      semantics a single array eval cannot reproduce).
    """

    _NP = "__np__"

    def visit_IfExp(self, node: ast.IfExp) -> ast.AST:
        node = self.generic_visit(node)
        return ast.Call(
            func=ast.Attribute(
                value=ast.Name(id=self._NP, ctx=ast.Load()),
                attr="where", ctx=ast.Load()),
            args=[node.test, node.body, node.orelse],
            keywords=[],
        )

    def visit_BoolOp(self, node: ast.BoolOp) -> ast.AST:
        node = self.generic_visit(node)
        fn = "logical_and" if isinstance(node.op, ast.And) \
            else "logical_or"
        out = node.values[0]
        for v in node.values[1:]:
            out = ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=self._NP, ctx=ast.Load()),
                    attr=fn, ctx=ast.Load()),
                args=[out, v], keywords=[],
            )
        return out

    def visit_UnaryOp(self, node: ast.UnaryOp) -> ast.AST:
        node = self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=self._NP, ctx=ast.Load()),
                    attr="logical_not", ctx=ast.Load()),
                args=[node.operand], keywords=[],
            )
        return node

    def visit_Compare(self, node: ast.Compare) -> ast.AST:
        node = self.generic_visit(node)
        for op in node.ops:
            if isinstance(op, (ast.In, ast.NotIn)):
                raise _NotVectorizable("membership test")
        if len(node.ops) == 1:
            return node
        # Chained comparison: python evaluates it as an AND of pairs,
        # which is ambiguous on arrays — expand explicitly.
        operands = [node.left] + list(node.comparators)
        out = None
        for left, op, right in zip(operands, node.ops, operands[1:]):
            pair = ast.Compare(left=left, ops=[op], comparators=[right])
            out = pair if out is None else ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=self._NP, ctx=ast.Load()),
                    attr="logical_and", ctx=ast.Load()),
                args=[out, pair], keywords=[],
            )
        return out

    def visit_Name(self, node: ast.Name) -> ast.AST:
        if node.id in ("random", "source"):
            raise _NotVectorizable(node.id)
        if node.id == "math":
            return ast.Name(id=self._NP, ctx=node.ctx)
        return node

    def visit_Call(self, node: ast.Call) -> ast.AST:
        node = self.generic_visit(node)
        if isinstance(node.func, ast.Name) \
                and node.func.id in ("min", "max"):
            if len(node.args) != 2 or node.keywords:
                raise _NotVectorizable("min/max with != 2 args")
            node.func = ast.Attribute(
                value=ast.Name(id=self._NP, ctx=ast.Load()),
                attr=("minimum" if node.func.id == "min"
                      else "maximum"),
                ctx=ast.Load())
        return node


def _free_names(tree: ast.AST) -> list:
    loads, stores = [], set()
    nodes = sorted(
        (n for n in ast.walk(tree) if isinstance(n, ast.Name)),
        key=lambda n: (n.lineno, n.col_offset),
    )
    for node in nodes:
        if isinstance(node.ctx, ast.Load):
            if node.id not in loads:
                loads.append(node.id)
        else:
            stores.add(node.id)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            if not isinstance(node, ast.Lambda):
                stores.add(node.name)
            for a in (node.args.args + node.args.kwonlyargs
                      + node.args.posonlyargs):
                stores.add(a.arg)
            if node.args.vararg:
                stores.add(node.args.vararg.arg)
            if node.args.kwarg:
                stores.add(node.args.kwarg.arg)
    reserved = set(dir(builtins)) | set(_SCOPE_MODULES) | {"source"}
    return [n for n in loads if n not in stores and n not in reserved]


def _load_source_module(path: str):
    spec = importlib.util.spec_from_file_location("_dcop_ext_source", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


class ExpressionFunction:
    """A callable built from a python expression (or function-body) string.

    >>> f = ExpressionFunction("a + b * 2")
    >>> sorted(f.variable_names)
    ['a', 'b']
    >>> f(a=1, b=2)
    5
    >>> g = f.partial(b=3)
    >>> list(g.variable_names)
    ['a']
    >>> g(a=1)
    7
    """

    def __init__(
        self,
        expression: str,
        source_file: Optional[str] = None,
        **fixed_vars,
    ):
        self._expression = expression
        self._source_file = source_file
        self._fixed_vars = dict(fixed_vars)

        self._scope = dict(_SCOPE_MODULES)
        if source_file:
            self._scope["source"] = _load_source_module(source_file)

        stripped = textwrap.dedent(expression).strip()
        try:
            tree = ast.parse(stripped, mode="eval")
            self._is_body = False
        except SyntaxError:
            tree = ast.parse(
                "def __expr__():\n" + textwrap.indent(textwrap.dedent(expression), "    ")
            )
            self._is_body = True

        names = _free_names(tree)
        self._all_names = [n for n in names]
        self._variable_names = [n for n in names if n not in self._fixed_vars]

        if self._is_body:
            src = "def __expr__({}):\n{}".format(
                ", ".join(self._all_names),
                textwrap.indent(textwrap.dedent(expression), "    "),
            )
            g = dict(self._scope)
            g["__builtins__"] = builtins
            exec(compile(src, "<dcop_expression>", "exec"), g)
            self._func = g["__expr__"]
            self._code = None
        else:
            self._func = None
            self._code = compile(stripped, "<dcop_expression>", "eval")
        # Vectorized variant compiled lazily on first use; False once
        # the transform (or a later eval) proved unsupported.
        self._vec_code = None

    @property
    def supports_vectorized(self) -> bool:
        """Whether a numpy-elementwise variant of the expression could
        be compiled (function bodies, ``random``/``source`` uses and
        membership tests cannot).  Compiling succeeding does NOT
        guarantee semantic equivalence on every input — callers
        spot-check :meth:`vectorized` results against scalar calls
        (see relations.NAryFunctionRelation.to_array)."""
        return self._compile_vectorized() is not None

    def _compile_vectorized(self):
        if self._vec_code is None:
            if self._is_body or self._source_file:
                self._vec_code = False
            else:
                try:
                    tree = ast.parse(
                        textwrap.dedent(self._expression).strip(),
                        mode="eval")
                    tree = _VectorizeTransform().visit(tree)
                    ast.fix_missing_locations(tree)
                    self._vec_code = compile(
                        tree, "<dcop_expression_vec>", "eval")
                except (_NotVectorizable, SyntaxError, ValueError):
                    self._vec_code = False
        return self._vec_code or None

    def mark_not_vectorizable(self) -> None:
        """Record that a vectorized eval produced wrong/failed results
        so later calls skip straight to the scalar path."""
        self._vec_code = False

    def vectorized(self, **arrays):
        """Evaluate the expression elementwise over numpy arrays.

        ``arrays`` maps variable names to broadcastable numpy arrays;
        fixed vars stay scalar.  Raises :class:`_NotVectorizable` when
        no elementwise variant exists; other exceptions propagate (the
        caller treats any failure as "use the scalar path").
        """
        code = self._compile_vectorized()
        if code is None:
            raise _NotVectorizable(self._expression)
        g = {"__builtins__": builtins,
             _VectorizeTransform._NP: np}
        scope = dict(self._fixed_vars)
        scope.update(arrays)
        return eval(code, g, scope)

    @property
    def expression(self) -> str:
        return self._expression

    @property
    def source_file(self) -> Optional[str]:
        return self._source_file

    @property
    def variable_names(self) -> Iterable[str]:
        """Names the function still depends on (fixed vars excluded)."""
        return list(self._variable_names)

    @property
    def fixed_vars(self) -> dict:
        return dict(self._fixed_vars)

    @property
    def __name__(self):
        return self._expression

    def __call__(self, *args, **kwargs):
        if args:
            kwargs.update(zip(self._variable_names, args))
        scope = dict(self._fixed_vars)
        scope.update(kwargs)
        if self._is_body:
            return self._func(**{n: scope[n] for n in self._all_names})
        g = dict(self._scope)
        g["__builtins__"] = builtins
        return eval(self._code, g, scope)

    def partial(self, **kwargs):
        fixed = dict(self._fixed_vars)
        fixed.update(kwargs)
        return ExpressionFunction(
            self._expression, source_file=self._source_file, **fixed
        )

    def __eq__(self, other):
        return (
            isinstance(other, ExpressionFunction)
            and self._expression == other._expression
            and self._fixed_vars == other._fixed_vars
        )

    def __hash__(self):
        return hash((self._expression, tuple(sorted(self._fixed_vars.items()))))

    def __repr__(self):
        return f"ExpressionFunction({self._expression!r})"

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "expression": self._expression,
            "source_file": self._source_file,
            "fixed_vars": dict(self._fixed_vars),
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(
            r["expression"],
            source_file=r.get("source_file"),
            **r.get("fixed_vars", {}),
        )

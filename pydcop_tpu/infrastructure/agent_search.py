"""Agent-mode computations for the search algorithms: DPOP and SyncBB.

Reference parity:
- dpop: pydcop/algorithms/dpop.py:115-441 — event-driven two-phase
  sweep over the DFS pseudo-tree; UTIL messages (dense cost tables)
  flow leaves→root, VALUE assignments flow root→leaves; first-optimum
  tie-breaking (relations.py:1554).
- syncbb: pydcop/algorithms/syncbb.py:176-512 — complete branch &
  bound over the lexical variable order; ONE token (forward/backward
  message) in flight at any time; termination broadcast carries the
  best assignment.

The relation algebra (join/projection/slice) is shared with the device
sweeps (pydcop_tpu/ops/dpop.py, algorithms/syncbb.py), so agent-mode
and device-mode costs agree exactly on the same problem.
"""

from typing import Any, Dict, List, Optional, Tuple

from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    find_arg_optimal,
    join,
    projection,
)
from pydcop_tpu.infrastructure.computations import (
    Message,
    VariableComputation,
    message_type,
    register,
)

# -- DPOP -------------------------------------------------------------- #


class DpopUtilMessage(Message):
    """UTIL table sent child→parent (reference DpopMessage, dpop.py:88:
    size = product of the table's dims)."""

    def __init__(self, util: NAryMatrixRelation):
        super().__init__("dpop_util", None)
        self._util = util

    @property
    def util(self) -> NAryMatrixRelation:
        return self._util

    @property
    def size(self) -> int:
        return int(self._util.matrix.size)

    def _simple_repr(self):
        from pydcop_tpu.utils.simple_repr import simple_repr

        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "util": simple_repr(self._util),
        }

    @classmethod
    def _from_repr(cls, r):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(from_repr(r["util"]))

    def __repr__(self):
        return f"DpopUtilMessage({self._util.scope_names})"


DpopValueMessage = message_type("dpop_value", ["assignment"])


class DpopComputation(VariableComputation):
    """One computation per pseudo-tree node.

    UTIL phase: seed with own unary costs, join assigned constraints,
    join children's UTIL tables as they arrive; when all children have
    reported, project self out and send UTIL to the parent (or, at the
    root, start the VALUE phase).  VALUE phase: slice the joined table
    on the ancestors' assignment, pick the first-optimal own value,
    extend the assignment and forward to children.
    """

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        node = comp_def.node
        self._parent: Optional[str] = node.parent
        self._children: List[str] = list(node.children)
        self._constraints = list(node.constraints)
        self._pending_children = set(self._children)
        self._joined: Optional[NAryMatrixRelation] = None

    @property
    def neighbors(self) -> List[str]:
        return ([self._parent] if self._parent else []) + self._children

    def footprint(self) -> float:
        from pydcop_tpu.computations_graph.pseudotree import (
            computation_memory,
        )

        return computation_memory(self.computation_def.node)

    def on_start(self):
        self._joined = NAryMatrixRelation(
            [self._variable], self._variable.cost_vector(),
            name=f"util_{self.name}",
        )
        for c in self._constraints:
            self._joined = join(
                self._joined, NAryMatrixRelation.from_func_relation(c)
            )
        if not self._pending_children:
            self._utils_complete()

    @register("dpop_util")
    def _on_util(self, sender, msg, t):
        if sender not in self._pending_children:
            return  # duplicate delivery
        self._pending_children.discard(sender)
        self._joined = join(self._joined, msg.util)
        if not self._pending_children:
            self._utils_complete()

    def _utils_complete(self):
        if self._parent is None:
            # Root: its joined table only spans itself.
            values, cost = find_arg_optimal(
                self._variable, self._joined, self.mode
            )
            self.value_selection(values[0], cost)
            self._forward_value({self.name: values[0]})
            self.finished()
        else:
            util = projection(self._joined, self._variable, self.mode)
            self.post_msg(self._parent, DpopUtilMessage(util))

    @register("dpop_value")
    def _on_value(self, sender, msg, t):
        ancestors: Dict[str, Any] = dict(msg.assignment)
        known = {
            v: ancestors[v] for v in self._joined.scope_names
            if v != self.name and v in ancestors
        }
        rel = self._joined.slice(known) if known else self._joined
        values, cost = find_arg_optimal(self._variable, rel, self.mode)
        self.value_selection(values[0], cost)
        ancestors[self.name] = values[0]
        self._forward_value(ancestors)
        self.finished()

    def _forward_value(self, assignment: Dict[str, Any]):
        for child in self._children:
            self.post_msg(child, DpopValueMessage(dict(assignment)))


# -- SyncBB ------------------------------------------------------------ #

SyncBBForwardMessage = message_type(
    "syncbb_forward", ["path", "pcost", "bound", "best", "best_cost"])
SyncBBBackwardMessage = message_type(
    "syncbb_backward", ["bound", "best", "best_cost"])
SyncBBTerminateMessage = message_type(
    "syncbb_terminate", ["assignment", "cost"])


class SyncBBComputation(VariableComputation):
    """Branch & bound over the lexical order, one token in flight.

    The token carries the partial path (list of (var, value) pairs),
    its accumulated cost, the current bound and incumbent.  Each node
    charges its unary cost plus the constraints whose scope completes
    at it (last variable in lexical order), exactly like the device
    search (algorithms/syncbb.py), so partial costs — and therefore
    pruning and the final cost — agree between modes.  Costs are
    sign-normalized so max-mode problems minimize the negated tables.
    """

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        node = comp_def.node
        self._next = node.next_node
        self._previous = node.previous_node
        # Constraints charged here: those whose scope's last variable
        # (lexical order = the ordered-graph order) is this one.
        self._charged = [
            c for c in node.constraints
            if max(c.scope_names) == self.name
        ]
        self._prefix: List[Tuple[str, Any]] = []
        self._prefix_cost = 0.0
        self._tried = -1  # index of the last tried domain value

    @property
    def neighbors(self) -> List[str]:
        return [n for n in (self._previous, self._next) if n]

    def _sign(self) -> float:
        return 1.0 if self.mode == "min" else -1.0

    def _charge(self, value) -> float:
        """Own contribution given the stored prefix: unary + charged
        constraints (sign-normalized)."""
        sign = self._sign()
        asst = dict(self._prefix)
        asst[self.name] = value
        cost = sign * self._variable.cost_for_val(value)
        for c in self._charged:
            cost += sign * c(**{n: asst[n] for n in c.scope_names})
        return cost

    def on_start(self):
        if self._previous is None:
            if self._next is None:
                # Single-variable problem: trivial optimum.
                costs = NAryMatrixRelation(
                    [self._variable], self._variable.cost_vector(),
                )
                values, cost = find_arg_optimal(
                    self._variable, costs, self.mode
                )
                self.value_selection(values[0], cost)
                self.finished()
                return
            self._advance(float("inf"), None, float("inf"))

    def _advance(self, bound: float, best, best_cost: float):
        """Try own values after self._tried; forward, record or
        backtrack (reference get_next_assignment, syncbb.py)."""
        domain = list(self._variable.domain)
        if self._next is None:
            # Last variable: scan remaining values, keep the best
            # completion under the bound, then backtrack.
            for i in range(self._tried + 1, len(domain)):
                value = domain[i]
                total = self._prefix_cost + self._charge(value)
                if total < bound:
                    bound = total
                    best = dict(self._prefix)
                    best[self.name] = value
                    best_cost = total
            self._tried = len(domain)
            self.post_msg(
                self._previous,
                SyncBBBackwardMessage(bound, best, best_cost),
            )
            return
        for i in range(self._tried + 1, len(domain)):
            value = domain[i]
            cost = self._prefix_cost + self._charge(value)
            if cost < bound:
                self._tried = i
                path = list(self._prefix) + [(self.name, value)]
                self.post_msg(
                    self._next,
                    SyncBBForwardMessage(
                        path, cost, bound, best, best_cost
                    ),
                )
                return
        # Exhausted under the current bound.
        self._tried = len(domain)
        if self._previous is None:
            self._terminate(best, best_cost)
        else:
            self.post_msg(
                self._previous,
                SyncBBBackwardMessage(bound, best, best_cost),
            )

    @register("syncbb_forward")
    def _on_forward(self, sender, msg, t):
        self._prefix = [tuple(p) for p in msg.path]
        self._prefix_cost = msg.pcost
        self._tried = -1
        self._advance(msg.bound, msg.best, msg.best_cost)

    @register("syncbb_backward")
    def _on_backward(self, sender, msg, t):
        self._advance(msg.bound, msg.best, msg.best_cost)

    @register("syncbb_terminate")
    def _on_terminate(self, sender, msg, t):
        self._finish_with(dict(msg.assignment), msg.cost)
        if self._next is not None:
            self.post_msg(
                self._next,
                SyncBBTerminateMessage(msg.assignment, msg.cost),
            )

    def _terminate(self, best, best_cost: float):
        if best is None:
            # No assignment under the bound (all-infinite problem):
            # keep the current/initial value.
            best, best_cost = {}, float("inf")
        self._finish_with(dict(best), best_cost)
        if self._next is not None:
            self.post_msg(
                self._next, SyncBBTerminateMessage(best, best_cost)
            )

    def _finish_with(self, assignment: Dict[str, Any], cost: float):
        value = assignment.get(self.name, self.current_value)
        self.value_selection(value, self._sign() * cost)
        self.finished()

"""DSA: Distributed Stochastic Algorithm (variants A/B/C).

Reference parity: pydcop/algorithms/dsa.py (params :130-135: probability
0.7, p_mode fixed/arity, variant B, stop_cycle; semantics :214-431).
Kernels: pydcop_tpu/ops/dsa.py.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'dsa', max_cycles=30, algo_params={'seed': 1})
    >>> round(res['cost'], 3)
    0.0
"""

from functools import partial
from typing import Optional

import numpy as np

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import compile_dcop, validated_aggregation
from pydcop_tpu.engine.runner import DeviceRunResult, run_device_fn
from pydcop_tpu.ops.dsa import run_dsa

GRAPH_TYPE = "constraints_hypergraph"

HEADER_SIZE = 0
UNIT_SIZE = 1

algo_params = [
    # Variable-aggregation strategy for the shared local-search
    # kernels (ops/localsearch.py): "scatter" is the parity
    # default; "ell" replaces every segment_sum/max/min with
    # compile-time dense-gather edge lists (the TPU HBM-regime
    # candidate, benchmarks/exp_aggregation.py).  Single-device;
    # sharded runs always use scatter.
    AlgoParameterDef(
        "aggregation", "str", ["scatter", "ell"], "scatter"
    ),
    AlgoParameterDef("probability", "float", None, 0.7),
    AlgoParameterDef("p_mode", "str", ["fixed", "arity"], "fixed"),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("seed", "int", None, 0),
]


def computation_memory(node) -> float:
    return chg.computation_memory(node)


def communication_load(src, target: str) -> float:
    return chg.communication_load(src, target)


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("dsa", comp_def)


def _arity_probabilities(graph, probability: float) -> np.ndarray:
    """p_mode=arity: p = 1.2 / sum(arity-1 over incident constraints)
    (reference dsa.py:257-263)."""
    n = graph.var_costs.shape[0]
    n_count = np.zeros(n, dtype=np.float64)
    for b in graph.buckets:
        arity = b.var_ids.shape[1]
        if arity < 2:
            continue
        for p in range(arity):
            np.add.at(n_count, np.asarray(b.var_ids[:, p]), arity - 1)
    probs = np.full(n, probability, dtype=np.float32)
    mask = n_count > 0
    probs[mask] = 1.2 / n_count[mask]
    return probs


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    warmup: bool = False,
                    **_) -> DeviceRunResult:
    params = algo_def.params
    pad_to = mesh.size if mesh is not None else (n_devices or 1)
    graph, meta = compile_dcop(
        dcop, pad_to=pad_to,
        aggregation=validated_aggregation(params, pad_to))
    cycles = params.get("stop_cycle") or max_cycles
    probability = params.get("probability", 0.7)
    if params.get("p_mode") == "arity":
        probability = _arity_probabilities(graph, probability)
    fn = partial(
        run_dsa,
        max_cycles=cycles,
        variant=params.get("variant", "B"),
        probability=probability,
        seed=params.get("seed", 0),
    )
    return run_device_fn(
        graph, meta, fn, mesh=mesh, n_devices=n_devices, warmup=warmup,
        finished=bool(params.get("stop_cycle")),
    )

"""CLI tests for batch + consolidate.

Mirrors the reference strategy: real subprocesses, temp work dirs
(reference tests/dcop_cli/).
"""

import json
import os
import subprocess
import sys

import yaml

from fixtures_paths import LOCAL_INSTANCES as INSTANCES
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def cli(args, cwd=None, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli"] + args,
        cwd=cwd, timeout=timeout, env=ENV, capture_output=True,
        text=True,
    )


def _batch_def(tmp_path):
    return {
        "sets": {
            "colorings": {
                "path": os.path.join(
                    INSTANCES, "coloring_chain.yaml"),
                "iterations": 1,
            },
        },
        "global_options": {"timeout": 3},
        "batches": {
            "sweep": {
                "command": "solve",
                "command_options": {
                    "algo": "dsa",
                    "algo_params": {"variant": ["A", "B"],
                                    "stop_cycle": 20},
                    "mode": "thread",
                },
                "global_options": {
                    "output": str(
                        tmp_path / "out_{algo_params[variant]}.json"
                    ),
                },
            },
        },
    }


def test_batch_simulate_lists_jobs(tmp_path):
    bench = tmp_path / "bench.yaml"
    bench.write_text(yaml.safe_dump(_batch_def(tmp_path)))
    res = cli(["batch", "--simulate", str(bench)])
    assert res.returncode == 0
    lines = [ln for ln in res.stdout.splitlines() if ln.strip()]
    assert len(lines) == 2  # variant sweep: A, B
    assert all("--algo dsa" in ln for ln in lines)
    assert any("variant:A" in ln for ln in lines)
    assert any("variant:B" in ln for ln in lines)


def test_batch_runs_and_resumes(tmp_path):
    bench = tmp_path / "bench.yaml"
    spec = _batch_def(tmp_path)
    bench.write_text(yaml.safe_dump(spec))
    res = cli(["batch", str(bench)])
    assert res.returncode == 0, res.stderr
    for variant in ("A", "B"):
        out = tmp_path / f"out_{variant}.json"
        assert out.exists()
        data = json.loads(out.read_text())
        assert data["status"] in ("FINISHED", "TIMEOUT")
    # Completed: progress file renamed to done_*.
    assert not (tmp_path / "progress_bench.yaml").exists()
    done = [f for f in os.listdir(tmp_path) if f.startswith("done_")]
    assert done
    # Seed a progress file marking all jobs done: nothing runs.
    for variant in ("A", "B"):
        (tmp_path / f"out_{variant}.json").unlink()
    os.rename(tmp_path / done[0], tmp_path / "progress_bench.yaml")
    res = cli(["batch", str(bench)])
    assert res.returncode == 0
    assert not (tmp_path / "out_A.json").exists()


def test_consolidate_solution(tmp_path):
    result = {
        "time": 1.5, "cost": 2.0, "cycle": 10, "msg_count": 5,
        "msg_size": 9, "status": "FINISHED",
    }
    f = tmp_path / "r.json"
    f.write_text(json.dumps(result))
    res = cli(["consolidate", "--solution", str(f)])
    assert res.returncode == 0
    assert res.stdout.strip() == "1.5,2.0,10,5,9,FINISHED"
    # With --output: header + append.
    out = tmp_path / "all.csv"
    cli(["--output", str(out), "consolidate", "--solution", str(f)])
    cli(["--output", str(out), "consolidate", "--solution", str(f)])
    lines = out.read_text().strip().splitlines()
    assert lines[0].startswith("time,cost")
    assert len(lines) == 3


def test_consolidate_distribution_cost(tmp_path):
    dist = tmp_path / "dist.yaml"
    dist.write_text(
        "distribution:\n"
        "  b1: [w1, w2, clash_12]\n"
        "  b2: [w3, w4, clash_23, clash_34]\n"
    )
    res = cli([
        "consolidate", "--distribution_cost", str(dist),
        "--algo", "maxsum",
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    assert res.returncode == 0, res.stderr
    row = res.stdout.strip().split(",")
    assert len(row) == 5
    assert row[1] == str(dist)


def test_consolidate_average(tmp_path):
    """--average (declared-but-unimplemented in the reference; real
    here): numeric means + FINISHED fraction over result files."""
    r1 = {"time": 2.0, "cost": 10, "cycle": 5, "msg_count": 100,
          "msg_size": 200, "status": "FINISHED"}
    r2 = {"time": 4.0, "cost": 20, "cycle": 15, "msg_count": 300,
          "msg_size": 400, "status": "TIMEOUT"}
    f1, f2 = tmp_path / "r1.json", tmp_path / "r2.json"
    f1.write_text(json.dumps(r1))
    f2.write_text(json.dumps(r2))
    res = cli(["consolidate", "--average", str(f1), str(f2)])
    assert res.returncode == 0
    assert res.stdout.strip() == "2,3.0,15.0,10.0,200.0,300.0,0.5"


def test_consolidate_average_skips_bad_files(tmp_path):
    good = tmp_path / "g.json"
    good.write_text(json.dumps(
        {"time": 1.0, "cost": 4, "cycle": 2, "msg_count": 8,
         "msg_size": 16, "status": "FINISHED"}))
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    res = cli(["consolidate", "--average", str(good), str(bad)])
    assert res.returncode == 0
    assert res.stdout.strip().startswith("1,1.0,4.0,2.0,")

"""Run every examples/ script as an acceptance test (the reference
treats its tests/integration scripts the same way, run_all.py:37)."""

import glob
import os
import subprocess
import sys

import pytest

EXAMPLES = sorted(glob.glob(os.path.join(
    os.path.dirname(__file__), "..", "..", "examples", "*.py")))
REPO_ROOT = os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", ".."))
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    # Examples import pydcop_tpu; as subprocess scripts their sys.path
    # gets examples/, not the repo root, so inject it explicitly.
    "PYTHONPATH": REPO_ROOT + (
        os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else ""),
}


def test_examples_exist():
    assert EXAMPLES


@pytest.mark.parametrize(
    "script", EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_runs_clean(script):
    out = subprocess.run(
        [sys.executable, script], timeout=180, env=ENV,
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr[-2000:]

"""Dynamic MaxSum: factor functions and scopes that change at run time.

Reference parity: pydcop/algorithms/maxsum_dynamic.py —
``DynamicFunctionFactorComputation`` (:40, same-scope function swap),
``FactorWithReadOnlyVariableComputation`` (:113, relation sliced on
subscribed read-only/sensor variables), ``DynamicFactorComputation``
(:188, scope changes with ADD/REMOVE variable notifications) and
``DynamicFactorVariableComputation`` (:352).  The reference classes are
documented in-tree as broken after the maxsum refactor (maxsum_dynamic
.py:57-60); the agent computations here (in
pydcop_tpu.infrastructure.agent_algorithms) are working equivalents on
the BSP MaxSum computations.

Device path: the batched engine handles dynamic problems by recompiling
the factor-graph tensors on topology events and warm-starting messages
(see engine.compile); a static problem solved through this module is
plain MaxSum, so ``solve_on_device`` delegates.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'maxsum_dynamic', max_cycles=50)
    >>> round(res['cost'], 3)
    0.0
"""

from pydcop_tpu.algorithms import maxsum as _maxsum

GRAPH_TYPE = "factor_graph"

algo_params = _maxsum.algo_params


def computation_memory(node) -> float:
    return _maxsum.computation_memory(node)


def communication_load(src, target: str) -> float:
    return _maxsum.communication_load(src, target)


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("maxsum_dynamic", comp_def)


def _slice_externals(dcop):
    """DCOP with every external variable frozen at its current value:
    constraints over externals are sliced, others pass through.  The
    device engine optimizes the writable variables only; external value
    changes are handled by re-slicing + recompiling."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import ExternalVariable

    if not dcop.external_variables:
        return dcop
    sliced = DCOP(dcop.name, objective=dcop.objective)
    for v in dcop.variables.values():
        sliced.add_variable(v)
    for c in dcop.constraints.values():
        ext = {
            v.name: v.value for v in c.dimensions
            if isinstance(v, ExternalVariable)
        }
        sliced.add_constraint(c.slice(ext) if ext else c)
    for a in dcop.agents.values():
        sliced.add_agents([a])
    return sliced


# Delegates to the maxsum engine after slicing externals, so the
# partitioned-sharding knob (shards=) flows through **kwargs.
SUPPORTS_SHARDS = True


def solve_on_device(dcop, algo_def, **kwargs):
    """Freeze external variables at their current values, then run the
    batched MaxSum engine on the writable problem."""
    return _maxsum.solve_on_device(_slice_externals(dcop), algo_def,
                                   **kwargs)

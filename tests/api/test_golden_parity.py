"""Golden-parity: device-engine solves vs brute-force optimum.

This is the CPU-vs-TPU / framework-vs-reference equivalence layer the
survey calls for (SURVEY.md §4): identical problems, identical optimal
costs.  Exact algorithms (dpop, syncbb) must hit the brute-force
optimum on every tractable fixture; approximate ones (maxsum) must
match it on the small fixtures they are documented to solve.

Two tiers: the committed local instances under ``tests/instances``
always run (the suite is self-contained), and when the reference
checkout is mounted the same batteries re-run on the reference's own
fixture files verbatim as the parity tier.
"""

import functools
import itertools
import os

import pytest

from fixtures_paths import (
    HAVE_REFERENCE,
    REF_INSTANCES,
    local,
    local_instances,
    ref_instances,
)
from pydcop_tpu.api import solve
from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

MAX_BRUTE_FORCE = 50_000


def _fixtures():
    yield from local_instances()
    yield from ref_instances()


@functools.lru_cache(maxsize=None)
def _brute_force_cost_for(path):
    """Optimal cost of a fixture file by enumeration, cached per path:
    collection builds TRACTABLE/INTRACTABLE from every fixture and each
    test needs the same value again — without the cache every pytest
    invocation enumerates each fixture's joint space twice."""
    return _brute_force_cost(load_dcop_from_file([path]))


def _brute_force_cost(dcop):
    """Optimal cost by enumeration; None when the space is too big."""
    variables = list(dcop.variables.values())
    space = 1
    for v in variables:
        space *= len(v.domain)
        if space > MAX_BRUTE_FORCE:
            return None
    best = None
    for values in itertools.product(*(v.domain for v in variables)):
        assignment = {
            v.name: val for v, val in zip(variables, values)
        }
        cost, _ = dcop.solution_cost(assignment)
        if best is None:
            best = cost
        elif dcop.objective == "min":
            best = min(best, cost)
        else:
            best = max(best, cost)
    return best


TRACTABLE = [
    p for p in _fixtures()
    if _brute_force_cost_for(p) is not None
]


@pytest.mark.parametrize(
    "path", TRACTABLE, ids=[os.path.basename(p) for p in TRACTABLE]
)
def test_dpop_matches_brute_force(path):
    dcop = load_dcop_from_file([path])
    expected = _brute_force_cost_for(path)
    res = solve(dcop, "dpop")
    assert res["cost"] == pytest.approx(expected, abs=1e-5), path


@pytest.mark.parametrize(
    "path", TRACTABLE, ids=[os.path.basename(p) for p in TRACTABLE]
)
def test_syncbb_matches_brute_force(path):
    dcop = load_dcop_from_file([path])
    if dcop.objective == "max":
        pytest.skip("syncbb is a minimizer (reference parity)")
    expected = _brute_force_cost_for(path)
    res = solve(dcop, "syncbb")
    assert res["cost"] == pytest.approx(expected, abs=1e-5), path


@pytest.mark.parametrize(
    "path", TRACTABLE, ids=[os.path.basename(p) for p in TRACTABLE]
)
def test_agent_ncbb_matches_brute_force(path):
    """Agent-mode NCBB's SEARCH phase (the part the reference stubs
    out, reference ncbb.py:341) must return the optimum like the
    engine path — asserted against brute force on every tractable
    reference fixture."""
    from pydcop_tpu.distribution.objects import (
        ImpossibleDistributionException,
    )

    dcop = load_dcop_from_file([path])
    expected = _brute_force_cost_for(path)
    try:
        res = solve(dcop, "ncbb", backend="thread",
                    distribution="adhoc", timeout=30)
    except ImpossibleDistributionException as exc:
        # Fixture's declared agents cannot host the hypergraph
        # computations (e.g. secp_simple1's capacity limits) — a
        # distribution-feasibility property, not a search property.
        pytest.skip(f"agents cannot host the graph: {exc}")
    assert res["status"] == "FINISHED", path
    assert res["cost"] == pytest.approx(expected, abs=1e-5), path


def test_agent_ncbb_chain_scales_by_separator_width():
    """A 20-variable chain (3^20 joint space, separator width 1) must
    solve fast: contexts are projected onto each subtree's separator,
    so the search explores O(depth * domain) contexts — without the
    projection this case fans out ~3^19 contexts and hangs."""
    import numpy as np

    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    dom = Domain("d", "", [0, 1, 2])
    dcop = DCOP("chain", objective="min")
    vs = [Variable(f"v{i:02d}", dom) for i in range(20)]
    for v in vs:
        dcop.add_variable(v)
    rng = np.random.default_rng(4)
    for i in range(19):
        costs = rng.integers(0, 9, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[i + 1]], costs, f"c{i}"))
    dcop.add_agents([AgentDef(f"a{i}") for i in range(5)])
    res = solve(dcop, "ncbb", backend="thread",
                distribution="adhoc", timeout=30)
    expected = solve(dcop, "dpop")
    assert res["status"] == "FINISHED"
    assert res["cost"] == expected["cost"]


@pytest.mark.parametrize("fixture,expected", [
    ("coloring_chain.yaml", -0.6),
    ("coloring_chain_func.yaml", -0.6),
    ("coloring_chain_init.yaml", -0.6),
    ("coloring_ext_costs.yaml", -0.6),
    ("pref_ring.yaml", 14.0),
])
def test_maxsum_reaches_optimum(fixture, expected):
    """Small colorings where maxsum reliably reaches the brute-force
    optimum (expected values verified by enumeration)."""
    dcop = load_dcop_from_file([local(fixture)])
    res = solve(dcop, "maxsum", max_cycles=200)
    assert res["cost"] == pytest.approx(expected, abs=1e-5)


@pytest.mark.skipif(not HAVE_REFERENCE, reason="reference not mounted")
@pytest.mark.parametrize("fixture,expected", [
    ("graph_coloring1.yaml", -0.1),
    ("graph_coloring1_func.yaml", -0.1),
    ("graph_coloring_eq.yaml", -0.3),
    ("graph_coloring_tuto.yaml", 12.0),
])
def test_maxsum_reaches_optimum_reference(fixture, expected):
    """Parity tier: same battery on the reference's own fixtures."""
    dcop = load_dcop_from_file(
        [os.path.join(REF_INSTANCES, fixture)]
    )
    res = solve(dcop, "maxsum", max_cycles=200)
    assert res["cost"] == pytest.approx(expected, abs=1e-5)


def test_secp_fixture_solves():
    dcop = load_dcop_from_file([local("secp_lamps.yaml")])
    expected = _brute_force_cost_for(local("secp_lamps.yaml"))
    res = solve(dcop, "dpop")
    assert res["cost"] == pytest.approx(expected, abs=1e-5)


# Fixtures whose joint space is too big to enumerate but whose
# pseudo-tree is narrow enough for DPOP — DPOP (exact by construction,
# itself brute-force-validated on every tractable fixture above) is
# the oracle here, completing coverage of ALL reference instance
# files.
INTRACTABLE = [
    p for p in _fixtures()
    if p not in TRACTABLE
]


@pytest.mark.parametrize(
    "path", INTRACTABLE,
    ids=[os.path.basename(p) for p in INTRACTABLE],
)
def test_exact_algorithms_agree_on_large_fixtures(path):
    from pydcop_tpu.distribution.objects import (
        ImpossibleDistributionException,
    )

    dcop = load_dcop_from_file([path])
    oracle = solve(load_dcop_from_file([path]), "dpop")
    assert oracle["status"] == "FINISHED"
    # syncbb's B&B bounds are too weak for the house-scale fixtures'
    # real-valued intentional costs (minutes of search); covered by
    # dpop+ncbb.
    slow_for_syncbb = os.path.basename(path) in (
        "SimpleHouse.yml", "loft_scene.yml")
    if dcop.objective == "min" and not slow_for_syncbb:
        res = solve(load_dcop_from_file([path]), "syncbb")
        assert res["cost"] == pytest.approx(
            oracle["cost"], abs=1e-5), "syncbb vs dpop"
    if any(c.arity > 2 for c in dcop.constraints.values()):
        pytest.skip("ncbb is defined on binary constraint graphs")
    try:
        res = solve(dcop, "ncbb", backend="thread",
                    distribution="adhoc", timeout=30)
    except ImpossibleDistributionException as exc:
        pytest.skip(f"agents cannot host the graph: {exc}")
    assert res["status"] == "FINISHED"
    assert res["cost"] == pytest.approx(
        oracle["cost"], abs=1e-5), "agent ncbb vs dpop"

"""Small-world benchmark generator: Watts-Strogatz constraint graph.

Reference parity: pydcop/commands/generators/smallworld.py (small_world
subcommand: binary constraints with random costs over a small-world
graph).
"""

from typing import Optional

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.generators.graphs import small_world_graph


def generate_small_world(
    num_variables: int,
    domain_range: int = 10,
    k: int = 4,
    p_rewire: float = 0.1,
    range_cost: int = 10,
    seed: Optional[int] = None,
) -> DCOP:
    rng = np.random.default_rng(seed)
    domain = Domain("d", "d", list(range(domain_range)))
    variables = [
        Variable(f"v{i:04d}", domain) for i in range(num_variables)
    ]
    dcop = DCOP(f"smallworld_{num_variables}", objective="min")
    for v in variables:
        dcop.add_variable(v)
    for idx, (i, j) in enumerate(
        small_world_graph(num_variables, k, p_rewire, seed=seed)
    ):
        table = rng.integers(
            0, range_cost, size=(domain_range, domain_range)
        ).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], table, f"c{idx}"))
    dcop.add_agents([
        AgentDef(f"a{i:04d}", capacity=100)
        for i in range(num_variables)
    ])
    return dcop

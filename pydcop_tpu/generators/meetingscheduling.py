"""Meeting-scheduling benchmark generator (PEAV model).

Reference parity: pydcop/commands/generators/meetingscheduling.py
(peav_model :317): Private-Events-As-Variables — one variable per
(resource, event) pair over the slot domain (0 = not scheduled);
intra-resource constraints penalize overlapping schedules and reward
valued slots (:528-585); inter-resource constraints force all
participants of an event to agree on its slot (:589-600, -penalty when
different).  Objective: max (utilities, penalties negative).
"""

from typing import Dict, List, Optional, Tuple

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation


def generate_meetings(
    slots_count: int,
    events_count: int,
    resources_count: int,
    max_resources_event: int,
    max_length_event: int = 1,
    max_resource_value: int = 10,
    penalty: int = 100,
    no_agents: bool = False,
    capacity: Optional[int] = None,
    seed: Optional[int] = None,
) -> DCOP:
    rng = np.random.default_rng(seed)
    # Slot 0 means "not scheduled"; real slots are 1..slots_count.
    domain = Domain("slots", "slots", list(range(slots_count + 1)))

    # Events: length + the resources they request.
    events: List[Dict] = []
    for e in range(events_count):
        n_res = int(rng.integers(1, max_resources_event + 1))
        events.append({
            "id": e,
            "length": int(rng.integers(1, max_length_event + 1)),
            "resources": sorted(
                rng.choice(resources_count, size=min(
                    n_res, resources_count), replace=False).tolist()
            ),
        })

    # Resource r's value for holding event e at slot t.
    value = rng.integers(
        1, max_resource_value + 1,
        size=(resources_count, events_count, slots_count + 1),
    ).astype(float)

    dcop = DCOP(
        f"meetings_{slots_count}_{events_count}_{resources_count}",
        objective="max",
    )

    # PEAV variables: one per (resource, event in which it participates).
    res_events: Dict[int, List[Dict]] = {r: [] for r in
                                         range(resources_count)}
    variables: Dict[Tuple[int, int], Variable] = {}
    for ev in events:
        for r in ev["resources"]:
            v = Variable(f"v_r{r}_e{ev['id']}", domain)
            variables[(r, ev["id"])] = v
            res_events[r].append(ev)
            dcop.add_variable(v)

    # Intra-resource constraints: overlap penalty + slot utilities.
    for r, evs in res_events.items():
        n = len(evs)
        if n == 1:
            ev = evs[0]
            v = variables[(r, ev["id"])]
            table = value[r, ev["id"], :].copy()
            table[0] = 0  # no utility when unscheduled
            dcop.add_constraint(
                NAryMatrixRelation([v], table, f"cu_{v.name}"))
            continue
        for i in range(n):
            for j in range(i + 1, n):
                e1, e2 = evs[i], evs[j]
                v1 = variables[(r, e1["id"])]
                v2 = variables[(r, e2["id"])]
                table = np.zeros((len(domain), len(domain)))
                for t1 in range(len(domain)):
                    for t2 in range(len(domain)):
                        overlap = (
                            t1 != 0 and t2 != 0 and (
                                t1 <= t2 <= t1 + e1["length"] - 1
                                or t2 <= t1 <= t2 + e2["length"] - 1
                            )
                        )
                        if overlap:
                            table[t1, t2] = -penalty
                        else:
                            u1 = value[r, e1["id"], t1] if t1 else 0
                            u2 = value[r, e2["id"], t2] if t2 else 0
                            table[t1, t2] = (u1 + u2) / (n - 1)
                dcop.add_constraint(NAryMatrixRelation(
                    [v1, v2], table, f"ci_{v1.name}_{v2.name}"))

    # Inter-resource constraints: all participants agree on the slot.
    for ev in events:
        participants = ev["resources"]
        for i in range(len(participants)):
            for j in range(i + 1, len(participants)):
                v1 = variables[(participants[i], ev["id"])]
                v2 = variables[(participants[j], ev["id"])]
                table = np.where(
                    np.eye(len(domain), dtype=bool), 0.0, -penalty
                )
                dcop.add_constraint(NAryMatrixRelation(
                    [v1, v2], table, f"ce_{v1.name}_{v2.name}"))

    if not no_agents:
        extra = {"capacity": capacity} if capacity else {}
        dcop.add_agents([
            AgentDef(f"a_r{r}", **extra) for r in range(resources_count)
        ])
    return dcop

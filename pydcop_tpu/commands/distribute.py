"""``pydcop distribute``: offline computation-to-agent distribution.

Reference parity: pydcop/commands/distribute.py (:170-225) — loads a
DCOP, builds the computation graph (from --graph or --algo's
GRAPH_TYPE), runs the chosen distribution method and emits a
distribution YAML with inputs + cost.
"""

import importlib
import time

from pydcop_tpu.commands._utils import emit_result

DIST_METHODS = [
    "oneagent", "adhoc", "ilp_fgdp", "ilp_compref", "ilp_compref_fg",
    "heur_comhost", "gh_secp_cgdp", "gh_secp_fgdp", "oilp_secp_fgdp",
    "oilp_secp_cgdp", "oilp_cgdp", "gh_cgdp",
]


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "distribute", help="distribute a static dcop")
    parser.add_argument("dcop_files", type=str, nargs="+")
    parser.add_argument(
        "-g", "--graph", required=False,
        choices=["factor_graph", "pseudotree",
                 "constraints_hypergraph", "ordered_graph"],
    )
    parser.add_argument(
        "-d", "--distribution", required=True, choices=DIST_METHODS)
    parser.add_argument(
        "--cost", choices=DIST_METHODS, default=None,
        help="method whose cost function evaluates the distribution",
    )
    parser.add_argument("-a", "--algo", required=False)
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

    if not args.graph and not args.algo:
        print("Error: one of --graph or --algo is required")
        return 2

    from pydcop_tpu.distribution.objects import (
        ImpossibleDistributionException,
    )

    dcop = load_dcop_from_file(args.dcop_files)
    algo_module = None
    computation_memory = communication_load = None
    if args.algo:
        algo_module = load_algorithm_module(args.algo)
        if args.graph and args.graph != algo_module.GRAPH_TYPE:
            print(
                f"Error: incompatible graph model {args.graph} and "
                f"algorithm {args.algo} (expects "
                f"{algo_module.GRAPH_TYPE})"
            )
            return 2
        computation_memory = algo_module.computation_memory
        communication_load = algo_module.communication_load
    graph_type = args.graph or algo_module.GRAPH_TYPE
    cg = load_graph_module(graph_type).build_computation_graph(dcop)

    inputs = {
        "dist_algo": args.distribution,
        "dcop": args.dcop_files,
        "graph": graph_type,
        "algo": args.algo,
    }
    dist_module = importlib.import_module(
        f"pydcop_tpu.distribution.{args.distribution}")
    t0 = time.perf_counter()
    try:
        dist = dist_module.distribute(
            cg, dcop.agents.values(), hints=dcop.dist_hints,
            computation_memory=computation_memory,
            communication_load=communication_load,
            timeout=args.timeout,
        )
    except ImpossibleDistributionException as e:
        emit_result({
            "inputs": inputs,
            "status": "FAIL",
            "error": str(e),
        }, args.output)
        return 0
    elapsed = time.perf_counter() - t0

    cost_module = dist_module
    if args.cost:
        cost_module = importlib.import_module(
            f"pydcop_tpu.distribution.{args.cost}")
    cost, comm, hosting = cost_module.distribution_cost(
        dist, cg, dcop.agents.values(),
        computation_memory=computation_memory,
        communication_load=communication_load,
    )

    result = {
        "inputs": inputs,
        "status": "SUCCESS",
        "distribution": dist.mapping,
        "cost": cost,
        "communication_cost": comm,
        "hosting_cost": hosting,
        "duration": elapsed,
    }
    emit_result(result, args.output)
    return 0

"""Deep battery over dcop/yamldcop.py — format parsing, every
constraint/variable flavor, error paths, and dump→reload round-trips
(reference test_dcop_serialization.py depth)."""

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.dcop.yamldcop import (
    DcopInvalidFormatError,
    dcop_yaml,
    load_agents,
    load_dcop,
    load_scenario,
    yaml_agents,
    yaml_scenario,
)

BASE = """
name: t
objective: min
domains:
  d3:
    values: [0, 1, 2]
variables:
  v1: {domain: d3}
  v2: {domain: d3}
"""


class TestDomains:
    def test_range_string(self):
        d = load_dcop("""
name: t
domains:
  d: {values: "1 .. 4"}
variables:
  v: {domain: d}
""")
        assert list(d.domain("d")) == [1, 2, 3, 4]

    def test_range_inside_list(self):
        d = load_dcop("""
name: t
domains:
  d:
    values: ["1 .. 3", "7"]
variables:
  v: {domain: d}
""")
        assert list(d.domain("d")) == [1, 2, 3, 7]

    def test_string_ints_coerced(self):
        d = load_dcop("""
name: t
domains:
  d: {values: ["1", "2"]}
variables:
  v: {domain: d}
""")
        assert list(d.domain("d")) == [1, 2]

    def test_mixed_strings_stay_strings(self):
        d = load_dcop("""
name: t
domains:
  d: {values: [R, G, B]}
variables:
  v: {domain: d}
""")
        assert list(d.domain("d")) == ["R", "G", "B"]

    def test_domain_type_preserved(self):
        d = load_dcop("""
name: t
domains:
  d: {values: [0, 1], type: luminosity}
variables:
  v: {domain: d}
""")
        assert d.domain("d").type == "luminosity"


class TestErrors:
    def test_missing_name(self):
        with pytest.raises(DcopInvalidFormatError, match="name"):
            load_dcop("objective: min")

    def test_empty_document(self):
        with pytest.raises(DcopInvalidFormatError):
            load_dcop("")

    def test_unknown_constraint_type(self):
        with pytest.raises(DcopInvalidFormatError, match="invalid type"):
            load_dcop(BASE + """
constraints:
  c1:
    type: nope
""")

    def test_extensional_unknown_variable(self):
        with pytest.raises(DcopInvalidFormatError, match="Unknown"):
            load_dcop(BASE + """
constraints:
  c1:
    type: extensional
    variables: [v1, ghost]
    values:
      1: 0 0
""")

    def test_extensional_bad_row_width(self):
        with pytest.raises(DcopInvalidFormatError, match="expected 2"):
            load_dcop(BASE + """
constraints:
  c1:
    type: extensional
    variables: [v1, v2]
    values:
      1: 0 0 0
""")

    def test_external_variable_requires_initial_value(self):
        with pytest.raises(DcopInvalidFormatError, match="initial_value"):
            load_dcop("""
name: t
domains:
  d: {values: [0, 1]}
external_variables:
  e: {domain: d}
""")

    def test_duplicate_route_rejected(self):
        with pytest.raises(DcopInvalidFormatError, match="more than once"):
            load_dcop(BASE + """
agents: [a1, a2]
routes:
  a1: {a2: 3}
  a2: {a1: 4}
""")


class TestConstraints:
    def test_intention(self):
        d = load_dcop(BASE + """
constraints:
  c1:
    type: intention
    function: abs(v1 - v2)
""")
        c = d.constraints["c1"]
        assert set(c.scope_names) == {"v1", "v2"}
        assert c(v1=0, v2=2) == 2

    def test_intention_partial(self):
        d = load_dcop(BASE + """
constraints:
  c1:
    type: intention
    function: v1 * 10 + v2
    partial: {v1: 2}
""")
        c = d.constraints["c1"]
        assert c.scope_names == ["v2"]
        assert c(1) == 21
        assert c.name == "c1"

    def test_extensional_default(self):
        d = load_dcop(BASE + """
constraints:
  c1:
    type: extensional
    default: 5
    variables: [v1, v2]
    values:
      0: 1 1
""")
        c = d.constraints["c1"]
        assert c(1, 1) == 0
        assert c(0, 0) == 5

    def test_extensional_multi_assignments_per_cost(self):
        d = load_dcop(BASE + """
constraints:
  c1:
    type: extensional
    variables: [v1, v2]
    values:
      7: 0 0 | 1 1 | 2 2
""")
        c = d.constraints["c1"]
        for i in range(3):
            assert c(i, i) == 7
        assert c(0, 1) == 0

    def test_extensional_unary(self):
        d = load_dcop(BASE + """
constraints:
  c1:
    type: extensional
    variables: v1
    values:
      2: 1
""")
        c = d.constraints["c1"]
        assert c.arity == 1
        assert c(1) == 2 and c(0) == 0

    def test_extensional_quoted_string_values(self):
        d = load_dcop("""
name: t
domains:
  d: {values: ['hot water', cold]}
variables:
  v1: {domain: d}
constraints:
  c1:
    type: extensional
    variables: v1
    values:
      3: "'hot water'"
""")
        assert d.constraints["c1"]("hot water") == 3

    def test_hard_constraint_infinity(self):
        d = load_dcop(BASE + """
constraints:
  c1:
    type: extensional
    default: .inf
    variables: [v1, v2]
    values:
      0: 0 1
""")
        assert d.constraints["c1"](0, 0) == float("inf")
        assert d.constraints["c1"](0, 1) == 0


class TestVariablesAndAgents:
    def test_variable_with_cost_function(self):
        d = load_dcop("""
name: t
domains:
  d: {values: [0, 1, 2]}
variables:
  v1:
    domain: d
    cost_function: v1 * 2
""")
        assert d.variables["v1"].cost_for_val(2) == 4

    def test_variable_noisy_cost(self):
        d = load_dcop("""
name: t
domains:
  d: {values: [0, 1]}
variables:
  v1:
    domain: d
    cost_function: v1 * 2
    noise_level: 0.05
""")
        v = d.variables["v1"]
        assert 0 <= v.cost_for_val(0) < 0.05

    def test_initial_value(self):
        d = load_dcop("""
name: t
domains:
  d: {values: [0, 1]}
variables:
  v1: {domain: d, initial_value: 1}
""")
        assert d.variables["v1"].initial_value == 1

    def test_agents_list_form(self):
        d = load_dcop(BASE + "agents: [a1, a2]\n")
        assert set(d.agents) == {"a1", "a2"}

    def test_agents_with_capacity(self):
        d = load_dcop(BASE + """
agents:
  a1: {capacity: 7}
""")
        assert d.agents["a1"].capacity == 7

    def test_hosting_costs_and_routes(self):
        d = load_dcop(BASE + """
agents: [a1, a2]
routes:
  default: 5
  a1: {a2: 2}
hosting_costs:
  default: 9
  a1:
    default: 3
    computations: {v1: 1}
""")
        a1, a2 = d.agents["a1"], d.agents["a2"]
        assert a1.route("a2") == 2
        assert a2.route("a1") == 2   # symmetric
        assert a1.hosting_cost("v1") == 1
        assert a1.hosting_cost("other") == 3
        assert a2.hosting_cost("v1") == 9   # global default

    def test_distribution_hints(self):
        d = load_dcop(BASE + """
distribution_hints:
  must_host:
    a1: [v1]
""")
        assert d.dist_hints.must_host("a1") == ["v1"]


class TestRoundTrips:
    def _roundtrip(self, yaml_str):
        d1 = load_dcop(yaml_str)
        d2 = load_dcop(dcop_yaml(d1))
        return d1, d2

    def test_intention_roundtrip(self):
        d1, d2 = self._roundtrip(BASE + """
constraints:
  c1:
    type: intention
    function: abs(v1 - v2)
""")
        for a in ((0, 0), (0, 2), (2, 1)):
            assert d1.constraints["c1"](*a) == d2.constraints["c1"](*a)

    def test_extensional_roundtrip(self):
        d1, d2 = self._roundtrip(BASE + """
constraints:
  c1:
    type: extensional
    default: 4
    variables: [v1, v2]
    values:
      1: 0 0 | 2 2
""")
        c1, c2 = d1.constraints["c1"], d2.constraints["c1"]
        for i in range(3):
            for j in range(3):
                assert c1(i, j) == c2(i, j)

    def test_objective_and_name_roundtrip(self):
        d1, d2 = self._roundtrip(
            BASE.replace("objective: min", "objective: max"))
        assert d2.name == "t" and d2.objective == "max"

    def test_agents_roundtrip(self):
        _, d2 = self._roundtrip(BASE + """
agents:
  a1: {capacity: 7}
  a2: {capacity: 8}
routes:
  a1: {a2: 2}
""")
        assert d2.agents["a1"].capacity == 7
        assert d2.agents["a1"].route("a2") == 2

    def test_yaml_agents_roundtrip(self):
        agents = [AgentDef("a1", capacity=5), AgentDef("a2", foo="x")]
        loaded = load_agents(yaml_agents(agents))
        assert [a.name for a in loaded] == ["a1", "a2"]
        assert loaded[0].capacity == 5

    def test_scenario_roundtrip(self):
        s = load_scenario("""
events:
  - id: e1
    delay: 2.5
  - id: e2
    actions:
      - type: remove_agent
        agent: a1
""")
        s2 = load_scenario(yaml_scenario(s))
        assert len(s2.events) == 2
        assert s2.events[0].is_delay and s2.events[0].delay == 2.5
        assert s2.events[1].actions[0].type == "remove_agent"
        assert s2.events[1].actions[0].args["agent"] == "a1"

    def test_device_solve_after_roundtrip(self):
        # The dumped file must stay solvable with identical cost.
        from pydcop_tpu.api import solve

        yaml_str = BASE + """
constraints:
  c1:
    type: intention
    function: 1 if v1 == v2 else 0
"""
        d1, d2 = self._roundtrip(yaml_str)
        r1 = solve(d1, "dpop")
        r2 = solve(d2, "dpop")
        assert r1["cost"] == r2["cost"] == 0

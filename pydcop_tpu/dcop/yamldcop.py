"""YAML (de)serialization for DCOPs, agents, distributions and scenarios.

Reference parity: pydcop/dcop/yamldcop.py (load_dcop_from_file :63,
load_dcop :96, dcop_yaml :119, _build_constraints :217, _build_agents
:316, yaml_agents :397, scenario load :504).  Format spec:
docs/usage/file_formats/dcop_format.yml in the reference — this module
accepts the exact same files (round-trip tested against the reference's
fixtures in tests/instances/).
"""

import os
import re
from typing import Any, Dict, Iterable, List, Optional, Union

import yaml

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
    VariableNoisyCostFunc,
    VariableWithCostFunc,
)
from pydcop_tpu.dcop.relations import (
    Constraint,
    NAryMatrixRelation,
    assignment_matrix,
    constraint_from_external_definition,
    constraint_from_str,
)
from pydcop_tpu.dcop.scenario import DcopEvent, EventAction, Scenario
from pydcop_tpu.distribution.objects import Distribution, DistributionHints

_RANGE_RE = re.compile(r"^\s*(-?\d+)\s*\.\.\s*(-?\d+)\s*$")


class DcopInvalidFormatError(Exception):
    pass


# --------------------------------------------------------------------- #
# Loading


def load_dcop_from_file(filenames: Union[str, Iterable[str]],
                        main_dir: Optional[str] = None) -> DCOP:
    """Load a DCOP from one or several YAML files (contents are
    concatenated, reference behavior yamldcop.py:63)."""
    if isinstance(filenames, str):
        filenames = [filenames]
    filenames = list(filenames)
    contents = []
    for f in filenames:
        with open(f, encoding="utf-8") as fh:
            contents.append(fh.read())
    if main_dir is None:
        main_dir = os.path.dirname(os.path.abspath(filenames[0]))
    return load_dcop("\n".join(contents), main_dir=main_dir)


def _parse_domain_values(raw_values) -> List:
    if isinstance(raw_values, str):
        m = _RANGE_RE.match(raw_values)
        if m:
            lo, hi = int(m.group(1)), int(m.group(2))
            return list(range(lo, hi + 1))
        # Single scalar string: fall through to the shared coercion so
        # values: "7" and values: ["7"] produce the same int domain.
        raw_values = [raw_values]
    elif not isinstance(raw_values, (list, tuple)):
        # Unquoted scalar (values: 7 — yaml already parsed the type):
        # a one-value domain, same as the quoted form.
        raw_values = [raw_values]
    values: List = []
    for v in raw_values:
        if isinstance(v, str):
            m = _RANGE_RE.match(v)
            if m:
                values.extend(range(int(m.group(1)), int(m.group(2)) + 1))
                continue
        values.append(v)
    # If every value is an int or a *string* that parses as one, the
    # domain is an int domain (reference behavior for ranges / quoted
    # ints) — this also covers a range mixed with quoted ints, which
    # would otherwise produce an inconsistent [1, 2, 3, '7'] domain.
    # Values yaml already parsed as floats/bools are kept as-is —
    # coercing them would corrupt the domain.
    def _is_intish(v):
        if isinstance(v, bool) or not isinstance(v, (int, str)):
            return False
        if isinstance(v, str):
            try:
                int(v)
            except ValueError:
                return False
        return True

    if values and any(isinstance(v, str) for v in values) \
            and all(_is_intish(v) for v in values):
        return [int(v) for v in values]
    return values


def load_dcop(yaml_str: str, main_dir: str = ".") -> DCOP:
    data = yaml.safe_load(yaml_str)
    if not data or "name" not in data:
        raise DcopInvalidFormatError("Missing DCOP name")
    objective = data.get("objective", "min")
    dcop = DCOP(
        data["name"], objective, description=data.get("description", "")
    )

    for dname, dspec in (data.get("domains") or {}).items():
        values = _parse_domain_values(dspec["values"])
        dcop.add_domain(Domain(dname, dspec.get("type", ""), values))

    for vname, vspec in (data.get("variables") or {}).items():
        dom = dcop.domain(vspec["domain"])
        initial = vspec.get("initial_value")
        if "cost_function" in vspec:
            if vspec.get("noise_level"):
                var: Variable = VariableNoisyCostFunc(
                    vname, dom, str(vspec["cost_function"]),
                    initial_value=initial,
                    noise_level=float(vspec["noise_level"]),
                )
            else:
                var = VariableWithCostFunc(
                    vname, dom, str(vspec["cost_function"]),
                    initial_value=initial,
                )
        else:
            var = Variable(vname, dom, initial_value=initial)
        dcop.add_variable(var)

    for vname, vspec in (data.get("external_variables") or {}).items():
        dom = dcop.domain(vspec["domain"])
        if "initial_value" not in vspec:
            raise DcopInvalidFormatError(
                f"External variable {vname} requires an initial_value"
            )
        dcop.add_external_variable(
            ExternalVariable(vname, dom, vspec["initial_value"])
        )

    all_vars = list(dcop.variables.values()) + list(
        dcop.external_variables.values()
    )
    for cname, cspec in (data.get("constraints") or {}).items():
        dcop.constraints[cname] = _build_constraint(
            cname, cspec, all_vars, main_dir
        )

    _build_agents(dcop, data.get("agents"), data.get("routes"),
                  data.get("hosting_costs"))

    hints = data.get("distribution_hints")
    if hints:
        dcop.dist_hints = DistributionHints(
            hints.get("must_host"), hints.get("host_with")
        )
    return dcop


def _build_constraint(cname: str, cspec: Dict, all_vars: List[Variable],
                      main_dir: str) -> Constraint:
    ctype = cspec.get("type")
    if ctype == "intention":
        expression = str(cspec["function"])
        if "source" in cspec:
            source = cspec["source"]
            if not os.path.isabs(source):
                source = os.path.join(main_dir, source)
            constraint = constraint_from_external_definition(
                cname, source, expression, all_vars
            )
        else:
            constraint = constraint_from_str(cname, expression, all_vars)
        partial = cspec.get("partial")
        if partial:
            sliced = constraint.slice(partial)
            sliced._name = cname
            return sliced
        return constraint
    if ctype == "extensional":
        by_name = {v.name: v for v in all_vars}
        var_names = cspec["variables"]
        if isinstance(var_names, str):
            var_names = [var_names]
        try:
            variables = [by_name[n] for n in var_names]
        except KeyError as e:
            raise DcopInvalidFormatError(
                f"Unknown variable in constraint {cname}: {e}"
            )
        default = cspec.get("default", 0)
        matrix = assignment_matrix(variables, default)
        for value, assignments in (cspec.get("values") or {}).items():
            for assignment in str(assignments).split("|"):
                tokens = _split_assignment_tokens(assignment)
                if len(tokens) != len(variables):
                    raise DcopInvalidFormatError(
                        f"Bad assignment {assignment!r} for constraint "
                        f"{cname}: expected {len(variables)} values"
                    )
                idx = tuple(
                    v.domain.to_domain_value(t)[0]
                    for v, t in zip(variables, tokens)
                )
                matrix[idx] = value
        return NAryMatrixRelation(variables, matrix, cname)
    raise DcopInvalidFormatError(
        f"Constraint {cname} has invalid type {ctype!r}"
    )


def _split_assignment_tokens(assignment: str) -> List[str]:
    """Split "1 2 'too bad'" into ['1', '2', 'too bad']."""
    tokens = re.findall(r"'[^']*'|\"[^\"]*\"|\S+", assignment.strip())
    return [t.strip("'\"") for t in tokens]


def _quote_token(token: str) -> str:
    """Quote an extensional-assignment token if it contains whitespace,
    so dumped files re-load through _split_assignment_tokens."""
    if re.search(r"\s", token):
        return "'" + token + "'"
    return token


def _build_agents(dcop: DCOP, agents_spec, routes_spec, hosting_spec):
    if agents_spec is None:
        return
    routes_spec = routes_spec or {}
    hosting_spec = hosting_spec or {}
    default_route = routes_spec.get("default", 1)
    default_hosting = hosting_spec.get("default", 0)

    # Routes are symmetric; defining the same pair twice is an error.
    routes: Dict[str, Dict[str, float]] = {}
    seen = set()
    for a, targets in routes_spec.items():
        if a == "default":
            continue
        for b, cost in targets.items():
            pair = frozenset((a, b))
            if pair in seen:
                raise DcopInvalidFormatError(
                    f"Route ({a}, {b}) defined more than once"
                )
            seen.add(pair)
            routes.setdefault(a, {})[b] = cost
            routes.setdefault(b, {})[a] = cost

    if isinstance(agents_spec, list):
        agents_spec = {a: {} for a in agents_spec}

    for aname, aspec in agents_spec.items():
        aspec = aspec or {}
        a_hosting = hosting_spec.get(aname, {}) or {}
        agent = AgentDef(
            aname,
            default_hosting_cost=a_hosting.get("default", default_hosting),
            hosting_costs=a_hosting.get("computations"),
            default_route=default_route,
            routes=routes.get(aname),
            **aspec,
        )
        dcop.add_agents(agent)


# --------------------------------------------------------------------- #
# Dumping


def dcop_yaml(dcop: DCOP) -> str:
    """Serialize a DCOP back to the YAML format."""
    data: Dict[str, Any] = {
        "name": dcop.name,
        "objective": dcop.objective,
    }
    if dcop.description:
        data["description"] = dcop.description
    data["domains"] = {
        d.name: {
            "values": list(d.values),
            **({"type": d.type} if d.type else {}),
        }
        for d in dcop.domains.values()
    }
    variables = {}
    for v in dcop.variables.values():
        vspec: Dict[str, Any] = {"domain": v.domain.name}
        if v.initial_value is not None:
            vspec["initial_value"] = v.initial_value
        if isinstance(v, VariableNoisyCostFunc):
            vspec["cost_function"] = v.cost_func.expression
            vspec["noise_level"] = v.noise_level
        elif isinstance(v, VariableWithCostFunc):
            if hasattr(v.cost_func, "expression"):
                vspec["cost_function"] = v.cost_func.expression
        variables[v.name] = vspec
    data["variables"] = variables
    if dcop.external_variables:
        data["external_variables"] = {
            v.name: {"domain": v.domain.name, "initial_value": v.value}
            for v in dcop.external_variables.values()
        }
    constraints = {}
    for c in dcop.constraints.values():
        if isinstance(c, NAryMatrixRelation):
            values: Dict[float, List[str]] = {}
            import numpy as np

            for idx in np.ndindex(*c.matrix.shape):
                val = float(c.matrix[idx])
                if val == 0:
                    continue
                assignment = " ".join(
                    _quote_token(str(v.domain[i]))
                    for v, i in zip(c.dimensions, idx)
                )
                values.setdefault(val, []).append(assignment)
            constraints[c.name] = {
                "type": "extensional",
                "variables": c.scope_names,
                "values": {
                    (int(v) if float(v).is_integer() else v):
                        " | ".join(assts)
                    for v, assts in values.items()
                },
            }
        else:
            expr = getattr(c, "expression", None)
            if expr is None:
                raise ValueError(
                    f"Cannot serialize constraint {c.name}: no expression"
                )
            constraints[c.name] = {"type": "intention", "function": expr}
    data["constraints"] = constraints
    if dcop.agents:
        data["agents"] = {
            a.name: (
                {**a.extra_attr} if a.extra_attr else {}
            )
            for a in dcop.agents.values()
        }
        # Routes (symmetric: dump each pair once) and hosting costs.
        routes: Dict[str, Dict[str, float]] = {}
        dumped_pairs = set()
        hosting: Dict[str, Any] = {}
        for a in dcop.agents.values():
            for other, cost in a.routes.items():
                pair = frozenset((a.name, other))
                if pair in dumped_pairs:
                    continue
                dumped_pairs.add(pair)
                routes.setdefault(a.name, {})[other] = cost
            h: Dict[str, Any] = {}
            if a.default_hosting_cost:
                h["default"] = a.default_hosting_cost
            if a.hosting_costs:
                h["computations"] = a.hosting_costs
            if h:
                hosting[a.name] = h
        default_routes = {a.default_route for a in dcop.agents.values()}
        if default_routes != {1} and len(default_routes) == 1:
            routes = {"default": default_routes.pop(), **routes}
        if routes:
            data["routes"] = routes
        if hosting:
            data["hosting_costs"] = hosting
    if dcop.dist_hints is not None:
        hints: Dict[str, Any] = {}
        if dcop.dist_hints.must_host_map:
            hints["must_host"] = dcop.dist_hints.must_host_map
        if hints:
            data["distribution_hints"] = hints
    return yaml.safe_dump(data, sort_keys=False, default_flow_style=False)


def yaml_agents(agents: List[AgentDef]) -> str:
    """Serialize a list of AgentDefs (``pydcop generate agents`` output)."""
    data: Dict[str, Any] = {}
    hosting: Dict[str, Any] = {}
    routes: Dict[str, Any] = {}
    for a in agents:
        data[a.name] = dict(a.extra_attr)
        if a.hosting_costs or a.default_hosting_cost:
            h: Dict[str, Any] = {}
            if a.default_hosting_cost:
                h["default"] = a.default_hosting_cost
            if a.hosting_costs:
                h["computations"] = a.hosting_costs
            hosting[a.name] = h
        if a.routes:
            routes[a.name] = a.routes
    out: Dict[str, Any] = {"agents": data}
    if hosting:
        out["hosting_costs"] = hosting
    if routes:
        out["routes"] = routes
    return yaml.safe_dump(out, sort_keys=False)


def load_agents_from_file(filename: str) -> List[AgentDef]:
    with open(filename, encoding="utf-8") as f:
        return load_agents(f.read())


def load_agents(yaml_str: str) -> List[AgentDef]:
    data = yaml.safe_load(yaml_str) or {}
    dcop = DCOP("agents_only")
    _build_agents(dcop, data.get("agents"), data.get("routes"),
                  data.get("hosting_costs"))
    return list(dcop.agents.values())


# --------------------------------------------------------------------- #
# Scenario


def load_scenario_from_file(filename: str) -> Scenario:
    with open(filename, encoding="utf-8") as f:
        return load_scenario(f.read())


def load_scenario(yaml_str: str) -> Scenario:
    data = yaml.safe_load(yaml_str) or {}
    events = []
    for espec in data.get("events") or []:
        if "delay" in espec:
            events.append(DcopEvent(espec.get("id", "delay"),
                                    delay=float(espec["delay"])))
        else:
            actions = [
                EventAction(
                    a["type"],
                    **{k: v for k, v in a.items() if k != "type"},
                )
                for a in espec.get("actions", [])
            ]
            events.append(DcopEvent(espec["id"], actions=actions))
    return Scenario(events)


def yaml_scenario(scenario: Scenario) -> str:
    events = []
    for e in scenario.events:
        if e.is_delay:
            events.append({"id": e.id, "delay": e.delay})
        else:
            events.append({
                "id": e.id,
                "actions": [
                    {"type": a.type, **a.args} for a in e.actions
                ],
            })
    return yaml.safe_dump({"events": events}, sort_keys=False)


# --------------------------------------------------------------------- #
# Distribution files (dist_format.yml)


def load_dist_from_file(filename: str) -> Distribution:
    with open(filename, encoding="utf-8") as f:
        return load_dist(f.read())


def load_dist(yaml_str: str) -> Distribution:
    data = yaml.safe_load(yaml_str) or {}
    mapping = data.get("distribution", {})
    return Distribution({a: list(cs or []) for a, cs in mapping.items()})


def yaml_dist(dist: Distribution, inputs: Optional[Dict] = None,
              cost: Optional[float] = None) -> str:
    data: Dict[str, Any] = {}
    if inputs:
        data["inputs"] = inputs
    data["distribution"] = dist.mapping
    if cost is not None:
        data["cost"] = cost
    return yaml.safe_dump(data, sort_keys=False)

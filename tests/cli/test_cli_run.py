"""CLI tests for the dynamic-DCOP commands: run + replica_dist.

Mirrors the reference's CLI test strategy (subprocess + JSON results,
tests/dcop_cli/).
"""

import json
import os
import subprocess
import sys

from fixtures_paths import LOCAL_INSTANCES as INSTANCES
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def run_cli(args, timeout=120):
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli"] + args,
        timeout=timeout, env=ENV,
    )
    return json.loads(out)


def test_replica_dist_places_replicas():
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli",
         "replica_dist", "-a", "dsa", "-d", "adhoc", "-k", "2",
         os.path.join(INSTANCES,
                      "coloring_4agents_10vars.yaml")],
        timeout=120, env=ENV,
    ).decode()
    assert "replica_dist:" in out
    # Every variable computation must have 2 replicas.
    import yaml

    data = yaml.safe_load(out)
    mapping = data["replica_dist"]
    assert len(mapping) == 10
    for comp, hosts in mapping.items():
        assert len(hosts) == 2, f"{comp}: {hosts}"


def test_run_with_scenario_repairs():
    result = run_cli([
        "-t", "12",
        "run", "-a", "dsa", "-d", "adhoc", "-k", "2",
        "-s", os.path.join(INSTANCES, "scenario_remove_a1.yaml"),
        os.path.join(INSTANCES, "coloring_4agents_10vars.yaml"),
    ], timeout=180)
    assert result["status"] in ("FINISHED", "TIMEOUT")
    # All 10 variables still have a value despite a1's departure.
    assert len(result["assignment"]) == 10
    replication = result["replication"]
    assert replication["ktarget"] == 2
    # a1 hosted at least v1 (must_host hint): repair happened.
    assert replication["repaired"], "no computation was repaired"


def test_run_device_mode_scenario():
    """Device-path dynamic DCOP (VERDICT #7): scenario events against
    the warm-started device engine, with cost continuity asserted —
    an agent departure re-homes its computations in the placement map
    but cannot perturb the on-device trajectory."""
    result = run_cli([
        "-t", "60",
        "run", "-a", "maxsum", "-d", "adhoc", "-k", "2",
        "-m", "device", "-c", "500",
        "-s", os.path.join(INSTANCES, "scenario_remove_a1.yaml"),
        os.path.join(INSTANCES, "coloring_4agents_10vars.yaml"),
    ], timeout=240)
    assert result["backend"] == "device"
    assert len(result["assignment"]) == 10
    # The departed agent's computations were re-homed.
    assert result["replication"]["repaired"]
    assert "a1" not in result["replication"]["placement_agents"]
    # The warm-started engine kept its trajectory across the event:
    # the event snapshot carries a live cycle counter and the final run
    # continued past it without any recompile or state reset.
    assert result["events"]
    for ev in result["events"]:
        assert ev["cycle"] >= 1
        assert result["cycle"] > ev["cycle"]
    # No graph change happened, so the slack path never recompiled.
    assert result["recompiles"] == 0


def test_run_process_mode_scenario_repairs():
    """Dynamic DCOP over OS processes (reference run.py:387): scenario
    removes a1, repair migrates its computations, all over HTTP between
    spawned agent processes."""
    result = run_cli([
        "-t", "12",
        "run", "-a", "dsa", "-d", "adhoc", "-m", "process", "-k", "2",
        "-s", os.path.join(INSTANCES, "scenario_remove_a1.yaml"),
        os.path.join(INSTANCES, "coloring_4agents_10vars.yaml"),
    ], timeout=180)
    assert result["backend"] == "process"
    assert len(result["assignment"]) == 10
    assert result["replication"]["ktarget"] == 2
    assert result["replication"]["repaired"]

"""The DCOP problem container.

Reference parity: pydcop/dcop/dcop.py (DCOP :41, add_agents :207, merge
:154, solution_cost :308, filter_dcop :370).
"""

from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
)
from pydcop_tpu.dcop.relations import Constraint


class DCOP:
    """A DCOP: domains, variables, constraints, agents and an objective.

    >>> from pydcop_tpu.dcop.objects import Variable, Domain
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('colors', 'color', ['R', 'G'])
    >>> v1, v2 = Variable('v1', d), Variable('v2', d)
    >>> c = constraint_from_str('c1', '1 if v1 == v2 else 0', [v1, v2])
    >>> dcop = DCOP('test', objective='min')
    >>> dcop.add_constraint(c)
    >>> sorted(dcop.variables)
    ['v1', 'v2']
    """

    def __init__(self, name: str = "dcop", objective: str = "min",
                 description: str = "",
                 domains: Optional[Dict[str, Domain]] = None,
                 variables: Optional[Dict[str, Variable]] = None,
                 constraints: Optional[Dict[str, Constraint]] = None,
                 agents: Optional[Dict[str, AgentDef]] = None):
        if objective not in ("min", "max"):
            raise ValueError(f"Objective must be min or max, got {objective}")
        self.name = name
        self.description = description
        self.objective = objective
        self.domains: Dict[str, Domain] = dict(domains) if domains else {}
        self.variables: Dict[str, Variable] = (
            dict(variables) if variables else {}
        )
        self.external_variables: Dict[str, ExternalVariable] = {}
        self.constraints: Dict[str, Constraint] = (
            dict(constraints) if constraints else {}
        )
        self._agents_def: "OrderedDict[str, AgentDef]" = OrderedDict()
        if agents:
            for a in agents.values():
                self.add_agents(a)
        self.dist_hints = None

    # ------------------------------------------------------------------ #
    # Content management

    def add_domain(self, domain: Domain):
        self.domains[domain.name] = domain

    def add_variable(self, variable: Variable):
        self.variables[variable.name] = variable
        self.domains.setdefault(variable.domain.name, variable.domain)

    def add_external_variable(self, variable: ExternalVariable):
        self.external_variables[variable.name] = variable
        self.domains.setdefault(variable.domain.name, variable.domain)

    def add_constraint(self, constraint: Constraint):
        """Add a constraint; its variables/domains are auto-registered."""
        self.constraints[constraint.name] = constraint
        for v in constraint.dimensions:
            if isinstance(v, ExternalVariable):
                self.add_external_variable(v)
            else:
                self.add_variable(v)

    def add_agents(self, agents: Union[AgentDef, Iterable[AgentDef], Dict]):
        if isinstance(agents, AgentDef):
            agents = [agents]
        elif isinstance(agents, dict):
            agents = list(agents.values())
        for a in agents:
            self._agents_def[a.name] = a

    @property
    def agents(self) -> Dict[str, AgentDef]:
        return self._agents_def

    def agent(self, name: str) -> AgentDef:
        return self._agents_def[name]

    def variable(self, name: str) -> Variable:
        return self.variables[name]

    def get_external_variable(self, name: str) -> ExternalVariable:
        return self.external_variables[name]

    def constraint(self, name: str) -> Constraint:
        return self.constraints[name]

    def domain(self, name: str) -> Domain:
        return self.domains[name]

    @property
    def all_variables(self) -> List[Variable]:
        return list(self.variables.values())

    def __add__(self, other: "DCOP") -> "DCOP":
        """Merge two DCOPs (same objective required)."""
        if self.objective != other.objective:
            raise ValueError("Cannot merge DCOPs with different objectives")
        merged = DCOP(f"{self.name}+{other.name}", self.objective)
        for d in (self, other):
            merged.domains.update(d.domains)
            merged.variables.update(d.variables)
            merged.external_variables.update(d.external_variables)
            merged.constraints.update(d.constraints)
            for a in d._agents_def.values():
                merged._agents_def[a.name] = a
        return merged

    # ------------------------------------------------------------------ #
    # Evaluation

    def solution_cost(self, assignment: Dict[str, Any],
                      infinity: float = float("inf")) -> Tuple[float, int]:
        """(cost, violation-count) of a full assignment.

        A constraint evaluating to +/- `infinity` counts as a hard
        violation and contributes 0 to the cost (reference convention,
        dcop.py:308-369).
        """
        cost, violations = 0.0, 0
        full = dict(assignment)
        for ev in self.external_variables.values():
            full.setdefault(ev.name, ev.value)
        for v in self.variables.values():
            if v.name not in full:
                raise ValueError(
                    f"Missing variable {v.name} in assignment"
                )
            cost += v.cost_for_val(full[v.name])
        for c in self.constraints.values():
            c_cost = c(**{v.name: full[v.name] for v in c.dimensions})
            if abs(c_cost) == infinity:
                violations += 1
            else:
                cost += c_cost
        return cost, violations

    def initial_assignment(self) -> Dict[str, Any]:
        """Initial (or first-domain-value) assignment of all variables."""
        return {
            v.name: (v.initial_value if v.initial_value is not None
                     else v.domain[0])
            for v in self.variables.values()
        }


def filter_dcop(dcop: DCOP, accept_unary: bool = False) -> DCOP:
    """Drop variables that appear in no (non-unary) constraint.

    Reference parity: dcop.py:370 — used to clean generated problems.
    """
    used = set()
    for c in dcop.constraints.values():
        if c.arity > 1 or accept_unary:
            used.update(c.scope_names)
    filtered = DCOP(dcop.name, dcop.objective, dcop.description)
    filtered.domains = dict(dcop.domains)
    for name, v in dcop.variables.items():
        if name in used:
            filtered.add_variable(v)
    for ev in dcop.external_variables.values():
        filtered.add_external_variable(ev)
    for c in dcop.constraints.values():
        if c.arity > 1 or accept_unary:
            filtered.add_constraint(c)
    filtered.add_agents(dcop.agents)
    filtered.dist_hints = dcop.dist_hints
    return filtered

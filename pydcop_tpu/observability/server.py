"""Live telemetry endpoint: scrape a RUNNING solve.

Until now metrics only materialized as end-of-run files (JSONL
snapshots + a ``.prom`` dump) — useless for watching a long
``pydcop solve`` or orchestrator run while it runs.
:class:`TelemetryServer` is a stdlib-only (``http.server``) HTTP
endpoint over the process-wide observability state:

- ``GET /metrics`` — the metrics registry in Prometheus text
  exposition format (scrape it directly, no pushgateway);
- ``GET /healthz`` — a JSON health verdict sourced from the active
  :class:`~pydcop_tpu.resilience.health.HealthMonitor` when one is
  registered (``alive``/``suspect``/``dead`` statuses per agent;
  any dead agent turns the endpoint 503) and a plain ``ok`` when
  none is — orchestration probes work in both modes;
- ``GET /events`` — a Server-Sent-Events stream of cycle/cost
  snapshots pushed by whichever
  :class:`~pydcop_tpu.observability.metrics.CycleSnapshotter` the
  current run drives (the class-wide listener hook), with keepalive
  comments while the solve is between chunks;
- ``GET /profile`` — the live device-efficiency rollup
  (observability/efficiency.py): backend-honest attainment, request
  time-ledger breakdown, waste by cause, top structures by device
  time.

Lifecycle is owned by
:class:`~pydcop_tpu.observability.ObservabilitySession` (``api.solve
(serve_metrics=PORT)`` / ``pydcop solve --serve_metrics PORT``), but
the server is freestanding — tests and tools start one directly.
``port=0`` asks the OS for a free port (:attr:`port` reports the
assignment), which is what keeps parallel test runs collision-free.

The server thread and every connection handler are daemons: a wedged
scraper can never keep the solve process alive.
"""

import json
import logging
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("pydcop.observability.server")

# Process-wide health source: the thread-backend run loop registers its
# HealthMonitor summary here for the duration of the run (see
# infrastructure/run.solve_with_agents); /healthz falls back to a plain
# "ok" when nothing is registered.
_health_provider: Optional[Callable[[], Dict[str, Any]]] = None
_health_lock = threading.Lock()


def set_health_provider(fn: Optional[Callable[[], Dict[str, Any]]]):
    """Register (or clear, with ``None``) the process-wide health
    source consumed by ``/healthz``."""
    global _health_provider
    with _health_lock:
        _health_provider = fn


def get_health_provider() -> Optional[Callable[[], Dict[str, Any]]]:
    with _health_lock:
        return _health_provider


def _probe_diagnostics() -> Optional[Dict[str, Any]]:
    """Accelerator-probe failure root cause for the /healthz body.

    The bench/CLI backend guards record every probe outcome via
    ``utils.cleanenv.record_diag`` — until now that evidence was
    bench-log-only, so an operator watching a CPU-fallback service
    had no way to see WHY the accelerator was skipped.  Returns None
    when no probe ever failed (the common healthy case keeps the
    body small); failures never flip the health status — a CPU
    fallback still serves correctly, the body just says what
    happened."""
    try:
        from pydcop_tpu.utils.cleanenv import (
            diag_events,
            is_probe_failure,
        )
    except Exception:  # noqa: BLE001 — probe must answer
        return None
    failures = [e for e in diag_events() if is_probe_failure(e)]
    if not failures:
        return None
    last = failures[-1]
    return {
        "failures": len(failures),
        "last_event": last.get("event"),
        "last_error": last.get("error"),
        "last_unix": last.get("unix"),
        "recent": failures[-5:],
    }


def health_verdict() -> Dict[str, Any]:
    """The /healthz body: provider data + an overall ``status`` rolled
    up from per-agent statuses (any dead -> ``failing``, any suspect
    -> ``degraded``, else ``ok``), plus the accelerator-probe failure
    root cause when any probe failed (``accelerator_probe`` key —
    informational, never changes the status).  Provider failures
    report ``unknown`` rather than crashing the probe."""
    provider = get_health_provider()
    if provider is None:
        data = {"status": "ok", "detail": "no health monitor active"}
    else:
        try:
            data = dict(provider())
        except Exception as exc:  # noqa: BLE001 — probe must answer
            data = {"status": "unknown",
                    "detail": f"health provider failed: {exc}"}
        else:
            statuses = data.get("statuses", {})
            if any(s == "dead" for s in statuses.values()):
                status = "failing"
            elif any(s == "suspect" for s in statuses.values()):
                status = "degraded"
            else:
                status = "ok"
            data.setdefault("status", status)
    probe = _probe_diagnostics()
    if probe is not None:
        data.setdefault("accelerator_probe", probe)
    return data


class _Handler(BaseHTTPRequestHandler):
    # Set per-server via the factory in TelemetryServer.start().
    telemetry: "TelemetryServer"

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: N802 — stdlib name
        logger.debug("telemetry %s", fmt % args)

    def _reply(self, code: int, body: bytes, content_type: str,
               close: bool = False):
        """``close=True`` advertises Connection: close (and makes the
        server honor it) — required on error replies sent WITHOUT
        reading a request body, or the unread bytes corrupt the next
        keep-alive request on the socket."""
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if close:
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            # Content negotiation per the Prometheus convention: the
            # classic v0.0.4 text parser errors on exemplar suffixes,
            # so they only ride when the scraper explicitly Accepts
            # the OpenMetrics dialect (Prometheus does exactly this
            # when exemplar storage is enabled).
            openmetrics = ("application/openmetrics-text"
                           in self.headers.get("Accept", ""))
            body = self.telemetry.registry.to_prometheus(
                openmetrics=openmetrics).encode()
            self._reply(
                200, body,
                "application/openmetrics-text; version=1.0.0; "
                "charset=utf-8" if openmetrics
                else "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/metrics.json":
            # The registry snapshot as JSON: the machine-mergeable
            # form the fleet router's GET /fleet/metrics aggregator
            # pulls from every replica (text exposition round-trips
            # lossily; the snapshot keeps kinds and histogram
            # structure intact).
            self._reply(200,
                        json.dumps(self.telemetry.registry.snapshot(),
                                   default=str).encode(),
                        "application/json")
        elif path == "/healthz":
            verdict = health_verdict()
            code = 503 if verdict.get("status") == "failing" else 200
            self._reply(code, json.dumps(verdict).encode(),
                        "application/json")
        elif path == "/profile":
            # The live efficiency rollup (ISSUE 14): backend-honest
            # attainment, the request-ledger where-the-time-went
            # breakdown, waste by cause, top structures by device
            # time.  ``pydcop profile report --url`` renders it.
            from pydcop_tpu.observability.efficiency import tracker

            self._reply(200,
                        json.dumps(tracker.rollup(),
                                   default=str).encode(),
                        "application/json")
        elif path == "/events":
            self._stream_events()
        elif path == "/debug/bundle":
            self._debug_bundle()
        else:
            self._reply(404, b'{"error": "unknown path"}',
                        "application/json")

    def _debug_bundle(self):
        """Cut an on-demand postmortem bundle: written to the
        recorder's bundle dir AND returned in the response (the
        ``pydcop debug bundle`` client saves it locally) — the
        operator gets the evidence even when the server host's disk
        is not reachable."""
        from pydcop_tpu.observability.flight import get_flight

        recorder = get_flight()
        if recorder is None:
            self._reply(503,
                        b'{"error": "flight recorder disabled '
                        b'(PYDCOP_FLIGHT_RECORDER=0)"}',
                        "application/json")
            return
        try:
            doc = recorder.make_bundle("on_demand", {"via": "http"})
            doc["path"] = recorder.write_bundle(doc)
        except Exception as exc:  # noqa: BLE001 — probe must answer
            self._reply(500, json.dumps(
                {"error": f"bundle failed: {exc}"}).encode(),
                "application/json")
            return
        self._reply(200, json.dumps(doc, default=str).encode(),
                    "application/json")

    def _stream_events(self):
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        # SSE is an unbounded body: no Content-Length, close delimits.
        self.send_header("Connection", "close")
        self.end_headers()
        q = self.telemetry._subscribe()
        try:
            # Replay the latest snapshot so a client connecting between
            # chunks sees state immediately, not on the next boundary.
            last = self.telemetry.last_event
            if last is not None:
                self._write_event(last)
            while not self.telemetry._stopping.is_set():
                try:
                    event = q.get(timeout=1.0)
                except queue.Empty:
                    self.wfile.write(b": keepalive\n\n")
                    self.wfile.flush()
                    continue
                self._write_event(event)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away — normal SSE termination
        finally:
            self.telemetry._unsubscribe(q)

    def _write_event(self, event: Dict[str, Any]):
        payload = json.dumps(event, default=str).encode()
        self.wfile.write(b"data: " + payload + b"\n\n")
        self.wfile.flush()


class TelemetryServer:
    """Serve /metrics, /healthz and /events for the process-wide
    observability state.  ``start()`` binds (``port=0`` = OS-assigned,
    see :attr:`port`) and serves from a daemon thread; ``stop()``
    shuts down and unhooks the snapshot listener.

    Subclasses mount extra routes by overriding :attr:`handler_class`
    with a ``_Handler`` subclass (the serving front end,
    serving/http.py, adds ``POST /solve`` / ``GET /result`` this
    way and keeps /metrics, /healthz and /events mounted alongside).
    """

    handler_class = _Handler

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry=None):
        from pydcop_tpu.observability.metrics import (
            registry as default_registry,
        )

        self.host = host
        self._requested_port = port
        self.registry = (registry if registry is not None
                         else default_registry)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._subscribers: List[queue.Queue] = []
        self._sub_lock = threading.Lock()
        self.last_event: Optional[Dict[str, Any]] = None

    # -- snapshot fan-out ---------------------------------------------- #

    def _subscribe(self) -> queue.Queue:
        q: queue.Queue = queue.Queue(maxsize=256)
        with self._sub_lock:
            self._subscribers.append(q)
        return q

    def _unsubscribe(self, q: queue.Queue):
        with self._sub_lock:
            if q in self._subscribers:
                self._subscribers.remove(q)

    def _on_snapshot(self, event: Dict[str, Any]):
        # One-off request-lifecycle events fan out live but must not
        # occupy the replay slot: a client connecting mid-run is
        # promised "the latest snapshot" (cycle/cost state), not the
        # terminal phase of some unrelated already-finished request.
        if event.get("event") != "request":
            self.last_event = event
        with self._sub_lock:
            subscribers = list(self._subscribers)
        for q in subscribers:
            try:
                q.put_nowait(event)
            except queue.Full:
                # Slow consumer: drop the oldest so the stream stays
                # current instead of stalling the producer.
                try:
                    q.get_nowait()
                    q.put_nowait(event)
                except (queue.Empty, queue.Full):
                    pass

    # -- lifecycle ----------------------------------------------------- #

    @property
    def port(self) -> Optional[int]:
        if self._httpd is None:
            return None
        return self._httpd.server_address[1]

    @property
    def url(self) -> Optional[str]:
        port = self.port
        return None if port is None else f"http://{self.host}:{port}"

    def start(self) -> "TelemetryServer":
        from pydcop_tpu.observability.metrics import CycleSnapshotter

        if self._httpd is not None:
            return self
        handler = type("BoundHandler", (self.handler_class,),
                       {"telemetry": self})
        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), handler)
        self._httpd.daemon_threads = True
        self._stopping.clear()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="pydcop-telemetry", daemon=True)
        self._thread.start()
        CycleSnapshotter.add_global_listener(self._on_snapshot)
        logger.info("telemetry server listening on %s", self.url)
        return self

    def stop(self):
        from pydcop_tpu.observability.metrics import CycleSnapshotter

        if self._httpd is None:
            return
        CycleSnapshotter.remove_global_listener(self._on_snapshot)
        self._stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = None
        self._thread = None

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

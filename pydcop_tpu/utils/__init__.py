"""Utility layer: serialization mixin, expression compiler, graph helpers.

Reference parity: pydcop/utils/ (simple_repr.py, expressionfunction.py,
graphs.py, various.py).
"""

"""A-MaxSum: asynchronous MaxSum.

Reference parity: pydcop/algorithms/amaxsum.py (:108-424) — same message
semantics as maxsum (it reuses maxsum's factor_costs_for_var /
costs_for_factor) but handlers fire per message instead of per BSP round,
and paused computations re-send start messages on resume (dynamic DCOP
support, :165-180).

Device path: on the batched engine, asynchrony has no performance
meaning — every message row updates each superstep, which corresponds to
the "fully fired" schedule of the asynchronous execution.  Solution
quality is equivalent (damping still applies); the asynchronous
*schedule* itself is only observable in agent mode, where the
infrastructure computations implement true per-message firing.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'amaxsum', max_cycles=50)
    >>> round(res['cost'], 3)
    0.0
"""

from typing import Optional

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms import maxsum as _maxsum
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.runner import DeviceRunResult

GRAPH_TYPE = "factor_graph"

algo_params = list(_maxsum.algo_params)

computation_memory = _maxsum.computation_memory
communication_load = _maxsum.communication_load


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("amaxsum", comp_def)


# Same engine as maxsum on the device path (asynchrony is an
# agent-mode schedule, not a kernel), so partitioned sharding
# (shards=) comes for free through the shared engine builder.
SUPPORTS_SHARDS = True


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    shards: Optional[int] = None,
                    stop_on_convergence: bool = True,
                    warmup: bool = False, **_) -> DeviceRunResult:
    return _maxsum.solve_on_device(
        dcop, algo_def, max_cycles=max_cycles, mesh=mesh,
        n_devices=n_devices, shards=shards,
        stop_on_convergence=stop_on_convergence,
        warmup=warmup,
    )

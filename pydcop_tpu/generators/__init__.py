"""Benchmark problem generators.

Reference parity: pydcop/commands/generators/ (graphcoloring.py,
ising.py, meetingscheduling.py, secp.py, agents.py, iot.py, scenario.py,
smallworld.py — CLI glue in commands/generate.py).

All generators here accept an explicit ``seed`` (the reference uses the
unseeded global ``random`` module; deterministic generation is required
for reproducible benchmarks and CPU/TPU parity runs).
"""

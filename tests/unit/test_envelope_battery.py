"""Battery for heterogeneous-structure envelope batching (ISSUE 11):
envelope-key ladder properties (covering, monotone), mask-padding
bit-identity against solo dispatches across topologies / arities /
domains, lane-packed disjoint unions (values, honest per-member
convergence), pad-accounting honesty (``envelope_waste`` sums), the
pack-vs-solo cost model and its portfolio-cache prior replay, the
scheduler's flush planning, the ``normalize_params`` ``prune=-1``
fall-through regression, and the ``serve_mixed`` sentinel family."""

import json

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    constraint_from_str,
)
from pydcop_tpu.engine import batch as engine_batch
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.ops import maxsum_lane as lane_ops
from pydcop_tpu.serving import binning
from pydcop_tpu.serving.service import SolveService

MAX_CYCLES = 40
PARAMS = {"max_cycles": MAX_CYCLES}


def _ring(n: int, d: int, seed: int, chords: int = 0) -> DCOP:
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP(f"ring{n}_{d}_{seed}_{chords}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n) for i in range(n)]
    edges += [(i, (i + n // 2) % n) for i in range(chords)]
    for k, (i, j) in enumerate(edges):
        table = rng.integers(0, 10, size=(d, d)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _mixed_arity(n: int, seed: int) -> DCOP:
    """Unary + binary + ternary factors — exercises multi-bucket
    envelope padding."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"mix{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n):
        i, j = k, (k + 1) % n
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], table, f"b{k}"))
    for k in range(0, n, 3):
        dcop.add_constraint(constraint_from_str(
            f"u{k}", f"v{k} * {1 + k % 3}", [vs[k]]))
    for k in range(0, n - 2, 4):
        dcop.add_constraint(constraint_from_str(
            f"t{k}", f"v{k} + v{k + 1} * v{k + 2}",
            [vs[k], vs[k + 1], vs[k + 2]]))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _graph(dcop):
    return compile_dcop(dcop, noise_level=0.01)[0]


def _solo_values(graph, max_cycles=MAX_CYCLES):
    values, _cycles, _res = engine_batch.run_stacked(
        [graph], max_cycles=max_cycles)
    return values[0]


def _covering_envelope(graphs, ladder=binning.DEFAULT_LADDER):
    envs = [binning.envelope_key(g, ladder) for g in graphs]
    arities = sorted({a for e in envs for a, _ in e.rows})
    rows = tuple(
        (a, max(dict(e.rows).get(a, ladder.rows[0]) for e in envs))
        for a in arities
    )
    return binning.Envelope(
        v_env=max(e.v_env for e in envs),
        d_env=max(e.d_env for e in envs),
        rows=rows,
    )


# ------------------------------------------------------------------ #
# envelope keys and the ladder


class TestEnvelopeKey:
    def test_envelope_covers_graph(self):
        for dcop in (_ring(9, 3, 0), _ring(23, 5, 1, chords=4),
                     _mixed_arity(12, 2)):
            g = _graph(dcop)
            env = binning.envelope_key(g)
            assert env.v_env >= g.n_vars
            assert env.d_env >= g.dmax
            rows = dict(env.rows)
            assert set(rows) == {b.arity for b in g.buckets}
            for b in g.buckets:
                assert rows[b.arity] >= b.n_factors

    def test_ladder_monotone(self):
        """A graph that grows in any dimension never gets a SMALLER
        envelope — the property that makes the key a proper tier."""
        sizes = [6, 9, 14, 22, 35, 70, 140]
        envs = [binning.envelope_key(_graph(_ring(n, 3, 0)))
                for n in sizes]
        for small, big in zip(envs, envs[1:]):
            assert big.v_env >= small.v_env
            assert big.d_env >= small.d_env
            assert dict(big.rows)[2] >= dict(small.rows)[2]

    def test_nearby_sizes_share_an_envelope(self):
        """The point of the tier: different structures with nearby
        shapes land on the SAME envelope (they'd never share a bin)."""
        g1, g2 = _graph(_ring(12, 3, 0)), _graph(_ring(15, 3, 1))
        assert binning.structure_signature(g1) != \
            binning.structure_signature(g2)
        assert binning.envelope_key(g1) == binning.envelope_key(g2)

    def test_ladder_round_past_top_rung(self):
        assert binning.ladder_round(5000, (8, 16)) == 8192

    def test_cells_accounting(self):
        g = _graph(_ring(10, 3, 0))
        # var table (11 rows incl. sentinel) * 3 + 10 binary factors
        # * 9.
        assert binning.graph_cells(g) == 11 * 3 + 10 * 9
        env = binning.Envelope(16, 4, ((2, 16),))
        assert binning.envelope_cells(env) == 17 * 4 + 16 * 16
        assert binning.lane_cells(g, 4) == 11 * 4 + 10 * 16


# ------------------------------------------------------------------ #
# mask-padding bit-identity


class TestEnvelopePadding:
    def test_padded_stack_bit_identical_across_topologies(self):
        """The tentpole claim: different-structure graphs padded to
        one envelope and dispatched together produce BIT-IDENTICAL
        per-instance values to their solo dispatches."""
        dcops = [_ring(12, 3, 0), _ring(9, 3, 1),
                 _ring(17, 4, 2, chords=3), _ring(25, 3, 3)]
        graphs = [_graph(d) for d in dcops]
        env = _covering_envelope(graphs)
        values, cycles, res = engine_batch.run_stacked(
            graphs, max_cycles=MAX_CYCLES, envelope=env)
        for i, g in enumerate(graphs):
            solo = _solo_values(g)
            assert np.array_equal(values[i][:g.n_vars],
                                  solo[:g.n_vars]), f"lane {i}"
        assert res.metrics["packing"] == "envelope"

    def test_padded_stack_bit_identical_mixed_arities(self):
        graphs = [_graph(_mixed_arity(9, 0)),
                  _graph(_mixed_arity(13, 1))]
        env = _covering_envelope(graphs)
        values, _cycles, _res = engine_batch.run_stacked(
            graphs, max_cycles=MAX_CYCLES, envelope=env)
        for i, g in enumerate(graphs):
            solo = _solo_values(g)
            assert np.array_equal(values[i][:g.n_vars],
                                  solo[:g.n_vars])

    def test_padded_stack_bit_identical_mixed_domains(self):
        """Domain padding regression: a d=2 instance padded into a
        d=5 envelope must keep its exact solo answer (BIG-masked
        slots must never win a reduction or shift the
        normalization)."""
        graphs = [_graph(_ring(10, 2, 0)), _graph(_ring(14, 5, 1))]
        env = _covering_envelope(graphs)
        assert env.d_env >= 5
        values, _cycles, _res = engine_batch.run_stacked(
            graphs, max_cycles=MAX_CYCLES, envelope=env)
        for i, g in enumerate(graphs):
            assert np.array_equal(values[i][:g.n_vars],
                                  _solo_values(g)[:g.n_vars])

    def test_exact_fit_returns_same_graph(self):
        g = _graph(_ring(10, 3, 0))
        env = binning.Envelope(
            v_env=g.n_vars, d_env=g.dmax,
            rows=tuple((b.arity, b.n_factors) for b in g.buckets))
        assert engine_batch.pad_graph_to_envelope(g, env) is g

    def test_exact_fit_drops_aggregation_arrays(self):
        """Even an exact-fit member must honor the drop-agg contract:
        stacked next to padded members (agg fields None) the pytrees
        must match, and agg shapes like ell's [V+1, K] are not
        envelope-determined."""
        from pydcop_tpu.engine.autotune import apply_aggregation

        g = apply_aggregation(_graph(_ring(10, 3, 0)), "ell")
        assert g.agg_ell is not None
        env = binning.Envelope(
            v_env=g.n_vars, d_env=g.dmax,
            rows=tuple((b.arity, b.n_factors) for b in g.buckets))
        padded = engine_batch.pad_graph_to_envelope(g, env)
        assert padded is not g
        assert padded.agg_ell is None and padded.agg_perm is None
        assert padded.var_costs is g.var_costs

    def test_envelope_must_cover(self):
        g = _graph(_ring(10, 3, 0))
        with pytest.raises(ValueError, match="does not cover"):
            engine_batch.pad_graph_to_envelope(
                g, binning.Envelope(4, 3, ((2, 16),)))
        with pytest.raises(ValueError, match="arities"):
            engine_batch.pad_graph_to_envelope(
                g, binning.Envelope(16, 3, ((3, 16),)))
        with pytest.raises(ValueError, match="rows"):
            engine_batch.pad_graph_to_envelope(
                g, binning.Envelope(16, 3, ((2, 4),)))

    def test_sentinel_remap(self):
        """A graph compiled with pad_to>1 has bucket rows pointing at
        ITS sentinel; envelope padding must re-point them at the
        envelope's sentinel, not leave them aimed at a now-real row."""
        g = compile_dcop(_ring(10, 3, 0), noise_level=0.01,
                         pad_to=8)[0]
        assert (np.asarray(g.buckets[0].var_ids) == g.n_vars).any()
        env = binning.Envelope(16, 4, ((2, 32),))
        padded = engine_batch.pad_graph_to_envelope(g, env)
        ids = np.asarray(padded.buckets[0].var_ids)
        assert not (ids == g.n_vars).any()
        assert (ids == 16).any()
        assert np.array_equal(
            engine_batch.run_stacked(
                [padded], max_cycles=MAX_CYCLES)[0][0][:g.n_vars],
            _solo_values(g)[:g.n_vars])

    def test_pad_accounting_honest(self):
        """``envelope_waste`` honesty: per-lane waste must equal
        1 - real_cells/envelope_cells exactly, and the dispatch-level
        figure must be their mean."""
        graphs = [_graph(_ring(12, 3, 0)), _graph(_ring(20, 3, 1))]
        env = _covering_envelope(graphs)
        _values, _cycles, res = engine_batch.run_stacked(
            graphs, max_cycles=MAX_CYCLES, envelope=env)
        lanes = res.metrics["envelope_waste_lanes"]
        env_cells = binning.envelope_cells(env)
        for g, waste in zip(graphs, lanes):
            expected = 1.0 - binning.graph_cells(g) / env_cells
            assert waste == pytest.approx(expected, abs=1e-4)
        assert res.metrics["envelope_waste"] == pytest.approx(
            sum(lanes) / len(lanes), abs=1e-4)


# ------------------------------------------------------------------ #
# lane-packed disjoint unions


class TestLanePacking:
    def test_lane_pack_bit_identical(self):
        dcops = [_ring(12, 3, 0), _ring(9, 3, 1), _ring(21, 3, 2),
                 _ring(15, 4, 3, chords=2)]
        graphs = [_graph(d) for d in dcops]
        values, cycles, res = engine_batch.run_lane_packed(
            graphs, max_cycles=MAX_CYCLES,
            ladder=binning.UNION_LADDER)
        for i, g in enumerate(graphs):
            assert np.array_equal(values[i],
                                  _solo_values(g)[:g.n_vars]), i
        assert res.metrics["packing"] == "lane"
        assert (cycles == MAX_CYCLES).all()

    def test_lane_pack_heterogeneous_arity_sets(self):
        """The union accepts members with entirely different arity
        sets — a binary-only ring next to a unary+binary+ternary
        graph."""
        graphs = [_graph(_ring(10, 3, 0)), _graph(_mixed_arity(9, 1))]
        values, _cycles, _res = engine_batch.run_lane_packed(
            graphs, max_cycles=MAX_CYCLES)
        for i, g in enumerate(graphs):
            assert np.array_equal(values[i],
                                  _solo_values(g)[:g.n_vars])

    def test_lane_converged_flags_match_solo(self):
        """Honest per-member convergence: the flags recovered from
        the union's suppression counters must equal each member's
        solo verdict — including a mixed converged/not-converged
        batch."""
        fast = _graph(_ring(6, 3, 0))         # converges quickly
        slow = _graph(_ring(30, 3, 1, chords=10))
        for budget in (4, MAX_CYCLES):
            solos = [
                engine_batch.run_stacked(
                    [g], max_cycles=budget)[2]
                .metrics["converged_lanes"][0]
                for g in (fast, slow)
            ]
            _v, _c, res = engine_batch.run_lane_packed(
                [fast, slow], max_cycles=budget)
            assert res.metrics["converged_lanes"] == solos, budget

    def test_pack_graphs_layout(self):
        graphs = [_graph(_ring(8, 3, 0)), _graph(_ring(11, 3, 1))]
        union, layout = lane_ops.pack_graphs(graphs)
        assert union.n_vars == 19
        assert layout.var_slices == ((0, 8), (8, 11))
        ids = np.asarray(union.buckets[0].var_ids)
        # Second member's rows reference offset indices only.
        for bi, start, n_rows in layout.row_slices[1]:
            block = ids[start:start + n_rows]
            real = block[block != union.n_vars]
            assert (real >= 8).all()


# ------------------------------------------------------------------ #
# the pack-vs-solo cost model


class TestPackDecision:
    def test_big_group_packs_small_pair_of_tiny_does_not(self):
        cells = 150  # tiny ring
        prior = binning.modeled_solve_ms(cells, MAX_CYCLES)
        pair = binning.pack_decision(
            [cells] * 2, [prior] * 2,
            packed_cells_total=binning.envelope_cells(
                binning.Envelope(256, 8, ((2, 256),))),
            max_cycles=MAX_CYCLES)
        assert not pair["packed"]  # giant envelope for two tiny rings
        group = binning.pack_decision(
            [cells] * 8, [prior] * 8,
            packed_cells_total=8 * cells + 200,
            max_cycles=MAX_CYCLES)
        assert group["packed"]

    def test_singleton_never_packs(self):
        d = binning.pack_decision(
            [100], [1.0], packed_cells_total=100,
            max_cycles=MAX_CYCLES)
        assert not d["packed"]

    def test_waste_reported(self):
        d = binning.pack_decision(
            [100, 100], [1.0, 1.0], packed_cells_total=400,
            max_cycles=MAX_CYCLES)
        assert d["waste"] == pytest.approx(0.5)

    def test_lane_union_cells_matches_run(self):
        """The decision model's union-cell prediction must equal what
        run_lane_packed actually builds (same ladder rounding)."""
        graphs = [_graph(_ring(12, 3, 0)), _graph(_ring(19, 3, 1))]
        predicted = binning.lane_union_cells(
            graphs, 3, binning.UNION_LADDER)
        union, _ = lane_ops.pack_graphs(graphs, d_env=3)
        padded = engine_batch.pad_graph_to_envelope(
            union,
            binning.envelope_key(
                union, binning.UNION_LADDER)._replace(
                    d_env=union.dmax))
        actual = padded.var_costs.size + sum(
            b.costs.size for b in padded.buckets)
        assert predicted == actual

    def test_portfolio_prior_replayed(self, tmp_path, monkeypatch):
        """Scheduler decision replay from the portfolio cache: a
        persisted PR-10 race time for a structure becomes that
        structure's solo prior (source 'portfolio'), scaled to the
        request's cycle budget — zero measurement on the serving
        path."""
        from pydcop_tpu.engine.autotune import (
            PORTFOLIO_RACE_CYCLES,
            cached_portfolio_timing_ms,
            graph_shape_key,
            portfolio_key,
        )

        g = _graph(_ring(12, 3, 0))
        key = portfolio_key(graph_shape_key(g))
        cache = tmp_path / "autotune.json"
        cache.write_text(json.dumps({key: {
            "algo": "maxsum_prune",
            "portfolio_timings_ms": {"maxsum": 9.0,
                                     "maxsum_prune": 6.0},
            "backend": "cpu",
        }}))
        monkeypatch.setenv("PYDCOP_AGG_AUTOTUNE_CACHE", str(cache))
        assert cached_portfolio_timing_ms(key) == 6.0
        ms, source = binning.solve_prior_ms(
            binning.graph_cells(g), MAX_CYCLES,
            cached_portfolio_timing_ms(key),
            race_cycles=PORTFOLIO_RACE_CYCLES)
        assert source == "portfolio"
        assert ms == pytest.approx(
            6.0 * MAX_CYCLES / PORTFOLIO_RACE_CYCLES)
        # End-to-end: the service's decision record says so too.
        svc = SolveService(batch_window_s=0.2, envelope_packing=True)
        svc.start()
        try:
            ids = [svc.submit(_ring(12, 3, 7), params=PARAMS),
                   svc.submit(_ring(15, 3, 8), params=PARAMS)]
            for rid in ids:
                assert svc.result(rid, wait=60)["status"] == \
                    "FINISHED"
            decisions = list(svc.envelope_decisions)
        finally:
            svc.stop(drain=False)
        assert decisions, "no pack decision recorded"
        assert "portfolio" in decisions[-1]["prior_sources"]

    def test_invalid_portfolio_cache_ignored(self, tmp_path,
                                             monkeypatch):
        from pydcop_tpu.engine.autotune import (
            cached_portfolio_timing_ms,
        )

        cache = tmp_path / "autotune.json"
        cache.write_text(json.dumps({"k": {"algo": "bogus"}}))
        monkeypatch.setenv("PYDCOP_AGG_AUTOTUNE_CACHE", str(cache))
        assert cached_portfolio_timing_ms("k") is None


# ------------------------------------------------------------------ #
# flush planning + service end-to-end


class TestFlushPlanning:
    def _reqs(self, svc, dcops):
        """Submit without a running scheduler: start() then stop the
        scheduler thread is heavyweight here, so build the request
        objects through the service's own compile path."""
        svc.start()
        reqs = []
        try:
            for d in dcops:
                rid = svc.submit(d, params=PARAMS)
                with svc._lock:
                    reqs.append(svc._requests[rid])
        finally:
            svc.stop(drain=False)
        return reqs

    def test_multi_bins_stay_exact(self):
        svc = SolveService(envelope_packing=True)
        reqs = self._reqs(svc, [_ring(10, 3, s) for s in range(3)])
        bins = {reqs[0].bin: reqs}
        plans = svc.plan_flush(bins)
        assert len(plans) == 1
        assert plans[0].envelope is None and plans[0].lane_d is None

    def test_singletons_group_and_pack(self):
        svc = SolveService(envelope_packing=True)
        dcops = [_ring(n, 3, n) for n in (9, 12, 15, 18, 21, 24)]
        reqs = self._reqs(svc, dcops)
        bins = {r.bin: [r] for r in reqs}
        plans = svc.plan_flush(bins)
        packed = [p for p in plans if p.lane_d or p.envelope]
        assert len(packed) == 1
        assert len(packed[0].reqs) == len(dcops)
        assert packed[0].lane_d == 3  # tiny domain routes lane
        assert list(svc.envelope_decisions)[-1]["packed"]

    def test_groups_chunk_at_max_batch(self):
        """The cost model must price the dispatches that actually
        execute: a group past max_batch splits into chunks BEFORE the
        decision, one verdict per chunk, and no plan ever exceeds the
        dispatch cap."""
        svc = SolveService(envelope_packing=True, max_batch=4)
        dcops = [_ring(8 + 2 * i, 3, i) for i in range(6)]
        reqs = self._reqs(svc, dcops)
        # The live scheduler recorded decisions while _reqs drained;
        # count only this explicit flush's.
        svc.envelope_decisions.clear()
        plans = svc.plan_flush({r.bin: [r] for r in reqs})
        assert all(len(p.reqs) <= 4 for p in plans)
        assert sum(len(p.reqs) for p in plans) == 6
        # Two multi-request chunks (4 + 2) -> two recorded decisions.
        assert len(list(svc.envelope_decisions)) == 2

    def test_prune_routes_off_the_lane_path(self):
        """prune is an edge-major-only kernel: pruned singletons must
        take the stacked-envelope route, never the lane union."""
        svc = SolveService(envelope_packing=True)
        dcops = [_ring(n, 3, n) for n in (9, 12, 15, 18)]
        svc.start()
        reqs = []
        try:
            for d in dcops:
                rid = svc.submit(d, params={"max_cycles": MAX_CYCLES,
                                            "prune": 1})
                with svc._lock:
                    reqs.append(svc._requests[rid])
        finally:
            svc.stop(drain=False)
        plans = svc.plan_flush({r.bin: [r] for r in reqs})
        assert all(p.lane_d is None for p in plans)

    def test_envelope_packing_off_dispatches_solo(self):
        svc = SolveService(envelope_packing=False)
        reqs = self._reqs(svc, [_ring(n, 3, n) for n in (9, 12, 15)])
        plans = svc.plan_flush({r.bin: [r] for r in reqs})
        assert len(plans) == 3
        assert all(p.envelope is None and p.lane_d is None
                   for p in plans)
        assert not svc.envelope_decisions

    def test_losing_group_falls_back_to_solo(self):
        """A group the cost model prices out must dispatch solo —
        packing is an optimization, never a forced path."""
        svc = SolveService(envelope_packing=True,
                           envelope_overhead_ms=0.0)
        reqs = self._reqs(svc, [_ring(n, 3, n) for n in (6, 7)])
        plans = svc.plan_flush({r.bin: [r] for r in reqs})
        assert len(plans) == 2
        decision = list(svc.envelope_decisions)[-1]
        assert not decision["packed"]

    def test_end_to_end_mixed_structures(self):
        """Through the real scheduler: distinct structures complete
        in fewer dispatches than requests, every answer equals the
        solo api.solve answer, and the per-request batch accounting
        says how it was packed."""
        from pydcop_tpu import api

        dcops = [_ring(n, 3, 100 + n) for n in (9, 11, 14, 17, 20)]
        svc = SolveService(batch_window_s=0.25).start()
        try:
            ids = [svc.submit(d, params=PARAMS) for d in dcops]
            results = [svc.result(i, wait=60) for i in ids]
            stats = svc.stats()
        finally:
            svc.stop(drain=False)
        assert all(r["status"] == "FINISHED" for r in results)
        assert stats["dispatches"] < len(dcops)
        assert stats["envelope_dispatches"] >= 1
        assert stats["envelope_packed_requests"] >= 2
        for dcop, res in zip(dcops, results):
            solo = api.solve(dcop, "maxsum", backend="device",
                             max_cycles=MAX_CYCLES)
            assert res["assignment"] == solo["assignment"]
            assert res["cost"] == solo["cost"]
            assert res["batch"]["packing"] in ("envelope", "lane",
                                               "structure")


# ------------------------------------------------------------------ #
# satellites: normalize_params prune fall-through + sentinel family


class TestParamValidation:
    def test_prune_minus_one_rejected(self):
        """Regression: an out-of-range int must 400 (ValueError), not
        fall through into the bin key."""
        with pytest.raises(ValueError, match="prune"):
            binning.normalize_params({"prune": -1})

    def test_prune_unparseable_rejected(self):
        with pytest.raises(ValueError, match="prune"):
            binning.normalize_params({"prune": "sometimes"})
        with pytest.raises(ValueError, match="prune"):
            binning.normalize_params({"prune": 7})

    def test_prune_valid_values_pass(self):
        assert binning.normalize_params({"prune": 1})["prune"] == 1
        assert binning.normalize_params(
            {"prune": "auto"})["prune"] == "auto"


class TestSentinelServeMixedFamily:
    def _write_round(self, root, idx, mixed):
        doc = {"n": idx, "parsed": {
            "value": 800.0, "backend": "cpu",
            "serve_mixed_problems_per_sec": mixed,
        }}
        (root / f"BENCH_r{idx:02d}.json").write_text(json.dumps(doc))

    def test_serve_mixed_series_judged(self, tmp_path):
        import sys

        sys.path.insert(0, "tools")
        try:
            import bench_sentinel
        finally:
            sys.path.pop(0)
        for i, v in enumerate([200.0, 210.0, 190.0], start=1):
            self._write_round(tmp_path, i, v)
        ok = bench_sentinel.run_check(str(tmp_path))
        assert "serve_mixed:cpu" in ok["series"]
        assert ok["series"]["serve_mixed:cpu"]["verdict"] == "ok"
        assert not ok["failed"]
        # A collapsed newest round regresses the family.
        self._write_round(tmp_path, 4, 60.0)
        bad = bench_sentinel.run_check(str(tmp_path))
        assert bad["series"]["serve_mixed:cpu"]["verdict"] == \
            "regressed"
        assert bad["failed"]

"""Fleet trace plane (ISSUE 20): wire-propagated trace context +
lossy span shipping + the router-side merged-trace collector.

The reference pyDCOP streams every agent's cycle/metric records to a
collector (``pydcop solve --collect_on``); our fleet had the same
blind spot at the process boundary — spans stopped at each replica
and ``pydcop trace merge`` was an offline manual step.  This module
closes the loop in three pieces:

- :class:`TraceContext` / :data:`HEADER`: the one wire field
  (``X-Pydcop-Trace: <trace_id>[;parent=<span_id>]``) the router
  stamps onto every forwarded submit, session event batch, epoch
  fence, migration call and retry attempt.  Replicas adopt the
  inbound ``trace_id`` (``service.submit(trace_id=...)``,
  ``sessions.open/apply_events(trace_id=...)``) so their existing
  ``serve_*``/``session_*``/engine-segment spans carry the router's
  id — cross-process causality without cross-process span parents
  (the PR-5 ``query_request`` lane stitcher builds the tree from
  time containment per lane).
- :class:`SpanShipper`: a worker-side tap on the default flight
  recorder that copies every completed span/instant into a BOUNDED
  queue and batch-POSTs it to the router (``POST /fleet/spans``)
  from a daemon thread.  Lossy by design: a full queue or a dead
  collector increments ``dropped_spans`` and never blocks or slows
  the solve path — telemetry must not backpressure solves.
- :class:`FleetCollector`: the router-side store — one bounded lane
  per source (each replica plus the router itself), rebased onto the
  unix clock with the PR-5 anchor machinery and id-namespaced per
  lane, scrapeable live at ``GET /fleet/trace`` and queryable per
  request at ``GET /fleet/forensics/<id>``.

``PYDCOP_FLEET_TRACE=0`` turns the whole plane off (read per call so
the perf-smoke pairwise gate can toggle it at runtime); the spawned
workers inherit the knob through the router's environment.
"""

import json
import logging
import os
import threading
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("pydcop.observability.fleettrace")

# The one wire field.  A header on forwarded HTTP requests; the same
# encoded string rides as a JSON field where a body is more natural
# (migration bundles already carry the session trace_id).
HEADER = "X-Pydcop-Trace"
ENV_KNOB = "PYDCOP_FLEET_TRACE"

# Shipper bounds: the queue cap is the non-negotiable backpressure
# contract (record() is O(1) and never blocks), the batch cap keeps a
# single POST body small, and the interval paces the daemon thread.
MAX_QUEUE = 4096
BATCH_MAX = 512
FLUSH_INTERVAL_S = 0.25
SHIP_TIMEOUT_S = 5.0

# Collector bound, per source lane: old events fall off the head.
LANE_EVENTS = 20000

# Id namespacing stride across sources in the merged trace — same
# scheme as trace.merge_traces, far above any real per-process span
# count.
_ID_STRIDE = 10 ** 9


def enabled() -> bool:
    """The fleet-trace master switch, read per call: default ON;
    ``PYDCOP_FLEET_TRACE=0`` (or false/off/no) disables minting,
    header stamping and shipping without a restart."""
    return os.environ.get(ENV_KNOB, "1").strip().lower() not in (
        "0", "false", "off", "no")


class TraceContext:
    """One request's wire context: the fleet-unique ``trace_id``
    every span adopts, plus (annotation only — nesting is built from
    time containment, not cross-process parents) the router span id
    it was minted under."""

    __slots__ = ("trace_id", "parent")

    def __init__(self, trace_id: str, parent: Optional[str] = None):
        self.trace_id = trace_id
        self.parent = parent

    def encode(self) -> str:
        if self.parent:
            return f"{self.trace_id};parent={self.parent}"
        return self.trace_id

    @staticmethod
    def decode(value: Optional[str]) -> Optional["TraceContext"]:
        """Tolerant decode: a malformed header yields None (the
        replica simply mints its own ids, exactly the pre-fleet
        behavior) — never an error on the request path."""
        if not value or not isinstance(value, str):
            return None
        parts = value.strip().split(";")
        trace_id = parts[0].strip()
        if not trace_id or len(trace_id) > 128:
            return None
        parent = None
        for part in parts[1:]:
            key, _, val = part.partition("=")
            if key.strip() == "parent" and val.strip():
                parent = val.strip()[:128]
        return TraceContext(trace_id, parent)


def mint() -> TraceContext:
    """A fresh admission-time context (router-side)."""
    return TraceContext(uuid.uuid4().hex[:16])


def decode_headers(headers) -> Optional[TraceContext]:
    """Pull the context off an inbound request's header map
    (``email.message.Message`` duck type — ``.get`` suffices)."""
    try:
        return TraceContext.decode(headers.get(HEADER))
    except Exception:  # noqa: BLE001 — telemetry never 500s a solve
        return None


def _copy_event(event: Dict[str, Any]) -> Dict[str, Any]:
    """Shallow-copy an event plus its args dict: recorded events are
    LIVE dicts (timed jit calls mutate ``args`` after the record), so
    anything leaving the recording thread must snapshot them — same
    contract as flight.FlightRecorder."""
    out = dict(event)
    args = out.get("args")
    if isinstance(args, dict):
        out["args"] = dict(args)
    return out


class _FlightTap:
    """Wraps whatever recorder currently sits on ``tracer.flight``:
    events keep flowing to it unchanged, and a copy goes to the
    sink.  Every other attribute (trigger/bundle/snapshot) delegates
    to the inner recorder so the postmortem plumbing keeps working
    with the tap installed."""

    def __init__(self, inner, sink: Callable[[Dict[str, Any]], None]):
        self.inner = inner
        self._sink = sink

    def record(self, event: Dict[str, Any]) -> None:
        if self.inner is not None:
            self.inner.record(event)
        try:
            self._sink(event)
        except Exception:  # noqa: BLE001 — never break the solve path
            pass

    def __getattr__(self, name):
        if self.inner is None:
            raise AttributeError(name)
        return getattr(self.inner, name)


def _install_tap(sink) -> _FlightTap:
    from pydcop_tpu.observability.trace import tracer

    tap = _FlightTap(tracer.flight, sink)
    tracer.set_flight(tap)
    return tap


def _remove_tap(tap: Optional[_FlightTap]) -> None:
    from pydcop_tpu.observability.trace import tracer

    if tap is None:
        return
    if tracer.flight is tap:
        tracer.set_flight(tap.inner)
    # Someone re-installed a recorder over the tap meanwhile: leave
    # their recorder alone — the tap just stops receiving events.


class SpanShipper:
    """Worker-side completed-span shipper.

    ``record()`` (called from the flight tap on whatever thread just
    closed a span) is a bounded O(1) append — when the queue is full
    the event is counted in ``dropped_spans`` and forgotten.  A
    daemon thread drains batches to the collector URL over the
    netfault seam; a failed ship re-counts the batch as dropped
    (lossy, honest, never retried — telemetry is not a durability
    domain)."""

    def __init__(self, source: str = "worker",
                 max_queue: int = MAX_QUEUE,
                 batch_max: int = BATCH_MAX,
                 flush_interval_s: float = FLUSH_INTERVAL_S):
        self.source = source
        self.max_queue = max_queue
        self.batch_max = batch_max
        self.flush_interval_s = flush_interval_s
        self.url: Optional[str] = None
        self._queue: deque = deque()
        self._dropped = 0
        self.shipped = 0
        self.batches = 0
        self._tap: Optional[_FlightTap] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._wake = threading.Event()

    # -- hot path ------------------------------------------------------- #

    def record(self, event: Dict[str, Any]) -> None:
        # No lock: deque.append is atomic, and the bound check racing
        # a concurrent pop can only UNDER-fill, never block.  The
        # drop counter may undercount by a hair under contention;
        # honesty requires it to be nonzero whenever drops happened,
        # which a benign lost increment cannot violate for the
        # sustained overload that causes drops.
        if len(self._queue) >= self.max_queue:
            self._dropped += 1
            return
        self._queue.append(_copy_event(event))

    @property
    def dropped_spans(self) -> int:
        return self._dropped

    def stats(self) -> Dict[str, Any]:
        return {
            "source": self.source,
            "url": self.url,
            "queued": len(self._queue),
            "shipped": self.shipped,
            "batches": self.batches,
            "dropped_spans": self._dropped,
        }

    # -- lifecycle ------------------------------------------------------ #

    def start(self) -> "SpanShipper":
        if self._tap is None:
            self._tap = _install_tap(self.record)
        if self._thread is None:
            self._stopping.clear()
            self._thread = threading.Thread(
                target=self._flush_loop,
                name="pydcop-span-shipper", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        _remove_tap(self._tap)
        self._tap = None
        self._stopping.set()
        self._wake.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=2.0)

    def set_target(self, url: Optional[str], source: str) -> None:
        self.url = url
        self.source = source
        self._wake.set()

    def _flush_loop(self) -> None:
        while not self._stopping.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — shipper never dies
                logger.debug("span flush failed", exc_info=True)

    def flush(self) -> int:
        """Drain up to one batch to the collector; returns how many
        events shipped (0 when idle, unconfigured, or the ship
        failed — failed batches are dropped, counted, not retried)."""
        url = self.url
        batch: List[Dict[str, Any]] = []
        while self._queue and len(batch) < self.batch_max:
            try:
                batch.append(self._queue.popleft())
            except IndexError:
                break
        if not batch:
            return 0
        if not url:
            self._dropped += len(batch)
            return 0
        from pydcop_tpu.observability.trace import trace_header
        from pydcop_tpu.serving import netfault

        doc = {
            "source": self.source,
            "header": trace_header(),
            "dropped_spans": self._dropped,
            "events": batch,
        }
        try:
            host, port, path = _split_url(url)
            status, _ctype, _body = netfault.exchange(
                self.source, "router", host, port, "POST", path,
                body=json.dumps(doc, default=str).encode(),
                timeout=SHIP_TIMEOUT_S)
        except OSError:
            self._dropped += len(batch)
            return 0
        if status != 200:
            self._dropped += len(batch)
            return 0
        self.shipped += len(batch)
        self.batches += 1
        return len(batch)


def _split_url(url: str):
    """``http://host:port[/base]`` -> (host, port, ship path)."""
    rest = url.split("://", 1)[-1]
    hostport, _, base = rest.partition("/")
    host, _, port = hostport.partition(":")
    path = ("/" + base.rstrip("/") if base else "") + "/fleet/spans"
    return host, int(port or 80), path


# Process-wide shipper: the worker's /admin/trace_collector endpoint
# (the router pushes its collector URL there at fleet start, after
# restarts, and on joins) configures exactly one of these.
_shipper: Optional[SpanShipper] = None
_shipper_lock = threading.Lock()


def configure_shipper(url: Optional[str], source: str = "worker",
                      enable: bool = True) -> Dict[str, Any]:
    """(Re)configure the process-wide span shipper: ``enable=False``
    (or no url) detaches the tap and stops shipping; otherwise the
    shipper is created on first use and retargeted in place.
    Idempotent; returns the resulting state."""
    global _shipper
    with _shipper_lock:
        if not enable or not url or not enabled():
            if _shipper is not None:
                _shipper.stop()
                stats = _shipper.stats()
                _shipper = None
                return {"enabled": False, **stats}
            return {"enabled": False}
        if _shipper is None:
            _shipper = SpanShipper(source)
            _shipper.start()
        _shipper.set_target(url, source)
        return {"enabled": True, **_shipper.stats()}


def shipper() -> Optional[SpanShipper]:
    return _shipper


class FleetCollector:
    """Router-side merged-trace store: one bounded event lane per
    source (each replica that ships batches, plus the router process
    itself via a flight tap), each with the shipping process's clock
    anchor so :meth:`merged_events` can rebase every lane onto the
    shared unix clock — the same alignment trick as
    ``trace.load_events_aligned``, applied live."""

    def __init__(self, lane_events: int = LANE_EVENTS):
        self._lock = threading.Lock()
        self._lanes: Dict[str, Dict[str, Any]] = {}
        self._order: List[str] = []
        self._tap: Optional[_FlightTap] = None
        self._router_header: Optional[Dict[str, Any]] = None
        self.lane_events = lane_events

    # -- ingest --------------------------------------------------------- #

    def _lane(self, source: str,
              header: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        lane = self._lanes.get(source)
        if lane is None:
            lane = {"header": header or {},
                    "events": deque(maxlen=self.lane_events),
                    "dropped": 0}
            self._lanes[source] = lane
            self._order.append(source)
        elif header:
            lane["header"] = header
        return lane

    def ingest(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """One shipped batch (``POST /fleet/spans`` body)."""
        source = str(doc.get("source") or "unknown")
        events = doc.get("events") or []
        if not isinstance(events, list):
            raise ValueError("'events' must be a list")
        with self._lock:
            lane = self._lane(source, doc.get("header"))
            lane["events"].extend(
                e for e in events if isinstance(e, dict))
            try:
                lane["dropped"] = max(
                    lane["dropped"],
                    int(doc.get("dropped_spans") or 0))
            except (TypeError, ValueError):
                pass
        return {"accepted": len(events), "source": source}

    def record(self, event: Dict[str, Any]) -> None:
        """Flight-tap sink for the router's own process."""
        with self._lock:
            if self._router_header is None:
                from pydcop_tpu.observability.trace import (
                    trace_header,
                )

                self._router_header = trace_header()
            lane = self._lane("router", self._router_header)
            lane["events"].append(_copy_event(event))

    def attach_router_tap(self) -> None:
        if self._tap is None:
            self._tap = _install_tap(self.record)

    def detach_router_tap(self) -> None:
        _remove_tap(self._tap)
        self._tap = None

    # -- query ---------------------------------------------------------- #

    def dropped_spans(self) -> int:
        with self._lock:
            return sum(l["dropped"] for l in self._lanes.values())

    def sources(self) -> List[str]:
        with self._lock:
            return list(self._order)

    def merged_events(self) -> List[Dict[str, Any]]:
        """Every lane rebased onto the unix clock (per-source anchor
        offset), shifted so the earliest event sits near 0, tids
        namespaced ``source:tid`` and integer span ids strided per
        source — the in-memory equivalent of ``pydcop trace merge``
        over one file per process, directly consumable by
        ``query_request``/``check_well_nested``."""
        with self._lock:
            lanes = [(src,
                      dict(self._lanes[src]["header"]),
                      list(self._lanes[src]["events"]))
                     for src in self._order]
        out: List[Dict[str, Any]] = []
        for li, (src, header, events) in enumerate(lanes):
            try:
                offset = (float(header.get("anchor_unix_us"))
                          - float(header.get("anchor_perf_us")))
            except (TypeError, ValueError):
                offset = 0.0
            base = li * _ID_STRIDE
            for ev in events:
                ev = _copy_event(ev)
                try:
                    ev["ts"] = float(ev.get("ts", 0.0)) + offset
                except (TypeError, ValueError):
                    continue
                ev["tid"] = f"{src}:{ev.get('tid', 0)}"
                for key in ("id", "parent"):
                    val = ev.get(key)
                    if isinstance(val, int):
                        ev[key] = base + val
                out.append(ev)
        if out:
            t0 = min(e["ts"] for e in out)
            for ev in out:
                ev["ts"] -= t0
        out.sort(key=lambda e: e["ts"])
        return out

    def merged_doc(self) -> Dict[str, Any]:
        """The ``GET /fleet/trace`` body: merged events plus the
        lossiness ledger (what each source admits to dropping)."""
        with self._lock:
            sources = [{"source": src,
                        "events": len(self._lanes[src]["events"]),
                        "dropped_spans": self._lanes[src]["dropped"]}
                       for src in self._order]
        return {
            "version": 1,
            "sources": sources,
            "dropped_spans": sum(s["dropped_spans"]
                                 for s in sources),
            "events": self.merged_events(),
        }

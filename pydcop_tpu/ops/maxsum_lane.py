"""Lane-major MaxSum superstep: factors on the TPU lane axis.

The default kernels (ops/maxsum.py) keep messages as ``[F, arity, D]``
— domain values on the minor axis.  DCOP domains are tiny (D=3..8) so
that layout leaves 120+ of the 128 TPU lanes idle in every vector op,
and past VMEM residency (~100k vars, the BENCH_TPU.md scale cliff) the
scatter/gather traffic is issued in D-element slivers.  An on-chip
prototype of the transposed layout measured 1.7x (10k vars) / 1.3x
(100k) on the raw message math (BENCH_TPU.md round 3); this module is
the full-superstep version of that layout, A/B-able against edge-major
via benchmarks/exp_layout.py and selectable with the maxsum
``layout="lane"`` algo param (engine/runner.MaxSumEngine).

Layout (one bucket of arity ``a``, F factors, padded domain D):

- messages  ``[D, a, F]``  — F minor: every elementwise op fills lanes;
- costs     ``[D, ..., D, F]`` (``a`` domain axes, then F);
- var_ids   ``[a, F]`` (transposed bucket scope);
- var costs/valid/beliefs/sums ``[D, V+1]`` — variables on lanes.

The flatten feeding variable aggregation is ``[D, a, F] -> [D, a*F]``,
a contiguous reshape (position-major edge order), so the superstep
contains NO transposes: the layout choice is made once at compile time
(``to_lane_graph``) and everything stays lane-major.

Aggregation is a scatter-add along the minor axis
(``sums.at[:, seg].add(flat)``) — the lane-major analogue of the
edge-major ``segment_sum``.  Scatter order matches edge order, and all
other ops are elementwise or tiny-D reductions in identical order, so
trajectories are BIT-IDENTICAL to edge-major per element (asserted by
tests/unit/test_maxsum_lane.py) *except* where a variable's incoming
edges arrive in a different order across layouts: edge-major flattens
(factor, position), lane-major (position, factor).  For single-bucket
binary graphs built by generators the per-variable contribution sets
are identical, so sums differ only by float reassociation; the parity
tests therefore assert exact assignment equality plus message
agreement to float tolerance, and bit-equality where the instance has
at most one bucket position per variable.

Semantics are the reference's exactly, same as ops/maxsum.py (factor
update pydcop/algorithms/maxsum.py:382, variable update :623 with
mean-normalization :670-674, damping :679, approx_match :688,
SAME_COUNT suppression :106).
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pydcop_tpu.engine.compile import (
    BIG,
    CompiledFactorGraph,
    FactorBucket,
)
from pydcop_tpu.ops.maxsum import SAME_COUNT

Msgs = Tuple[jnp.ndarray, ...]  # one [D, arity, F] array per bucket


class LaneBucket(NamedTuple):
    """All factors of one arity, lane-major."""

    costs: jnp.ndarray    # [D]*arity + [F]
    var_ids: jnp.ndarray  # [arity, F] int32 (sentinel V on padding)

    @property
    def arity(self) -> int:
        return self.var_ids.shape[0]

    @property
    def n_factors(self) -> int:
        return self.var_ids.shape[1]


class LaneGraph(NamedTuple):
    """Lane-major twin of CompiledFactorGraph (scatter aggregation
    only — the sort-based strategies are edge-major concepts)."""

    var_costs: jnp.ndarray   # [Dmax, V+1]
    var_valid: jnp.ndarray   # [Dmax, V+1]
    buckets: Tuple[LaneBucket, ...]

    @property
    def n_vars(self) -> int:
        return self.var_costs.shape[1] - 1

    @property
    def dmax(self) -> int:
        return self.var_costs.shape[0]


class PackLayout(NamedTuple):
    """Where each member of a lane-packed union landed (ISSUE 11).

    Lane packing turns N *different*-structure problems into ONE
    disjoint-union factor graph: variables concatenate (one shared
    sentinel at the end), and each arity's factors concatenate on the
    lane (F) axis.  Because the union is a disjoint union, message
    passing decomposes exactly — no member's messages can reach
    another member's variables — so per-member results equal solo
    solves while the device sees one dense dispatch with NO
    per-member shape padding (the only mask waste is the shared
    domain rung)."""

    # Per member: (start, n_vars) into the union's variable rows.
    var_slices: Tuple[Tuple[int, int], ...]
    # Per member: ((bucket_index, start, n_rows), ...) into the
    # union's buckets — only arities the member actually has.
    row_slices: Tuple[Tuple[Tuple[int, int, int], ...], ...]
    # Union bucket arity order (sorted ascending).
    arities: Tuple[int, ...]


def pack_graphs(graphs, d_env: Optional[int] = None
                ) -> Tuple[CompiledFactorGraph, PackLayout]:
    """Disjoint-union pack: concatenate compiled graphs into one
    edge-major CompiledFactorGraph (host numpy), domains mask-padded
    to the shared ``d_env`` (default: the group's max) with the
    compiler's own discipline (``BIG`` cost, ``var_valid=False``).

    Members may have entirely different variable counts, factor
    counts and arity sets.  Each member's rows keep their relative
    order inside the union buckets, so the per-variable scatter-add
    accumulates a member's contributions in the same order a solo
    dispatch would — the parity the envelope battery asserts.

    The union keeps a single sentinel row (index ``sum(v_i)``);
    members' own compile-time sentinel references are re-pointed at
    it.  Aggregation arrays are dropped (scatter path).  Use
    :func:`to_lane_graph` on the result to run lane-major, and
    :func:`converged_per_graph` to recover per-member convergence.
    """
    if not graphs:
        raise ValueError("pack_graphs needs at least one graph")
    if d_env is None:
        d_env = max(g.dmax for g in graphs)
    if d_env < max(g.dmax for g in graphs):
        raise ValueError(
            f"d_env={d_env} below the group's max domain "
            f"{max(g.dmax for g in graphs)}")
    v_total = sum(g.n_vars for g in graphs)
    dtype = graphs[0].var_costs.dtype
    var_costs = np.full((v_total + 1, d_env), BIG, dtype=dtype)
    var_valid = np.zeros((v_total + 1, d_env), dtype=bool)
    var_slices = []
    offset = 0
    for g in graphs:
        v, d = g.n_vars, g.dmax
        var_costs[offset:offset + v, :d] = np.asarray(g.var_costs)[:v]
        var_valid[offset:offset + v, :d] = np.asarray(g.var_valid)[:v]
        var_slices.append((offset, v))
        offset += v

    arities = sorted({b.arity for g in graphs for b in g.buckets})
    bucket_index = {a: i for i, a in enumerate(arities)}
    costs_parts = {a: [] for a in arities}
    ids_parts = {a: [] for a in arities}
    row_cursor = {a: 0 for a in arities}
    row_slices = []
    for g, (start, _v) in zip(graphs, var_slices):
        v = g.n_vars
        member_rows = []
        for b in g.buckets:
            a, n_rows, d = b.arity, b.n_factors, g.dmax
            block = np.full((n_rows,) + (d_env,) * a, BIG,
                            dtype=b.costs.dtype)
            block[(slice(None),) + (slice(0, d),) * a] = \
                np.asarray(b.costs)
            ids = np.asarray(b.var_ids).astype(np.int32).copy()
            # Member-local indices -> union indices; the member's own
            # sentinel (v) re-points at the union sentinel (v_total).
            sent = ids == v
            ids = ids + start
            ids[sent] = v_total
            costs_parts[a].append(block)
            ids_parts[a].append(ids)
            member_rows.append(
                (bucket_index[a], row_cursor[a], n_rows))
            row_cursor[a] += n_rows
        row_slices.append(tuple(member_rows))

    buckets = tuple(
        FactorBucket(
            costs=np.concatenate(costs_parts[a], axis=0),
            var_ids=np.concatenate(ids_parts[a], axis=0),
        )
        for a in arities
    )
    union = CompiledFactorGraph(
        var_costs=var_costs, var_valid=var_valid, buckets=buckets,
    )
    layout = PackLayout(
        var_slices=tuple(var_slices),
        row_slices=tuple(row_slices),
        arities=tuple(arities),
    )
    return union, layout


def converged_per_graph(v2f_count, f2v_count,
                        layout: PackLayout) -> Tuple[bool, ...]:
    """Per-member convergence verdicts from a packed run's final
    send-suppression counters.  An edge's count is reset to 1 on a
    mismatched send and incremented on a match, so ``count >= 2`` on
    every edge of a member (both directions) is exactly that member's
    slice of the global ``stable`` conjunction — the packed dispatch
    reports honest per-request ``converged`` flags even though the
    union carries one shared flag.  Counter arrays are the lane-major
    ``[arity, F]`` per-bucket LaneState counters (the F axis is
    sliced)."""
    verdicts = []
    for member_rows in layout.row_slices:
        ok = True
        for bi, start, n_rows in member_rows:
            for counts in (v2f_count[bi], f2v_count[bi]):
                rows = np.asarray(counts)[:, start:start + n_rows]
                ok = ok and bool((rows >= 2).all())
        verdicts.append(ok)
    return tuple(verdicts)


def to_lane_graph(graph: CompiledFactorGraph) -> LaneGraph:
    """One-time compile-side relayout (host numpy; the superstep never
    transposes)."""
    return LaneGraph(
        var_costs=np.ascontiguousarray(np.asarray(graph.var_costs).T),
        var_valid=np.ascontiguousarray(np.asarray(graph.var_valid).T),
        buckets=tuple(
            LaneBucket(
                costs=np.ascontiguousarray(
                    np.moveaxis(np.asarray(b.costs), 0, -1)),
                var_ids=np.ascontiguousarray(np.asarray(b.var_ids).T),
            )
            for b in graph.buckets
        ),
    )


class LaneState(NamedTuple):
    v2f: Msgs            # last SENT variable -> factor messages
    f2v: Msgs            # last SENT factor -> variable messages
    v2f_count: Msgs      # [arity, F] int8 consecutive-same counts
    f2v_count: Msgs
    stable: jnp.ndarray  # scalar bool
    cycle: jnp.ndarray   # scalar int32


def init_state(graph: LaneGraph) -> LaneState:
    d = graph.var_costs.shape[0]
    dtype = graph.var_costs.dtype

    # Independent arrays per field (no tuple reuse): the segment jits
    # donate the state pytree (engine/runner.py), and donation rejects
    # the same buffer appearing in two donated slots.
    def zeros():
        return tuple(
            jnp.zeros((d,) + b.var_ids.shape, dtype=dtype)
            for b in graph.buckets
        )

    def counts():
        return tuple(
            jnp.zeros(b.var_ids.shape, dtype=jnp.int8)
            for b in graph.buckets
        )

    return LaneState(
        v2f=zeros(), f2v=zeros(),
        v2f_count=counts(), f2v_count=counts(),
        stable=jnp.asarray(False),
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def _edge_match(new, old, stability, valid):
    """Per-edge approx_match over the domain axis (axis 0 here);
    algebra identical to ops/maxsum._edge_match.  Returns [a, F]."""
    delta = jnp.abs(new - old)
    s = jnp.abs(new + old)
    ok = (2 * delta < stability * s) | (delta == 0)
    return jnp.all(ok | ~valid, axis=0)


def _send_or_suppress(cand, prev, count, stability, valid, first):
    """SAME_COUNT send-suppression, lane-major (match flags are
    [a, F]; the broadcast goes on the leading domain axis)."""
    match = _edge_match(cand, prev, stability, valid) & ~first
    send = ~match | (count < SAME_COUNT)
    sent = jnp.where(send[None], cand, prev)
    new_count = jnp.where(
        match, jnp.minimum(count + 1, SAME_COUNT + 1), 1
    )
    return sent, new_count, match


def factor_to_var(graph: LaneGraph, v2f: Msgs) -> Msgs:
    """All factor→variable messages, one batched min-reduction per
    bucket over the leading domain axes (F rides along on lanes)."""
    out = []
    for bucket, msgs in zip(graph.buckets, v2f):
        d, arity, f = msgs.shape
        total = bucket.costs                     # [D, ..., D, F]
        for q in range(arity):
            shape = [1] * arity + [f]
            shape[q] = d
            total = total + msgs[:, q].reshape(shape)
        outs_p = []
        for p in range(arity):
            axes = tuple(i for i in range(arity) if i != p)
            reduced = jnp.min(total, axis=axes) if axes else total
            outs_p.append(reduced - msgs[:, p])
        out.append(jnp.stack(outs_p, axis=1))    # [D, a, F]
    return tuple(out)


def aggregate_beliefs(graph: LaneGraph, f2v: Msgs
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sum incoming factor messages per variable: scatter-add along
    the minor (variable) axis.  The feeding reshape is contiguous —
    this is the op the lane layout exists for."""
    sums = jnp.zeros_like(graph.var_costs)       # [D, V+1]
    for bucket, msgs in zip(graph.buckets, f2v):
        d = msgs.shape[0]
        flat = msgs.reshape(d, -1)               # [D, a*F]
        seg = bucket.var_ids.reshape(-1)         # [a*F]
        sums = sums.at[:, seg].add(flat)
    return graph.var_costs + sums, sums


def var_to_factor(graph: LaneGraph, f2v: Msgs, beliefs, sums) -> Msgs:
    """Belief minus own contribution, mean-normalized over valid
    domain slots (domain axis = axis 0)."""
    out = []
    for bucket, msgs in zip(graph.buckets, f2v):
        valid = graph.var_valid[:, bucket.var_ids]   # [D, a, F]
        raw = beliefs[:, bucket.var_ids] - msgs
        factor_sum = sums[:, bucket.var_ids] - msgs
        n_valid = jnp.maximum(
            jnp.sum(valid, axis=0, keepdims=True), 1
        )
        avg = (
            jnp.sum(jnp.where(valid, factor_sum, 0.0), axis=0,
                    keepdims=True)
            / n_valid
        )
        out.append(jnp.where(valid, raw - avg,
                             jnp.asarray(BIG, raw.dtype)))
    return tuple(out)


def select_values(graph: LaneGraph, beliefs: jnp.ndarray) -> jnp.ndarray:
    """Per-variable argmin of belief over valid slots ([V] int32)."""
    masked = jnp.where(graph.var_valid, beliefs, jnp.inf)
    return jnp.argmin(masked[:, :-1], axis=0).astype(jnp.int32)


def _damp(new: Msgs, old: Msgs, damping: float, first) -> Msgs:
    return tuple(
        jnp.where(first, n, damping * o + (1.0 - damping) * n)
        for n, o in zip(new, old)
    )


def superstep(state: LaneState, graph: LaneGraph, *, damping: float,
              damp_vars: bool, damp_factors: bool,
              stability: float) -> LaneState:
    """One synchronous cycle, same Jacobi semantics as
    ops/maxsum.superstep (both sides fire from last cycle's mail)."""
    first = state.cycle == 0
    valids = tuple(
        graph.var_valid[:, b.var_ids] for b in graph.buckets
    )

    f2v_cand = factor_to_var(graph, state.v2f)
    if damp_factors and damping > 0:
        f2v_cand = _damp(f2v_cand, state.f2v, damping, first)

    beliefs, sums = aggregate_beliefs(graph, state.f2v)
    v2f_cand = var_to_factor(graph, state.f2v, beliefs, sums)
    if damp_vars and damping > 0:
        v2f_cand = _damp(v2f_cand, state.v2f, damping, first)

    f2v_new, f2v_count = [], []
    v2f_new, v2f_count = [], []
    all_match = jnp.asarray(True)
    for i, valid in enumerate(valids):
        sent, cnt, match = _send_or_suppress(
            f2v_cand[i], state.f2v[i], state.f2v_count[i],
            stability, valid, first)
        f2v_new.append(sent)
        f2v_count.append(cnt)
        all_match = all_match & jnp.all(match | ~jnp.any(valid, 0))
        sent, cnt, match = _send_or_suppress(
            v2f_cand[i], state.v2f[i], state.v2f_count[i],
            stability, valid, first)
        v2f_new.append(sent)
        v2f_count.append(cnt)
        all_match = all_match & jnp.all(match | ~jnp.any(valid, 0))

    return LaneState(
        v2f=tuple(v2f_new),
        f2v=tuple(f2v_new),
        v2f_count=tuple(v2f_count),
        f2v_count=tuple(f2v_count),
        stable=all_match & ~first,
        cycle=state.cycle + 1,
    )


def assignment_constraint_cost(graph: LaneGraph,
                               values: jnp.ndarray) -> jnp.ndarray:
    """Total factor-table cost of an assignment ([V] value indices);
    padding rows contribute 0 (see ops/maxsum counterpart)."""
    vals = jnp.concatenate(
        [values, jnp.zeros((1,), dtype=values.dtype)]
    )
    total = jnp.asarray(0.0, dtype=graph.var_costs.dtype)
    for bucket in graph.buckets:
        arity, f = bucket.var_ids.shape
        d = graph.var_costs.shape[0]
        idx = vals[bucket.var_ids]               # [arity, F]
        flat = jnp.zeros((f,), dtype=jnp.int32)
        for p in range(arity):
            flat = flat * d + idx[p]
        table = bucket.costs.reshape(-1, f)      # [D^arity, F]
        total = total + jnp.sum(
            jnp.take_along_axis(table, flat[None, :], axis=0)
        )
    return total


def _reject_prune(prune: bool):
    """Branch-and-bound pruning is an edge-major kernel (it gathers
    reduction rows of the [F, D, D] hypercubes); the lane layout's
    transposed messages would need their own compaction.  The engine
    refuses layout='lane' + prune at construction — this guard keeps
    the ops-level contract explicit for direct callers."""
    if prune:
        raise NotImplementedError(
            "prune=True is edge-major only; run with layout='edge'")


def run_maxsum(graph: LaneGraph, max_cycles: int, *,
               damping: float = 0.5, damp_vars: bool = True,
               damp_factors: bool = True, stability: float = 0.1,
               stop_on_convergence: bool = True,
               prune: bool = False,
               ) -> Tuple[LaneState, jnp.ndarray]:
    """Full lane-major MaxSum run in one XLA program."""
    return run_maxsum_from(
        graph, init_state(graph), max_cycles,
        damping=damping, damp_vars=damp_vars,
        damp_factors=damp_factors, stability=stability,
        stop_on_convergence=stop_on_convergence, prune=prune,
    )


def run_maxsum_from(graph: LaneGraph, state: LaneState,
                    extra_cycles: int, *,
                    damping: float = 0.5, damp_vars: bool = True,
                    damp_factors: bool = True, stability: float = 0.1,
                    stop_on_convergence: bool = True,
                    prune: bool = False,
                    ) -> Tuple[LaneState, jnp.ndarray]:
    _reject_prune(prune)

    def step(state):
        return superstep(
            state, graph, damping=damping, damp_vars=damp_vars,
            damp_factors=damp_factors, stability=stability,
        )

    limit = state.cycle + extra_cycles
    if stop_on_convergence:
        state = jax.lax.while_loop(
            lambda s: (s.cycle < limit) & ~s.stable, step, state,
        )
    else:
        state = jax.lax.while_loop(
            lambda s: s.cycle < limit, step, state,
        )
    beliefs, _ = aggregate_beliefs(graph, state.f2v)
    values = select_values(graph, beliefs)
    return state, values


def run_maxsum_trace(graph: LaneGraph, max_cycles: int, *,
                     damping: float = 0.5, damp_vars: bool = True,
                     damp_factors: bool = True, stability: float = 0.1,
                     var_base_costs: Optional[jnp.ndarray] = None,
                     stop_on_convergence: bool = True,
                     prune: bool = False,
                     ) -> Tuple[LaneState, jnp.ndarray, jnp.ndarray]:
    """Lane-major twin of ops/maxsum.run_maxsum_trace (same while_loop
    + carried-cost-buffer structure, same early exit at the fixpoint
    with the tail of the curve holding the final cost).
    ``var_base_costs`` is [V, Dmax] edge-major (FactorGraphMeta
    convention) — transposed once here, not per cycle."""
    _reject_prune(prune)
    base_t = None if var_base_costs is None else var_base_costs.T

    def cost_of(values):
        cost = assignment_constraint_cost(graph, values)
        if base_t is not None:
            cost = cost + jnp.sum(jnp.take_along_axis(
                base_t, values[None, :], axis=0))
        return cost

    def step(carry):
        state, costs, last = carry
        state = superstep(
            state, graph, damping=damping, damp_vars=damp_vars,
            damp_factors=damp_factors, stability=stability,
        )
        beliefs, _ = aggregate_beliefs(graph, state.f2v)
        values = select_values(graph, beliefs)
        cost = cost_of(values)
        costs = jax.lax.dynamic_update_slice(
            costs, cost[None], (state.cycle - 1,))
        return state, costs, cost

    def done(carry):
        state = carry[0]
        out = state.cycle >= max_cycles
        if stop_on_convergence:
            out = out | state.stable
        return out

    zero = jnp.asarray(0.0, graph.var_costs.dtype)
    state, costs, last = jax.lax.while_loop(
        lambda c: ~done(c), step,
        (init_state(graph),
         jnp.zeros((max_cycles,), graph.var_costs.dtype), zero),
    )
    costs = jnp.where(
        jnp.arange(max_cycles) >= state.cycle, last, costs)
    beliefs, _ = aggregate_beliefs(graph, state.f2v)
    values = select_values(graph, beliefs)
    return state, values, costs

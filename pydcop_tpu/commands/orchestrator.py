"""``pydcop orchestrator`` — placeholder, implemented later this round.

Reference parity target: pydcop/commands/orchestrator.py.
"""


def set_parser(subparsers):
    parser = subparsers.add_parser("orchestrator", help="orchestrator (not yet implemented)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    print("pydcop orchestrator: not implemented yet in pydcop-tpu")
    return 3

"""Replica placement data objects.

Reference parity: pydcop/replication/objects.py (ReplicaDistribution
:40-80: mapping computation -> hosting agents, replicas_on :64,
agents_for_computation :72).
"""

from typing import Dict, List

from pydcop_tpu.utils.simple_repr import SimpleRepr


class ReplicaDistribution(SimpleRepr):
    """Mapping computation-name -> list of agents hosting a replica.

    >>> rd = ReplicaDistribution({'c1': ['a1', 'a2'], 'c2': ['a2']})
    >>> rd.agents_for_computation('c1')
    ['a1', 'a2']
    >>> rd.replicas_on('a2')
    ['c1', 'c2']
    """

    def __init__(self, mapping: Dict[str, List[str]]):
        self._mapping = {c: list(agts) for c, agts in mapping.items()}

    @property
    def mapping(self) -> Dict[str, List[str]]:
        return {c: list(agts) for c, agts in self._mapping.items()}

    @property
    def computations(self) -> List[str]:
        return list(self._mapping)

    def agents_for_computation(self, computation: str) -> List[str]:
        return list(self._mapping[computation])

    def replicas_on(self, agent: str,
                    raise_on_unknown: bool = False) -> List[str]:
        found = sorted(
            c for c, agts in self._mapping.items() if agent in agts
        )
        if not found and raise_on_unknown and not any(
            agent in agts for agts in self._mapping.values()
        ):
            raise ValueError(f"No replicas on agent {agent}")
        return found

    def add_replica(self, computation: str, agent: str):
        hosts = self._mapping.setdefault(computation, [])
        if agent not in hosts:
            hosts.append(agent)

    def remove_agent(self, agent: str):
        """Drop every replica hosted on a departed agent."""
        for hosts in self._mapping.values():
            if agent in hosts:
                hosts.remove(agent)

    def __eq__(self, other):
        return (
            isinstance(other, ReplicaDistribution)
            and self._mapping == other._mapping
        )

    def __repr__(self):
        return f"ReplicaDistribution({self._mapping})"

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "mapping": self.mapping,
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["mapping"])

"""``pydcop generate`` — placeholder, implemented later this round.

Reference parity target: pydcop/commands/generate.py.
"""


def set_parser(subparsers):
    parser = subparsers.add_parser("generate", help="generate (not yet implemented)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    print("pydcop generate: not implemented yet in pydcop-tpu")
    return 3

"""Shared bases/helpers for agent-mode algorithm computations."""

from typing import Any, Dict, List, Tuple

from pydcop_tpu.dcop.relations import optimal_cost_value
from pydcop_tpu.infrastructure.computations import VariableComputation


class HypergraphComputation(VariableComputation):
    """Base for constraints-hypergraph computations: neighbor set from
    the node's constraints, sign normalization, unary costs."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        self.constraints = list(comp_def.node.constraints)
        self._neighbors = list(dict.fromkeys(
            v.name for c in self.constraints for v in c.dimensions
            if v.name != self.name
        ))

    @property
    def neighbors(self) -> List[str]:
        return self._neighbors

    @property
    def sign(self) -> float:
        # Internally always minimize sign*cost.
        return 1.0 if self.mode == "min" else -1.0

    def _finish_no_neighbors(self) -> bool:
        if self._neighbors:
            return False
        value, cost = optimal_cost_value(self._variable, self.mode)
        self.value_selection(value, cost)
        self.finished()
        self.stop()
        return True


def scan_best(domain, eval_fn) -> Tuple[float, List[Any]]:
    """(best_eval, values-at-best) of ``eval_fn`` over ``domain``,
    values kept in domain order — the shared candidate scan of the
    breakout-family wave protocols."""
    best_eval, best_vals = None, []
    for v in domain:
        e = eval_fn(v)
        if best_eval is None or e < best_eval:
            best_eval, best_vals = e, [v]
        elif e == best_eval:
            best_vals.append(v)
    return best_eval, best_vals


def wins_neighborhood(name: str, improve: float,
                      neighbor_improves: Dict[str, float]) -> bool:
    """Strict max in the neighborhood, lexically-smallest name winning
    ties (reference dba.py:507-517 / gdba.py:657)."""
    n_max = max(neighbor_improves.values())
    return improve > n_max or (
        improve == n_max
        and all(
            name < s for s, i in neighbor_improves.items()
            if i == n_max
        )
    )

"""Remote-controlled agents: deploy/run/stop driven by the orchestrator.

Reference parity: pydcop/infrastructure/orchestratedagents.py
(OrchestratedAgent :71, OrchestrationComputation :178) — the agent-side
management computation handling deploy/run/pause/resume/stop messages
and reporting value changes, cycle changes and computation completion to
the orchestrator.
"""

import logging
from typing import Optional

from pydcop_tpu.dcop.objects import AgentDef
from pydcop_tpu.infrastructure.agents import Agent
from pydcop_tpu.infrastructure.communication import (
    CommunicationLayer,
    MSG_MGT,
)
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
    build_computation,
    message_type,
    register,
)

ORCHESTRATOR_AGENT = "orchestrator"
ORCHESTRATOR_MGT = "_mgt_orchestrator"

DeployMessage = message_type("deploy", ["comp_def"])
RunAgentMessage = message_type("run_computations", ["computations"])
PauseMessage = message_type("pause_computations", ["computations"])
ResumeMessage = message_type("resume_computations", ["computations"])
StopAgentMessage = message_type("stop_agent", [])
AgentStoppedMessage = message_type("agent_stopped", ["agent", "metrics"])
ValueChangeMessage = message_type(
    "value_change", ["agent", "computation", "value", "cost", "cycle"])
CycleChangeMessage = message_type(
    "cycle_change", ["agent", "computation", "cycle"])
ComputationFinishedMessage = message_type(
    "computation_finished", ["agent", "computation"])
AgentReadyMessage = message_type("agent_ready", ["agent", "address"])
RemoveComputationsMessage = message_type(
    "remove_computations", ["computations"])

logger = logging.getLogger("pydcop.orchestratedagent")


class OrchestrationComputation(MessagePassingComputation):
    """Agent-side management computation (name: ``_mgt_<agent>``)."""

    def __init__(self, agent: Agent):
        super().__init__(f"_mgt_{agent.name}")
        self.agent = agent
        agent.on_value_change = self._on_value_change
        agent.on_cycle_change = self._on_cycle_change
        agent.on_computation_finished = self._on_comp_finished

    def on_start(self):
        # Announce ourselves to the orchestrator.
        self.post_msg(
            ORCHESTRATOR_MGT,
            AgentReadyMessage(self.agent.name, None),
            MSG_MGT,
        )

    @register("deploy")
    def _on_deploy(self, sender, msg, t):
        comp_def = msg.comp_def
        computation = build_computation(comp_def)
        self.agent.add_computation(computation)
        logger.debug(
            "Deployed computation %s on agent %s",
            comp_def.name, self.agent.name,
        )

    @register("run_computations")
    def _on_run(self, sender, msg, t):
        computations = msg.computations
        self.agent.run(computations if computations else None)

    @register("pause_computations")
    def _on_pause(self, sender, msg, t):
        for name in msg.computations or [
            c.name for c in self.agent.computations
            if not c.name.startswith("_")
        ]:
            if self.agent.has_computation(name):
                self.agent.computation(name).pause(True)

    @register("resume_computations")
    def _on_resume(self, sender, msg, t):
        # Per-computation isolation: one computation's poisoned
        # buffered message (its resume flush re-raises the first
        # delivery error) must not leave the agent's OTHER
        # computations paused forever.  EVERY failure is logged here
        # with the failing computation's name — resume errors can also
        # come from on_pause hooks (before any flush logging), and
        # only the first error is re-raised to the agent loop.
        first_error = None
        for name in msg.computations or [
            c.name for c in self.agent.computations
            if not c.name.startswith("_")
        ]:
            if not self.agent.has_computation(name):
                continue
            try:
                self.agent.computation(name).pause(False)
            except Exception as e:  # noqa: BLE001 - rethrown below
                self.agent.logger.exception(
                    "Error resuming computation %s", name)
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error

    @register("remove_computations")
    def _on_remove_computations(self, sender, msg, t):
        """Retire temporarily-hosted computations (e.g. repair-DCOP
        variables once the repair round is decided)."""
        for name in msg.computations:
            if self.agent.has_computation(name):
                self.agent.remove_computation(name)

    @register("stop_agent")
    def _on_stop(self, sender, msg, t):
        metrics = self.agent.metrics()
        self.post_msg(
            ORCHESTRATOR_MGT,
            AgentStoppedMessage(self.agent.name, metrics),
            MSG_MGT,
        )
        self.agent.stop()

    # -- reporting ----------------------------------------------------- #

    def _on_value_change(self, comp):
        self.post_msg(
            ORCHESTRATOR_MGT,
            ValueChangeMessage(
                self.agent.name, comp.name, comp.current_value,
                comp.current_cost, getattr(comp, "cycle_count", 0),
            ),
            MSG_MGT,
        )

    def _on_cycle_change(self, comp):
        self.post_msg(
            ORCHESTRATOR_MGT,
            CycleChangeMessage(
                self.agent.name, comp.name,
                getattr(comp, "cycle_count", 0),
            ),
            MSG_MGT,
        )

    def _on_comp_finished(self, comp):
        self.post_msg(
            ORCHESTRATOR_MGT,
            ComputationFinishedMessage(self.agent.name, comp.name),
            MSG_MGT,
        )


class OrchestratedAgent(Agent):
    """An agent bootstrapped against an orchestrator's directory."""

    def __init__(self, agent_def: AgentDef, comm: CommunicationLayer,
                 orchestrator_address,
                 delay: Optional[float] = None,
                 replication: bool = False,
                 ui_port: Optional[int] = None):
        super().__init__(agent_def.name, comm, agent_def, delay=delay,
                         ui_port=ui_port)
        self.discovery.use_directory(
            ORCHESTRATOR_AGENT, orchestrator_address
        )
        # Seed the orchestrator's management computation address.
        self.discovery.register_computation(
            ORCHESTRATOR_MGT, ORCHESTRATOR_AGENT,
            orchestrator_address, publish=False,
        )
        self._orchestration = OrchestrationComputation(self)
        self.add_computation(self._orchestration)
        self.discovery.register_agent(self.name, comm.address)
        # Register the service computations globally so the orchestrator
        # (mgt) and the directory (publications to _discovery_<agent>)
        # can reach us.
        self.discovery.register_computation(
            self._orchestration.name, self.name, comm.address
        )
        self.discovery.register_computation(
            self.discovery.discovery_computation.name, self.name,
            comm.address,
        )
        # Resilience: host a replica-placement computation so this
        # agent can replicate its computations and adopt others'
        # replicas on repair (reference ResilientAgent, agents.py:927).
        self.replication_comp = None
        if replication:
            from pydcop_tpu.replication.dist_ucs_hostingcosts import (
                build_replication_computation,
            )

            self.replication_comp = build_replication_computation(
                self, self.discovery
            )
            self.add_computation(self.replication_comp)
            self.discovery.register_computation(
                self.replication_comp.name, self.name, comm.address
            )

    def start(self):
        super().start()
        self._orchestration.start()
        if self.replication_comp is not None:
            self.replication_comp.start()


def ResilientAgent(agent_def: AgentDef, comm: CommunicationLayer,
                   orchestrator_address, delay: Optional[float] = None
                   ) -> OrchestratedAgent:
    """An orchestrated agent with replication enabled (reference
    agents.py:927 ResilientAgent)."""
    return OrchestratedAgent(
        agent_def, comm, orchestrator_address, delay=delay,
        replication=True,
    )

"""Scenario event processing for dynamic DCOPs.

Reference parity: pydcop/infrastructure/orchestrator.py:340 (_process_event
scheduling) and :955-1010 (_orchestrator_scenario_event: pause, apply
agent removals, trigger repair, resume).

Current support: delay events and remove_agent actions (the removed
agent's computations are reported; repair-based migration arrives with
the replication layer).  Unknown action types are logged and skipped.
"""

import logging
import time

logger = logging.getLogger("pydcop.scenario")


def run_scenario_events(orchestrator, scenario):
    """Execute scenario events against a running orchestrator."""
    for event in scenario.events:
        if event.is_delay:
            time.sleep(event.delay)
            continue
        logger.info("Scenario event %s", event.id)
        orchestrator.pause_agents()
        for action in event.actions or []:
            if action.type == "remove_agent":
                agent = action.args.get("agent")
                logger.info("Scenario: removing agent %s", agent)
                orchestrator.remove_agent(agent)
            else:
                logger.warning(
                    "Unsupported scenario action %s (skipped)",
                    action.type,
                )
        orchestrator.resume_agents()

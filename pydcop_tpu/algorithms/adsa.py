"""A-DSA: asynchronous DSA, clock-driven.

Reference parity: pydcop/algorithms/adsa.py (:121-131: params variant,
probability, period 0.5) — each variable re-evaluates on a periodic
clock tick using whatever neighbor values it has seen, instead of
waiting for a full cycle of value messages.

Device path: the lockstep engine evaluates every variable each
superstep, i.e. the `period` is one superstep for everyone; `period` is
accepted for compatibility and used by the agent-mode runtime (periodic
actions on the agent clock).

Measured semantics cost of the lockstep substitution (20-seed paired
CI, tests/api/test_async_equivalence.py): at MATCHED cycle budgets
lockstep solution quality is slightly worse than the clock-driven
async runtime (mean gap ~3% of the constraint count — simultaneous
neighbor flips thrash where async's skewed updates do not); at native
budgets the gap vanishes, because device supersteps are ~free and the
engine simply runs more of them.
"""

from typing import Optional

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.algorithms import dsa as _dsa
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.runner import DeviceRunResult

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("probability", "float", None, 0.7),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("period", "float", None, 0.5),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("seed", "int", None, 0),
]

computation_memory = _dsa.computation_memory
communication_load = _dsa.communication_load


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("adsa", comp_def)


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    warmup: bool = False,
                    **_) -> DeviceRunResult:
    inner = AlgorithmDef(
        "dsa",
        {
            "probability": algo_def.params.get("probability", 0.7),
            "p_mode": "fixed",
            "variant": algo_def.params.get("variant", "B"),
            "stop_cycle": algo_def.params.get("stop_cycle", 0),
            "seed": algo_def.params.get("seed", 0),
        },
        algo_def.mode,
    )
    return _dsa.solve_on_device(
        dcop, inner, max_cycles=max_cycles, mesh=mesh,
        n_devices=n_devices, warmup=warmup,
    )

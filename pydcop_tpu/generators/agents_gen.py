"""Agent-definition generator: capacities, hosting costs, routes.

Reference parity: pydcop/commands/generators/agents.py — modes
``variables`` (one agent per variable of given dcops) and ``count``;
hosting-cost methods None / name_mapping (cost 0 for the computation
whose name maps to the agent) / var_startswith; route methods None /
uniform.
"""

from typing import List, Optional

import numpy as np

from pydcop_tpu.dcop.objects import AgentDef


def generate_agents(
    mode: str = "count",
    count: Optional[int] = None,
    variables: Optional[List[str]] = None,
    agent_prefix: str = "a",
    capacity: int = 100,
    hosting: str = "None",
    hosting_default: Optional[int] = None,
    routes: str = "None",
    routes_default: Optional[int] = None,
    adjacency: Optional[List] = None,
    seed: Optional[int] = None,
) -> List[AgentDef]:
    """`adjacency` (pairs of variable names sharing a constraint) is
    required for routes='graph': connected agents get cheap (1) routes,
    all other pairs the default."""
    rng = np.random.default_rng(seed)
    if hosting == "name_mapping" and mode != "variables":
        raise ValueError(
            "hosting 'name_mapping' requires mode 'variables' (one "
            "agent per variable, from dcop files)"
        )
    if routes == "graph" and adjacency is None:
        raise ValueError(
            "routes 'graph' requires dcop files (constraint adjacency)"
        )
    if mode == "variables":
        if not variables:
            raise ValueError(
                "agents generation mode 'variables' requires variables"
            )
        names = [f"{agent_prefix}{v}" for v in variables]
    else:
        if not count:
            raise ValueError(
                "agents generation mode 'count' requires count"
            )
        width = len(str(count - 1))
        names = [
            f"{agent_prefix}{i:0{width}d}" for i in range(count)
        ]
        variables = variables or []

    agents = []
    for i, name in enumerate(names):
        hosting_costs = {}
        default_hosting = 0
        if hosting != "None":
            if hosting_default is None:
                raise ValueError(
                    "--hosting requires --hosting_default"
                )
            default_hosting = hosting_default
            if hosting == "name_mapping" and mode == "variables":
                hosting_costs = {variables[i]: 0}
            elif hosting == "var_startswith":
                hosting_costs = {
                    v: 0 for v in variables
                    if name.endswith(v) or v.startswith(
                        name[len(agent_prefix):])
                }
        route_costs = {}
        default_route = 1
        if routes != "None":
            if routes_default is None:
                raise ValueError("--routes requires --routes_default")
            default_route = routes_default
            if routes == "graph" and mode == "variables":
                # Cheap routes between agents whose variables share a
                # constraint; default cost elsewhere (symmetric: stored
                # on both agents via the shared dict below).
                var_of_agent = variables[i]
                for (a, b) in adjacency:
                    other_var = None
                    if a == var_of_agent:
                        other_var = b
                    elif b == var_of_agent:
                        other_var = a
                    if other_var is not None and other_var in variables:
                        j = variables.index(other_var)
                        route_costs[names[j]] = 1
        agents.append(AgentDef(
            name,
            default_hosting_cost=default_hosting,
            hosting_costs=hosting_costs,
            default_route=default_route,
            routes=route_costs,
            capacity=capacity,
        ))
    return agents

"""Elastic-fleet battery (ISSUE 16): multi-host control plane, live
session migration, SLO-driven autoscaling, and weighted fair queuing.

- the WFQ fair scheduler: a tenant's flood advances only its OWN
  virtual-time tag, so a quiet tenant's next request overtakes the
  flood's tail; rejection is shaping (429 accounting), not failure;
- the autoscale policy as pure logic (synthetic replicas, no
  processes): up on p99 breach or deep queues under the ceiling,
  down only after a quiet streak above the floor, inert unless both
  ``slo_p99_ms`` and ``max_replicas`` are armed;
- journal compaction bounds recovery (the ISSUE-16 satellite): a
  rebased checkpoint drops the pre-checkpoint event tail, and the
  compacted file holds ONLY pending records — a dead replica's
  replacement replays pending work, not segment history;
- the migration rebase: a live engine's current problem serializes
  back to dcop yaml and rebuilds to the same cost (the zero-replay
  bundle's correctness core) and bundle validation rejects garbage;
- control-plane identity (``fleet_host_id``), ``--join`` wiring and
  CLI knobs, remote-join address validation;
- a REAL 2-replica/2-host fleet: SIGKILL the session-owning replica
  and (a) a submit that lands on the dead slot before the prober's
  verdict reroutes over ForwardNotSent to a survivor, (b) an open
  SSE stream through the router ends in a clean reconnectable EOF
  (never a hang), (c) the reconnect resumes the stream and acked
  event batches survive;
- the bench sentinel's ``fleet_elastic`` family: empty, malformed
  and too-short histories report instead of crashing, and a real
  regression in the new family still trips the gate.
"""

import json
import os
import signal
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.dcop.yamldcop import dcop_yaml, load_dcop
from pydcop_tpu.engine.multihost import fleet_host_id
from pydcop_tpu.serving import journal as journal_mod
from pydcop_tpu.serving import migration
from pydcop_tpu.serving.router import (
    UP,
    FairScheduler,
    FleetRouter,
    Replica,
)

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

SESSION_PARAMS = {"noise": 0.01, "stability": 0.001,
                  "max_cycles": 500}


def _path_dcop(n: int, seed: int) -> DCOP:
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"elastic_{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n - 1):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[k + 1]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _req(url, method="GET", payload=None, timeout=60):
    data = (json.dumps(payload).encode()
            if payload is not None else None)
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


# ------------------------------------------------------------------ #
# weighted fair queuing


class TestFairScheduler:
    def test_quiet_tenant_overtakes_a_flood(self):
        """Tenant A floods the single slot; tenant B's lone request
        must be admitted right behind the in-flight one — ahead of
        the flood's tail — because B's tag starts at the current
        virtual time while A's tags kept advancing."""
        fair = FairScheduler(fair_share=1)
        assert fair.acquire("A", up=1)     # occupies the only slot
        order = []
        lock = threading.Lock()

        def worker(tenant):
            assert fair.acquire(tenant, up=1, timeout=30)
            with lock:
                order.append(tenant)
            fair.release()

        flood = [threading.Thread(target=worker, args=("A",))
                 for _ in range(4)]
        for t in flood:
            t.start()
        deadline = time.monotonic() + 10
        while fair.stats()["queued"] < 4 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        late = threading.Thread(target=worker, args=("B",))
        late.start()
        while fair.stats()["queued"] < 5 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        fair.release()                     # open the floodgate
        for t in flood + [late]:
            t.join(timeout=30)
        assert len(order) == 5
        # B overtook at least three of A's four queued requests.
        assert order.index("B") <= 1, order

    def test_rejection_is_shaping_not_failure(self):
        fair = FairScheduler(fair_share=1)
        assert fair.acquire("A", up=1)
        assert fair.acquire("B", up=1, timeout=0.05) is False
        stats = fair.stats()
        assert stats["rejected"] == 1
        assert stats["admitted"] == 1
        fair.release()
        assert fair.stats()["active"] == 0

    def test_capacity_scales_with_live_replicas(self):
        fair = FairScheduler(fair_share=2)
        for _ in range(4):                 # up=2 → cap 4
            assert fair.acquire("A", up=2, timeout=0.5)
        assert fair.acquire("A", up=2, timeout=0.05) is False
        assert fair.acquire("A", up=3, timeout=0.5)   # cap now 6


# ------------------------------------------------------------------ #
# autoscale policy (synthetic replicas, no processes)


def _policy_router(n=2, **kw) -> FleetRouter:
    router = FleetRouter(replicas=max(n, 1), **kw)
    for k in range(n):
        replica = Replica(k, None, f"/dev/null-{k}",
                          host_id=f"host{k % max(router.hosts, 1)}")
        replica.status = UP
        replica.port = 1
        router.replicas.append(replica)
    return router


class TestAutoscalePolicy:
    def test_inert_unless_armed(self):
        router = _policy_router(2, slo_p99_ms=100)   # no ceiling
        for _ in range(50):
            router.record_latency(1000.0)
        assert router.autoscale_decision() is None

    def test_scales_up_on_p99_breach(self):
        router = _policy_router(2, slo_p99_ms=100, min_replicas=2,
                                max_replicas=4)
        for _ in range(50):
            router.record_latency(250.0)
        assert router.autoscale_decision() == "up"

    def test_scales_up_on_deep_queues_without_latency(self):
        router = _policy_router(2, slo_p99_ms=100, min_replicas=2,
                                max_replicas=4)
        for r in router.replicas:
            r.queue_depth = 10             # >> 2 * live
        assert router.rolling_p99() is None
        assert router.autoscale_decision() == "up"

    def test_respects_the_ceiling(self):
        router = _policy_router(4, slo_p99_ms=100, max_replicas=4)
        for _ in range(50):
            router.record_latency(250.0)
        assert router.autoscale_decision() is None

    def test_scales_down_only_after_quiet_streak(self):
        router = _policy_router(3, slo_p99_ms=100, min_replicas=2,
                                max_replicas=4,
                                scale_down_quiet_checks=3)
        for _ in range(50):
            router.record_latency(10.0)    # far under slo/2
        assert router.autoscale_decision() is None
        assert router.autoscale_decision() is None
        assert router.autoscale_decision() == "down"

    def test_breach_resets_the_quiet_streak(self):
        router = _policy_router(3, slo_p99_ms=100, min_replicas=2,
                                max_replicas=4,
                                scale_down_quiet_checks=2)
        for _ in range(50):
            router.record_latency(10.0)
        assert router.autoscale_decision() is None   # quiet 1/2
        for _ in range(100):
            router.record_latency(250.0)
        assert router.autoscale_decision() == "up"   # streak reset
        # Flush the whole rolling window (deque maxlen): while any
        # breach sample is still inside it, p99 stays breached and
        # "up" remains the CORRECT verdict.
        for _ in range(600):
            router.record_latency(10.0)
        assert router.autoscale_decision() is None   # quiet 1/2 again

    def test_respects_the_floor(self):
        router = _policy_router(2, slo_p99_ms=100, min_replicas=2,
                                max_replicas=4,
                                scale_down_quiet_checks=1)
        for _ in range(50):
            router.record_latency(10.0)
        assert router.autoscale_decision() is None


# ------------------------------------------------------------------ #
# journal compaction bounds recovery (ISSUE 16 satellite)


class TestCompactionBoundsRecovery:
    def _fill(self, journal_dir, rebased):
        jn = journal_mod
        jn.append_record(journal_dir, jn.accepted_record(
            "r-done", "dcop: a", {"max_cycles": 10}))
        jn.append_record(journal_dir, jn.completed_record(
            "r-done", "FINISHED"))
        jn.append_record(journal_dir, jn.accepted_record(
            "r-pending", "dcop: b", {"max_cycles": 10}))
        jn.append_record(journal_dir, jn.session_open_record(
            "s1", "dcop: base", {"max_cycles": 10}))
        for seq in range(1, 6):
            jn.append_record(journal_dir, jn.session_event_record(
                "s1", seq, [{"type": "noop", "n": seq}]))
        jn.append_record(journal_dir, jn.session_ckpt_record(
            "s1", 3, "/tmp/ck.npz", cycle=7,
            dcop="dcop: rebased" if rebased else None))

    def test_rebased_ckpt_drops_the_pre_checkpoint_tail(self,
                                                        tmp_path):
        jd = str(tmp_path)
        self._fill(jd, rebased=True)
        pending, sessions, _results = journal_mod.compact_journal(jd)
        assert [r["id"] for r in pending] == ["r-pending"]
        (sess,) = sessions
        assert [r["seq"] for r in sess["events"]] == [4, 5]
        assert sess["ckpt"]["dcop"] == "dcop: rebased"

    def test_plain_ckpt_keeps_every_event(self, tmp_path):
        jd = str(tmp_path)
        self._fill(jd, rebased=False)
        _pending, sessions, _results = journal_mod.compact_journal(jd)
        (sess,) = sessions
        assert [r["seq"] for r in sess["events"]] == [1, 2, 3, 4, 5]

    def test_compacted_file_holds_only_pending_records(self,
                                                       tmp_path):
        """THE recovery-time bound: re-scanning the compacted file
        must visit exactly the pending request + the session's
        post-checkpoint replay set — no completed pairs, no
        pre-checkpoint events, no closed sessions."""
        jd = str(tmp_path)
        self._fill(jd, rebased=True)
        journal_mod.append_record(jd, journal_mod.session_open_record(
            "s-closed", "dcop: c", {}))
        journal_mod.append_record(
            jd, journal_mod.session_close_record(
                "s-closed", "MIGRATED"))
        journal_mod.compact_journal(jd)
        records, _bytes, torn = journal_mod.scan_journal(
            os.path.join(jd, journal_mod.JOURNAL_FILE))
        assert not torn
        kinds = sorted((r["kind"], r.get("seq", 0)) for r in records)
        assert kinds == [
            (journal_mod.ACCEPTED, 0),
            (journal_mod.SESSION_CKPT, 3),
            (journal_mod.SESSION_EVENT, 4),
            (journal_mod.SESSION_EVENT, 5),
            (journal_mod.SESSION_OPEN, 0),
        ]
        assert all(r["id"] != "s-closed" for r in records)
        # Idempotent: compacting the compacted file changes nothing.
        pending2, sessions2, _results2 = journal_mod.compact_journal(jd)
        assert [r["id"] for r in pending2] == ["r-pending"]
        assert [r["seq"] for r in sessions2[0]["events"]] == [4, 5]


# ------------------------------------------------------------------ #
# crash-durable results: a 202 whose solve FINISHED moments before
# the kill must still resolve to its 200 on the replacement process


class TestDurableResults:
    def test_completed_with_result_survives_compaction(self,
                                                       tmp_path):
        jd = str(tmp_path)
        jn = journal_mod
        jn.append_record(jd, jn.accepted_record("r1", "dcop: a", {}))
        jn.append_record(jd, jn.completed_record(
            "r1", "FINISHED",
            result={"id": "r1", "status": "FINISHED", "cost": 3.0}))
        jn.append_record(jd, jn.accepted_record("r2", "dcop: b", {}))
        # Payload-less tombstone (pre-ISSUE-16 journals): dropped.
        jn.append_record(jd, jn.accepted_record("r3", "dcop: c", {}))
        jn.append_record(jd, jn.completed_record("r3", "FINISHED"))
        pending, _sessions, results = jn.compact_journal(jd)
        assert [r["id"] for r in pending] == ["r2"]
        assert [r["id"] for r in results] == ["r1"]
        recs, _bytes, torn = jn.scan_journal(
            os.path.join(jd, jn.JOURNAL_FILE))
        assert not torn
        assert sorted((r["kind"], r["id"]) for r in recs) == [
            (jn.ACCEPTED, "r2"), (jn.COMPLETED, "r1")]

    def test_retention_keeps_the_newest_tail(self, tmp_path):
        jd = str(tmp_path)
        jn = journal_mod
        for i in range(jn.COMPLETED_KEEP + 40):
            jn.append_record(jd, jn.completed_record(
                f"x{i}", "FINISHED", result={"id": f"x{i}"}))
        _p, _s, results = jn.compact_journal(jd)
        assert len(results) == jn.COMPLETED_KEEP
        assert results[0]["id"] == "x40"
        assert results[-1]["id"] == f"x{jn.COMPLETED_KEEP + 39}"

    def test_recovered_service_serves_the_predecessors_outcome(
            self, tmp_path):
        """Kill-equivalent crash AFTER a solve finished: the
        replacement's /result-path lookups (result/status/trace_id)
        answer from the journal, and the outcome equals the
        predecessor's."""
        from pydcop_tpu.serving.service import SolveService

        d = str(tmp_path)
        svc = SolveService(journal_dir=d).start()
        rid = svc.submit(load_dcop(dcop_yaml(_path_dcop(8, 11))),
                         params={"max_cycles": 30})
        res = svc.result(rid, wait=120)
        assert res is not None and res["status"] == "FINISHED"
        # SIGKILL-equivalent: no drain, no close record — just stop
        # the scheduler thread and slam the journal handle shut.
        svc._scheduler._stop.set()
        svc._journal.close()

        svc2 = SolveService(journal_dir=d, recover=True).start()
        try:
            got = svc2.result(rid)
            assert got is not None
            assert got["status"] == "FINISHED"
            assert got["cost"] == res["cost"]
            assert got["assignment"] == res["assignment"]
            assert svc2.status(rid) == "FINISHED"
            assert svc2.trace_id(rid) == res["trace_id"]
            with pytest.raises(KeyError):
                svc2.result("never-acked")
        finally:
            svc2.stop(drain=False)


# ------------------------------------------------------------------ #
# migration rebase + bundle validation


class TestMigrationBundle:
    def test_rebase_roundtrips_to_the_same_cost(self):
        from pydcop_tpu.engine.dynamic import build_dynamic_engine
        from pydcop_tpu.serving.sessions import apply_event_batch

        rng = np.random.default_rng(5)
        dcop = _path_dcop(8, 5)
        engine = build_dynamic_engine(dcop, dict(SESSION_PARAMS))
        engine.run(max_cycles=500)
        batch = [{"type": "change_factor", "name": "c3",
                  "table": rng.integers(0, 10, size=(3, 3))
                  .astype(float).tolist()}]
        _a, _t, err = apply_event_batch(engine, batch)
        assert err is None
        res = engine.run(max_cycles=500)
        cost = engine.cost(res.assignment)

        rebased = migration.engine_dcop_yaml(engine)
        clone = build_dynamic_engine(load_dcop(rebased),
                                     dict(SESSION_PARAMS))
        res2 = clone.run(max_cycles=500)
        assert clone.cost(res2.assignment) == cost

    def test_bundle_roundtrips_fields(self):
        bundle = migration.build_bundle(
            "s1", "t1", "dcop: x", True, {"max_cycles": 10},
            seq=4, cycle=9,
            events=[{"seq": 4, "events": []}],
            npz_bytes=b"\x00\x01", ckpt_seq=3)
        blob = json.loads(json.dumps(bundle))   # wire round-trip
        assert blob["session_id"] == "s1"
        assert blob["rebased"] is True
        assert blob["seq"] == 4 and blob["ckpt_seq"] == 3
        assert migration._bundle_npz_bytes(blob) == b"\x00\x01"

    def test_install_rejects_garbage(self):
        with pytest.raises(ValueError):
            migration.install_bundle(None, {"version": 99})
        with pytest.raises(ValueError):
            migration.install_bundle(
                None, {"version": migration.BUNDLE_VERSION,
                       "session_id": ""})


# ------------------------------------------------------------------ #
# control-plane identity, join wiring, CLI knobs


class TestControlPlane:
    def test_fleet_host_id_env_override(self, monkeypatch):
        monkeypatch.setenv("PYDCOP_HOST_ID", "rack7")
        assert fleet_host_id() == "rack7"
        monkeypatch.delenv("PYDCOP_HOST_ID")
        assert fleet_host_id() == socket.gethostname()

    def test_register_remote_rejects_bad_address(self):
        router = _policy_router(1)
        with pytest.raises(ValueError):
            router.register_remote("not-an-address")

    def test_join_excludes_local_fleet(self):
        from pydcop_tpu import api

        with pytest.raises(ValueError):
            api.serve(replicas=2, join="http://127.0.0.1:1/")

    def test_elastic_cli_knobs_parse(self):
        import argparse

        from pydcop_tpu.commands import serve as serve_cmd

        parser = argparse.ArgumentParser()
        sub = parser.add_subparsers()
        serve_cmd.set_parser(sub)
        args = parser.parse_args(
            ["serve", "--hosts", "2", "--join",
             "http://127.0.0.1:9", "--host_id", "hostX",
             "--slo_p99_ms", "250", "--min_replicas", "2",
             "--max_replicas", "6"])
        assert args.hosts == 2
        assert args.join == "http://127.0.0.1:9"
        assert args.host_id == "hostX"
        assert args.slo_p99_ms == 250.0
        assert args.min_replicas == 2
        assert args.max_replicas == 6

    def test_cli_rejects_join_with_local_fleet(self):
        import argparse

        from pydcop_tpu.commands import serve as serve_cmd

        parser = argparse.ArgumentParser()
        sub = parser.add_subparsers()
        serve_cmd.set_parser(sub)
        args = parser.parse_args(
            ["serve", "--join", "http://127.0.0.1:9",
             "--replicas", "2"])
        assert serve_cmd.run_cmd(args) == 2

    def test_announce_join_retries_then_gives_up(self):
        from pydcop_tpu import api

        # Nothing listens on this port: every attempt fails and the
        # worker stays standalone instead of crashing.
        assert api._announce_join(
            "http://127.0.0.1:9", "http://127.0.0.1:8",
            host_id="h") is False


# ------------------------------------------------------------------ #
# the real fleet: host kill under an open SSE stream


class TestFleetKillEndToEnd:
    def test_forward_retry_sse_eof_and_event_survival(self,
                                                      tmp_path):
        """One fleet, three ISSUE-16 satellites:

        (a) a request that picks the just-killed replica before the
            prober's verdict reroutes (ForwardNotSent) to a survivor
            instead of failing;
        (c) the SSE stream proxied through the router for a session
            owned by the victim ends with a clean EOF within the
            probe window — never a hang — and a reconnect resumes
            the stream on the new owner;
        plus the durability core: the acked event batch survives the
        kill (the next PATCH lands as seq 2).
        """
        from pydcop_tpu import api

        # A wide heartbeat keeps the just-killed replica in the
        # candidate set for ~a beat: the window in which a submit can
        # actually pick the dead slot and exercise the
        # ForwardNotSent reroute (satellite a).
        handle = api.serve(port=0, replicas=2, hosts=2,
                           batch_window_s=0.05, max_batch=8,
                           heartbeat_s=1.5,
                           journal_dir=str(tmp_path / "jnl"))
        try:
            url = handle.url
            router = handle.router
            assert {r.host_id for r in router.replicas} \
                == {"host0", "host1"}

            rng = np.random.default_rng(2)
            dcop = _path_dcop(10, 1707)
            status, body = _req(
                url + "/session", "POST",
                {"dcop": dcop_yaml(dcop),
                 "params": SESSION_PARAMS})
            assert status == 201, body
            sid = body["session_id"]
            batches = [
                [{"type": "change_factor",
                  "name": f"c{int(rng.integers(9))}",
                  "table": rng.integers(0, 10, size=(3, 3))
                  .astype(float).tolist()}]
                for _ in range(2)
            ]
            status, ack = _req(
                url + f"/session/{sid}/events", "PATCH",
                {"events": batches[0], "wait": True,
                 "timeout": 30.0})
            assert status == 200 and ack["seq"] == 1, ack

            # Open the SSE stream THROUGH the router before the kill.
            stream = urllib.request.urlopen(
                url + f"/session/{sid}/events", timeout=30)
            assert stream.status == 200

            victim = router.pinned(sid, router._session_pins)
            assert victim is not None
            os.kill(victim.proc.pid, signal.SIGKILL)

            # (a) ForwardNotSent reroute: async submits fired in the
            # window between the SIGKILL and the prober's verdict.
            # Distinct structures rendezvous ~evenly across both
            # slots, so some pick the dead one — its refused connect
            # must reroute to the survivor (202 to the client, never
            # a failure), not surface an error.
            acked = []
            for s in range(200):
                if router.reroutes >= 1 or victim.status != UP:
                    break
                solo = _path_dcop(6 + (s % 12), 40 + s)
                status, body = _req(
                    url + "/solve", "POST",
                    {"dcop": dcop_yaml(solo),
                     "params": {"max_cycles": 60}})
                assert status == 202, (s, status, body)
                acked.append(body["id"])
            assert router.reroutes >= 1, \
                (router.reroutes, victim.status, len(acked))
            # The fleet keeps serving end-to-end through the death.
            status, body = _req(
                url + "/solve", "POST",
                {"dcop": dcop_yaml(_path_dcop(12, 77)),
                 "wait": True, "timeout": 60,
                 "params": {"max_cycles": 60}})
            assert status == 200 \
                and body["status"] == "FINISHED", body

            # (c) clean reconnectable EOF, not a hang: the proxy
            # breaks the relay once the prober declares the owner
            # dead (read timeout max(8*hb, 3) + verdict ~8 beats).
            t0 = time.monotonic()
            while True:
                chunk = stream.read(65536)
                if not chunk:
                    break
                assert time.monotonic() - t0 < 30, \
                    "SSE stream hung past the probe window"
            stream.close()
            assert time.monotonic() - t0 < 30

            # Reconnect resumes: the session moved (adopted by the
            # survivor or replayed by the restart); the stream must
            # come back 200 and the acked batch must still be there.
            deadline = time.monotonic() + 120
            reconnected = False
            while time.monotonic() < deadline and not reconnected:
                try:
                    s2 = urllib.request.urlopen(
                        url + f"/session/{sid}/events", timeout=10)
                    if s2.status == 200:
                        reconnected = True
                        s2.close()
                except (urllib.error.HTTPError, OSError):
                    time.sleep(0.2)
            assert reconnected, "SSE reconnect never succeeded"

            deadline = time.monotonic() + 120
            while True:
                status, ack2 = _req(
                    url + f"/session/{sid}/events", "PATCH",
                    {"events": batches[1], "wait": True,
                     "timeout": 30.0})
                if status == 200:
                    break
                assert status in (409, 503), (status, ack2)
                assert time.monotonic() < deadline, (status, ack2)
                time.sleep(0.2)
            assert ack2["seq"] == 2, ack2
            status, final = _req(url + f"/session/{sid}", "DELETE")
            assert status == 200, final
        finally:
            handle.stop()


# ------------------------------------------------------------------ #
# worker admin surface validation (no fleet needed)


class TestAdminSurface:
    def test_admin_endpoint_validation(self):
        from pydcop_tpu import api

        handle = api.serve(port=0, batch_window_s=0.02)
        try:
            url = handle.url
            status, body = _req(url + "/admin/export_session",
                                "POST", {"session_id": "nope"})
            assert status == 404, body
            status, body = _req(url + "/admin/export_session",
                                "POST", {})
            assert status == 400, body
            status, body = _req(url + "/admin/no_such_op",
                                "POST", {})
            assert status == 404, body
            status, body = _req(url + "/admin/import_session",
                                "POST", {"version": 99})
            assert status == 400, body
        finally:
            handle.stop()


# ------------------------------------------------------------------ #
# bench sentinel: the brand-new fleet_elastic family


def _load_sentinel():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_sentinel_under_test",
        os.path.join(REPO, "tools", "bench_sentinel.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _round(n, value=100.0, fleet_elastic=None, backend="cpu"):
    parsed = {"value": value, "backend": backend}
    if fleet_elastic is not None:
        parsed["fleet_elastic_problems_per_sec"] = fleet_elastic
        parsed["leg_backends"] = {
            "fleet_elastic": {"backend": backend}}
    return {"n": n, "parsed": parsed}


class TestSentinelNewFamily:
    def test_empty_history_reports_instead_of_crashing(self,
                                                       tmp_path):
        sentinel = _load_sentinel()
        report = sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        assert report["series"] == {}

    def test_malformed_history_is_skipped(self, tmp_path):
        sentinel = _load_sentinel()
        (tmp_path / "BENCH_r1.json").write_text("[1, 2]")
        (tmp_path / "BENCH_r2.json").write_text(
            '{"parsed": "not a dict"}')
        (tmp_path / "BENCH_r3.json").write_text("not json at all")
        (tmp_path / "BENCH_TPU_LAST.json").write_text("[]")
        report = sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        assert len(report["skipped"]) == 4

    def test_new_family_with_short_history_is_insufficient(
            self, tmp_path):
        sentinel = _load_sentinel()
        (tmp_path / "BENCH_r1.json").write_text(
            json.dumps(_round(1, fleet_elastic=5.0)))
        report = sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        verdicts = report["series"]
        assert verdicts["fleet_elastic:cpu"]["verdict"] \
            == "insufficient"

    def test_regression_in_the_new_family_trips_the_gate(
            self, tmp_path):
        sentinel = _load_sentinel()
        for n, v in enumerate([10.0, 10.0, 10.0, 3.0], start=1):
            (tmp_path / f"BENCH_r{n}.json").write_text(
                json.dumps(_round(n, fleet_elastic=v)))
        report = sentinel.run_check(str(tmp_path))
        assert report["failed"] is True
        assert report["series"]["fleet_elastic:cpu"]["verdict"] \
            == "regressed"

    def test_healthy_new_family_passes(self, tmp_path):
        sentinel = _load_sentinel()
        for n, v in enumerate([10.0, 10.5, 9.8, 10.2], start=1):
            (tmp_path / f"BENCH_r{n}.json").write_text(
                json.dumps(_round(n, fleet_elastic=v)))
        report = sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        assert report["series"]["fleet_elastic:cpu"]["verdict"] \
            == "ok"

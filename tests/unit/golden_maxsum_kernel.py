"""Frozen copy of the round-4 MaxSum superstep pipeline.

This is the executable perf/semantics baseline for
``test_perf_regression.py``: the live kernel (pydcop_tpu/ops/maxsum.py)
is timed against this copy IN THE SAME PROCESS, so the ratio is immune
to machine-load drift (the absolute cycles/s on this box moved ~30%
between rounds from load alone — BENCH_r01 vs r03 — which is exactly
what a wall-clock budget test would false-alarm on).

Do NOT update this file when optimizing the live kernel unless the
regression test's parity assertion demands it: it exists to stay
behind.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import BIG, CompiledFactorGraph

Msgs = Tuple[jnp.ndarray, ...]

SAME_COUNT = 4


class GoldenState(NamedTuple):
    v2f: Msgs
    f2v: Msgs
    v2f_count: Msgs
    f2v_count: Msgs
    stable: jnp.ndarray
    cycle: jnp.ndarray


def init_state(graph: CompiledFactorGraph) -> GoldenState:
    d = graph.var_costs.shape[1]
    dtype = graph.var_costs.dtype
    zeros = tuple(
        jnp.zeros(b.var_ids.shape + (d,), dtype=dtype)
        for b in graph.buckets
    )
    counts = tuple(
        jnp.zeros(b.var_ids.shape, dtype=jnp.int32)
        for b in graph.buckets
    )
    return GoldenState(
        v2f=zeros, f2v=zeros, v2f_count=counts, f2v_count=counts,
        stable=jnp.asarray(False),
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def _edge_match(new, old, stability, valid):
    delta = jnp.abs(new - old)
    s = jnp.abs(new + old)
    ok = (2 * delta < stability * s) | (delta == 0)
    return jnp.all(ok | ~valid, axis=-1)


def _send_or_suppress(cand, prev, count, stability, valid, first):
    match = _edge_match(cand, prev, stability, valid) & ~first
    send = ~match | (count < SAME_COUNT)
    sent = jnp.where(send[..., None], cand, prev)
    new_count = jnp.where(
        match, jnp.minimum(count + 1, SAME_COUNT + 1), 1
    )
    return sent, new_count, match


def factor_to_var(graph, v2f):
    out = []
    for bucket, msgs in zip(graph.buckets, v2f):
        f, arity, d = msgs.shape
        total = bucket.costs
        for q in range(arity):
            shape = [f] + [1] * arity
            shape[q + 1] = d
            total = total + msgs[:, q].reshape(shape)
        outs_p = []
        for p in range(arity):
            axes = tuple(i + 1 for i in range(arity) if i != p)
            reduced = jnp.min(total, axis=axes) if axes else total
            outs_p.append(reduced - msgs[:, p])
        out.append(jnp.stack(outs_p, axis=1))
    return tuple(out)


def aggregate_beliefs(graph, f2v):
    n_segments = graph.var_costs.shape[0]
    d = graph.var_costs.shape[1]
    sums = jnp.zeros_like(graph.var_costs)
    for bucket, msgs in zip(graph.buckets, f2v):
        flat = msgs.reshape(-1, d)
        seg = bucket.var_ids.reshape(-1)
        sums = sums + jax.ops.segment_sum(
            flat, seg, num_segments=n_segments
        )
    return graph.var_costs + sums, sums


def var_to_factor(graph, f2v, beliefs, sums):
    out = []
    for bucket, msgs in zip(graph.buckets, f2v):
        valid = graph.var_valid[bucket.var_ids]
        raw = beliefs[bucket.var_ids] - msgs
        factor_sum = sums[bucket.var_ids] - msgs
        n_valid = jnp.maximum(
            jnp.sum(valid, axis=-1, keepdims=True), 1
        )
        avg = (
            jnp.sum(jnp.where(valid, factor_sum, 0.0), axis=-1,
                    keepdims=True)
            / n_valid
        )
        out.append(jnp.where(valid, raw - avg, BIG))
    return tuple(out)


def select_values(graph, beliefs):
    masked = jnp.where(graph.var_valid, beliefs, jnp.inf)
    return jnp.argmin(masked[:-1], axis=1).astype(jnp.int32)


def _damp(new, old, damping, first):
    return tuple(
        jnp.where(first, n, damping * o + (1.0 - damping) * n)
        for n, o in zip(new, old)
    )


def superstep(state, graph, *, damping, damp_vars, damp_factors,
              stability):
    first = state.cycle == 0
    valids = tuple(
        graph.var_valid[b.var_ids] for b in graph.buckets
    )
    f2v_cand = factor_to_var(graph, state.v2f)
    if damp_factors and damping > 0:
        f2v_cand = _damp(f2v_cand, state.f2v, damping, first)
    beliefs, sums = aggregate_beliefs(graph, state.f2v)
    v2f_cand = var_to_factor(graph, state.f2v, beliefs, sums)
    if damp_vars and damping > 0:
        v2f_cand = _damp(v2f_cand, state.v2f, damping, first)
    f2v_new, f2v_count = [], []
    v2f_new, v2f_count = [], []
    all_match = jnp.asarray(True)
    for i, valid in enumerate(valids):
        sent, cnt, match = _send_or_suppress(
            f2v_cand[i], state.f2v[i], state.f2v_count[i],
            stability, valid, first)
        f2v_new.append(sent)
        f2v_count.append(cnt)
        all_match = all_match & jnp.all(match | ~jnp.any(valid, -1))
        sent, cnt, match = _send_or_suppress(
            v2f_cand[i], state.v2f[i], state.v2f_count[i],
            stability, valid, first)
        v2f_new.append(sent)
        v2f_count.append(cnt)
        all_match = all_match & jnp.all(match | ~jnp.any(valid, -1))
    return GoldenState(
        v2f=tuple(v2f_new),
        f2v=tuple(f2v_new),
        v2f_count=tuple(v2f_count),
        f2v_count=tuple(f2v_count),
        stable=all_match & ~first,
        cycle=state.cycle + 1,
    )


def run_maxsum(graph, max_cycles, *, damping=0.5, damp_vars=True,
               damp_factors=True, stability=0.1):
    def step(state):
        return superstep(
            state, graph, damping=damping, damp_vars=damp_vars,
            damp_factors=damp_factors, stability=stability,
        )

    state = jax.lax.while_loop(
        lambda s: s.cycle < max_cycles, step, init_state(graph)
    )
    beliefs, _ = aggregate_beliefs(graph, state.f2v)
    return state, select_values(graph, beliefs)

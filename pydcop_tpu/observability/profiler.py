"""XLA cost attribution: measured flops/bytes/peak-HBM per compiled
program, keyed by the engine's jit cache key.

The roofline numbers in ``engine/roofline.py`` are *models* — hand
derivations from bucket shapes that assume perfect fusion.  XLA itself
knows better: every compiled executable carries a ``cost_analysis()``
(flops, bytes accessed, transcendentals) and a ``memory_analysis()``
(argument/output/temp sizes — the peak-HBM story) computed from the
optimized HLO.  This module captures both per compiled segment and
feeds them to the metrics registry, the ``jit_compile`` trace span,
``DeviceRunResult.metrics`` and (via ``roofline_report(measured=...)``)
the benchmark's utilization claims — measured, not estimated.

Capture discipline: the running jit cache must never be disturbed, so
the profiler lowers the SAME jitted callable against
``ShapeDtypeStruct`` avals (no device buffers touched — safe even when
the arguments were donated) and compiles a throwaway AOT executable
purely for its analysis tables.  That is one extra compile per cache
key, paid only while profiling is enabled; the capture happens OUTSIDE
the engine's timed interval so measured rates are unpolluted.  Backends
that return nothing (or raise — the analysis API is not part of JAX's
stability contract) produce an explicit ``{"available": False,
"reason": ...}`` marker instead of silently missing data, so a reader
can distinguish "not profiled" from "profiled, backend said nothing".

Enablement: :class:`~pydcop_tpu.observability.ObservabilitySession`
turns the profiler on for observed solves; ``PYDCOP_XLA_PROFILE=1``
forces it on (bench.py), ``=0`` forces it off regardless of session.
"""

import os
import threading
import time
from typing import Any, Dict, Optional

_FLOPS_KEYS = ("flops",)
_BYTES_KEYS = ("bytes accessed",)


def _env_override() -> Optional[bool]:
    raw = os.environ.get("PYDCOP_XLA_PROFILE")
    if raw is None:
        return None
    return raw not in ("0", "false", "no", "")


def key_str(key: Any) -> str:
    """Canonical string form of a jit cache key (used as the metrics
    label and the ``DeviceRunResult.metrics['xla_cost']`` key)."""
    return str(key)


class XlaCostProfiler:
    """Captures per-executable XLA cost/memory analysis, keyed by the
    engine's jit cache key.

    ``capture`` is called by ``timed_jit_call`` on every COLD dispatch
    (once per cache key); entries accumulate in :attr:`entries` until
    :meth:`clear`.  All failures are folded into unavailable markers —
    profiling must never break a solve.
    """

    def __init__(self):
        self._enabled = False
        self._lock = threading.Lock()
        self.entries: Dict[str, Dict[str, Any]] = {}

    @property
    def enabled(self) -> bool:
        env = _env_override()
        return self._enabled if env is None else env

    @enabled.setter
    def enabled(self, value: bool):
        self._enabled = bool(value)

    # -- capture -------------------------------------------------------- #

    def capture(self, key: Any, fn, args: tuple) -> Dict[str, Any]:
        """Lower+compile ``fn`` against the avals of ``args`` and
        record its cost/memory analysis under ``key``.

        Never raises; returns the entry (an unavailable marker when
        the backend yields nothing).  Idempotent per key — a re-cold
        dispatch (fresh engine, same key string) overwrites with
        identical data.
        """
        t0 = time.perf_counter()
        try:
            entry = self._analyze(fn, args)
        except Exception as exc:  # noqa: BLE001 — analysis API unstable
            entry = {
                "available": False,
                "reason": f"{type(exc).__name__}: {exc}"[:200],
            }
        entry["capture_s"] = round(time.perf_counter() - t0, 6)
        skey = key_str(key)
        with self._lock:
            self.entries[skey] = entry
        self._export_metrics(skey, entry)
        return entry

    @staticmethod
    def _analyze(fn, args: tuple) -> Dict[str, Any]:
        import jax

        def aval(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return x

        # The batched/lane dispatch paths wrap their jitted callable
        # in functools.partial to bind static kwargs; partials have no
        # ``.lower``, so unwrap and re-apply the bound arguments —
        # without this the serving hot path (exactly where efficiency
        # attainment matters most) never got a cost entry.
        kwargs: Dict[str, Any] = {}
        target = fn
        if not hasattr(target, "lower"):
            inner = getattr(fn, "func", None)
            if inner is not None and hasattr(inner, "lower"):
                args = tuple(getattr(fn, "args", ()) or ()) + args
                kwargs = dict(getattr(fn, "keywords", {}) or {})
                target = inner
        compiled = target.lower(
            *jax.tree_util.tree_map(aval, args), **kwargs).compile()
        cost = compiled.cost_analysis()
        # Per-device list on some versions, plain dict on others.
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        entry: Dict[str, Any] = {}
        if isinstance(cost, dict):
            for k in _FLOPS_KEYS:
                if k in cost:
                    entry["flops"] = float(cost[k])
                    break
            for k in _BYTES_KEYS:
                if k in cost:
                    entry["bytes_accessed"] = float(cost[k])
                    break
            if "transcendentals" in cost:
                entry["transcendentals"] = float(cost["transcendentals"])
        mem = None
        try:
            mem = compiled.memory_analysis()
        except Exception:  # noqa: BLE001
            mem = None
        if mem is not None:
            for attr, out in (
                ("argument_size_in_bytes", "argument_bytes"),
                ("output_size_in_bytes", "output_bytes"),
                ("temp_size_in_bytes", "temp_bytes"),
                ("generated_code_size_in_bytes", "code_bytes"),
            ):
                val = getattr(mem, attr, None)
                if val is not None:
                    entry[out] = float(val)
            # Peak device footprint of one dispatch: live arguments +
            # outputs + transient scratch.  (Donation aliases argument
            # and output buffers, so this is an upper bound.)
            peak = sum(entry.get(k, 0.0) for k in
                       ("argument_bytes", "output_bytes", "temp_bytes"))
            if peak:
                entry["peak_bytes"] = peak
        if not entry:
            return {
                "available": False,
                "reason": "backend returned no cost/memory analysis",
            }
        entry["available"] = True
        return entry

    def _export_metrics(self, skey: str, entry: Dict[str, Any]):
        from pydcop_tpu.observability.metrics import registry
        from pydcop_tpu.observability.trace import tracer

        if tracer.enabled:
            tracer.instant("xla_cost", "engine", key=skey, **{
                k: v for k, v in entry.items() if k != "capture_s"
            })
        # Key-labeled series are unbounded across engines, so — like
        # the runner's per-key jit accounting — they are opt-in
        # detail: only recorded while metrics were actually requested
        # (registry.active).  A bench/PYDCOP_XLA_PROFILE=1 run that
        # never activates the registry still gets its entries through
        # DeviceRunResult.metrics, without leaking stale samples into
        # a later solve's .prom dump.
        if not registry.active:
            return
        if entry.get("available"):
            if entry.get("flops"):
                registry.counter(
                    "pydcop_xla_flops_total",
                    "XLA-measured flops of compiled programs "
                    "(one increment per cold compile)",
                ).inc(entry["flops"], key=skey)
            if entry.get("bytes_accessed"):
                registry.counter(
                    "pydcop_xla_bytes_total",
                    "XLA-measured bytes accessed by compiled programs",
                ).inc(entry["bytes_accessed"], key=skey)
            if entry.get("peak_bytes"):
                registry.gauge(
                    "pydcop_xla_peak_bytes",
                    "Peak device bytes (args+outputs+temps) of a "
                    "compiled program",
                ).set(entry["peak_bytes"], key=skey)
        else:
            registry.counter(
                "pydcop_xla_analysis_unavailable_total",
                "Cold compiles whose backend returned no XLA "
                "cost/memory analysis",
            ).inc()

    # -- readback ------------------------------------------------------- #

    def get(self, key: Any) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.entries.get(key_str(key))

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self.entries.items()}

    def clear(self):
        with self._lock:
            self.entries = {}


profiler = XlaCostProfiler()


def get_profiler() -> XlaCostProfiler:
    return profiler

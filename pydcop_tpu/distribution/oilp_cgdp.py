"""oilp_cgdp: optimal ILP for the Constraint-Graph Distribution Problem.

Reference parity: pydcop/distribution/oilp_cgdp.py (AAMAS-18).  The
weighted MILP over RATIO * communication + (1-RATIO) * hosting costs,
with one SECP-friendly twist the generic ilp_compref model does not
have: any computation with a hosting cost of 0 on some agent is forced
onto that agent before solving (reference :174-185 "Force computation
with hosting cost of 0 to be hosted on that agent").
"""

from pydcop_tpu.distribution._base import (
    RATIO_HOST_COMM,
    distribution_cost_impl,
    ilp_place,
)


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None,
               timeout=None, **_):
    agentsdef = list(agentsdef)
    pinned = {}
    for node in computation_graph.nodes:
        for agent in agentsdef:
            if agent.hosting_cost(node.name) == 0:
                pinned[node.name] = agent.name
                break
    return ilp_place(
        computation_graph, agentsdef, hints,
        computation_memory, communication_load,
        timeout=timeout,
        comm_weight=RATIO_HOST_COMM,
        hosting_weight=1 - RATIO_HOST_COMM,
        pinned=pinned,
    )


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    return distribution_cost_impl(
        distribution, computation_graph, agentsdef,
        computation_memory, communication_load)

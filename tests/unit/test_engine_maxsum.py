"""Engine + MaxSum kernel tests.

The oracle for kernel semantics is a naive dict-based reimplementation of
the reference's message updates (factor_costs_for_var maxsum.py:382,
costs_for_factor :623) evaluated on tiny graphs.
"""

import itertools

import jax
import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable, VariableWithCostFunc
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.compile import BIG, compile_dcop, compile_factor_graph
from pydcop_tpu.engine.runner import MaxSumEngine
from pydcop_tpu.engine.sharding import make_mesh
from pydcop_tpu.ops import maxsum as ops


def _tiny_dcop():
    d = Domain("colors", "", ["R", "G"])
    v1 = VariableWithCostFunc("v1", d, "-0.1 if v1 == 'R' else 0.1")
    v2 = VariableWithCostFunc("v2", d, "-0.1 if v2 == 'G' else 0.1")
    v3 = VariableWithCostFunc("v3", d, "-0.1 if v3 == 'G' else 0.1")
    c1 = constraint_from_str("c1", "1 if v1 == v2 else 0", [v1, v2])
    c2 = constraint_from_str("c2", "1 if v2 == v3 else 0", [v2, v3])
    dcop = DCOP("tiny")
    dcop.add_constraint(c1)
    dcop.add_constraint(c2)
    return dcop


class TestCompile:
    def test_shapes_and_padding(self):
        dcop = _tiny_dcop()
        graph, meta = compile_dcop(dcop)
        assert graph.n_vars == 3
        assert graph.dmax == 2
        assert len(graph.buckets) == 1  # all arity-2
        b = graph.buckets[0]
        assert b.costs.shape == (2, 2, 2)
        assert b.var_ids.shape == (2, 2)
        assert meta.factor_names == ("c1", "c2")

    def test_mixed_arity_buckets(self):
        d = Domain("d", "", [0, 1, 2])
        x, y, z = (Variable(n, d) for n in "xyz")
        c1 = constraint_from_str("c1", "x + y", [x, y])
        c2 = constraint_from_str("c2", "x * y * z", [x, y, z])
        c3 = constraint_from_str("c3", "z", [z])
        graph, meta = compile_factor_graph([x, y, z], [c1, c2, c3])
        arities = sorted(b.arity for b in graph.buckets)
        assert arities == [1, 2, 3]

    def test_domain_padding_big(self):
        d2 = Domain("d2", "", [0, 1])
        d3 = Domain("d3", "", [0, 1, 2])
        x, y = Variable("x", d2), Variable("y", d3)
        c = constraint_from_str("c", "x + y", [x, y])
        graph, _ = compile_factor_graph([x, y], [c])
        costs = graph.buckets[0].costs
        # x axis padded at index 2:
        assert np.all(costs[0, 2, :] == BIG)
        assert costs[0, 1, 2] == 3  # valid corner

    def test_row_padding(self):
        dcop = _tiny_dcop()
        graph, meta = compile_dcop(dcop, pad_to=8)
        b = graph.buckets[0]
        assert b.costs.shape[0] == 8
        assert np.all(b.var_ids[2:] == graph.n_vars)  # sentinel
        assert np.all(b.costs[2:] == 0)
        assert meta.bucket_sizes == (2,)

    def test_max_mode_negates(self):
        d = Domain("d", "", [0, 1])
        x = Variable("x", d)
        c = constraint_from_str("c", "x * 5", [x])
        dcop = DCOP("t", objective="max")
        dcop.add_constraint(c)
        graph, meta = compile_dcop(dcop)
        assert graph.buckets[0].costs[0, 1] == -5
        assert meta.mode == "max"

    def test_zero_ary_folded(self):
        d = Domain("d", "", [0, 1])
        x = Variable("x", d)
        from pydcop_tpu.dcop.relations import ZeroAryRelation

        c = constraint_from_str("c", "x", [x])
        z = ZeroAryRelation("z", 7.0)
        graph, meta = compile_factor_graph([x], [c, z])
        assert meta.constant_cost == 7.0
        assert len(graph.buckets) == 1


def _naive_factor_msg(table, in_msgs, target_pos):
    """Reference semantics: min over other vars' assignments of
    table + sum of their incoming messages (maxsum.py:382)."""
    arity = table.ndim
    dom = table.shape
    out = []
    for d in range(dom[target_pos]):
        best = np.inf
        ranges = [range(dom[q]) if q != target_pos else [d]
                  for q in range(arity)]
        for idx in itertools.product(*ranges):
            val = table[idx]
            for q in range(arity):
                if q != target_pos:
                    val += in_msgs[q][idx[q]]
            best = min(best, val)
        out.append(best)
    return np.array(out)


class TestKernelsVsNaive:
    def test_factor_to_var_matches_naive(self):
        rng = np.random.default_rng(0)
        d = Domain("d", "", [0, 1, 2])
        x, y, z = (Variable(n, d) for n in "xyz")
        c = constraint_from_str("c", "x*9 + y*3 + z", [x, y, z])
        graph, _ = compile_factor_graph([x, y, z], [c])
        msgs = rng.normal(size=(1, 3, 3)).astype(np.float32)
        f2v = ops.factor_to_var(graph, (msgs,))
        table = np.asarray(graph.buckets[0].costs[0])
        for p in range(3):
            expected = _naive_factor_msg(
                table, [msgs[0, q] for q in range(3)], p
            )
            np.testing.assert_allclose(
                np.asarray(f2v[0][0, p]), expected, rtol=1e-5
            )

    def test_var_to_factor_normalization(self):
        """v2f = var_cost + sum(other factors) - mean(sum other factors)
        (reference maxsum.py:623-674)."""
        dcop = _tiny_dcop()
        graph, meta = compile_dcop(dcop)
        rng = np.random.default_rng(1)
        f2v = (rng.normal(size=(2, 2, 2)).astype(np.float32),)
        beliefs, sums = ops.aggregate_beliefs(graph, f2v)
        v2f = ops.var_to_factor(graph, f2v, beliefs, sums)

        # Check message v2 -> c1 (factor 0, position 1 holds v2).
        i_v2 = meta.var_names.index("v2")
        assert graph.buckets[0].var_ids[0, 1] == i_v2
        # v2 receives from c1 (slot [0,1]) and c2 (slot [1,0]).
        assert graph.buckets[0].var_ids[1, 0] == i_v2
        other = np.asarray(f2v[0][1, 0])           # from c2
        var_cost = np.array([0.1, -0.1])           # v2 costs
        expected = var_cost + other - other.mean()
        np.testing.assert_allclose(
            np.asarray(v2f[0][0, 1]), expected, rtol=1e-5
        )

    def test_select_values_tie_breaks_first(self):
        d = Domain("d", "", [0, 1])
        x = Variable("x", d)
        c = constraint_from_str("c", "x * 0", [x])
        graph, _ = compile_factor_graph([x], [c])
        beliefs, _ = ops.aggregate_beliefs(
            graph, (np.zeros((1, 1, 2), np.float32),)
        )
        vals = ops.select_values(graph, beliefs)
        assert int(vals[0]) == 0


class TestEndToEnd:
    def test_tiny_coloring_optimal(self):
        dcop = _tiny_dcop()
        graph, meta = compile_dcop(dcop)
        engine = MaxSumEngine(graph, meta)
        res = engine.run(max_cycles=100)
        assert res.converged
        cost, violations = dcop.solution_cost(res.assignment)
        assert violations == 0
        assert cost == pytest.approx(-0.1)

    def test_max_mode(self):
        d = Domain("d", "", [0, 1, 2])
        x, y = Variable("x", d), Variable("y", d)
        c = constraint_from_str("c", "x + y", [x, y])
        dcop = DCOP("t", objective="max")
        dcop.add_constraint(c)
        graph, meta = compile_dcop(dcop)
        res = MaxSumEngine(graph, meta).run(max_cycles=50)
        assert res.assignment == {"x": 2, "y": 2}

    def test_fixed_cycles_no_convergence_stop(self):
        dcop = _tiny_dcop()
        graph, meta = compile_dcop(dcop)
        engine = MaxSumEngine(graph, meta)
        res = engine.run(max_cycles=7, stop_on_convergence=False)
        assert res.cycles == 7

    def test_sharded_equals_unsharded(self):
        """8-device virtual CPU mesh must give identical results."""
        assert len(jax.devices()) >= 8, "conftest must force 8 devices"
        d = Domain("d", "", list(range(4)))
        rng = np.random.default_rng(7)
        variables = [Variable(f"v{i}", d) for i in range(12)]
        constraints = []
        for k in range(20):
            i, j = rng.choice(12, size=2, replace=False)
            constraints.append(constraint_from_str(
                f"c{k}", f"abs(v{i} - v{j}) * {rng.integers(1, 4)}",
                variables))
        dcop = DCOP("rand")
        for c in constraints:
            dcop.add_constraint(c)

        graph1, meta1 = compile_dcop(dcop)
        res1 = MaxSumEngine(graph1, meta1).run(max_cycles=60)

        mesh = make_mesh(8)
        graph8, meta8 = compile_dcop(dcop, pad_to=8)
        res8 = MaxSumEngine(graph8, meta8, mesh=mesh).run(max_cycles=60)

        assert res1.assignment == res8.assignment
        assert res1.cycles == res8.cycles


class TestTimingConvention:
    """DeviceRunResult timing contract (engine/runner.py docstring):
    cold calls report the whole interval in BOTH fields with
    cold_start=True; warm calls split compile_time_s=0."""

    def _engine(self):
        from pydcop_tpu.dcop.objects import Domain, Variable
        from pydcop_tpu.dcop.relations import constraint_from_str
        from pydcop_tpu.engine.compile import compile_factor_graph
        from pydcop_tpu.engine.runner import MaxSumEngine

        d = Domain("d", "", [0, 1, 2])
        vs = [Variable(f"v{i}", d) for i in range(4)]
        cs = [constraint_from_str(f"c{i}", f"v{i} + v{i+1}",
                                  [vs[i], vs[i + 1]]) for i in range(3)]
        graph, meta = compile_factor_graph(vs, cs)
        return MaxSumEngine(graph, meta)

    def test_cold_then_warm(self):
        engine = self._engine()
        cold = engine.run(max_cycles=5, stop_on_convergence=False)
        assert cold.metrics["cold_start"] is True
        assert cold.compile_time_s == cold.time_s > 0
        warm = engine.run(max_cycles=5, stop_on_convergence=False)
        assert warm.metrics["cold_start"] is False
        assert warm.compile_time_s == 0.0
        assert 0 < warm.time_s < cold.time_s

    def test_distinct_keys_are_cold_again(self):
        engine = self._engine()
        engine.run(max_cycles=5, stop_on_convergence=False)
        other = engine.run(max_cycles=7, stop_on_convergence=False)
        assert other.metrics["cold_start"] is True

    def test_trace_has_own_key(self):
        engine = self._engine()
        engine.run(max_cycles=5, stop_on_convergence=False)
        tr = engine.run_trace(max_cycles=5)
        assert tr.metrics["cold_start"] is True
        tr2 = engine.run_trace(max_cycles=5)
        assert tr2.metrics["cold_start"] is False

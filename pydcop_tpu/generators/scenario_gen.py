"""Dynamic-DCOP scenario generators.

:func:`generate_scenario` — reference parity:
pydcop/commands/generators/scenario.py — evts_count events of
actions_count remove_agent actions each, separated by fixed delays;
never removes the orchestrator or already-removed agents.

:func:`generate_factor_scenario` — problem-mutation events for the
incremental device engine (``pydcop solve --scenario``, stateful
serve sessions — docs/sessions.md): seeded change_factor /
remove_factor / add_factor / add_variable actions over a concrete
DCOP's binary constraints, the test-input factory for the session
plane and the dynamic bench leg.
"""

from typing import List, Optional

import numpy as np

from pydcop_tpu.dcop.scenario import DcopEvent, EventAction, Scenario


def generate_scenario(
    evts_count: int,
    actions_count: int,
    delay: float,
    agents: List[str],
    initial_delay: float = 20,
    end_delay: float = 20,
    seed: Optional[int] = None,
) -> Scenario:
    rng = np.random.default_rng(seed)
    available = list(agents)
    events = [DcopEvent("init_delay", delay=initial_delay)]
    for e in range(evts_count):
        if len(available) < actions_count:
            break
        chosen = rng.choice(
            len(available), size=actions_count, replace=False)
        removed = [available[i] for i in sorted(chosen, reverse=True)]
        for name in removed:
            available.remove(name)
        events.append(DcopEvent(
            f"e{e}",
            actions=[
                EventAction("remove_agent", agent=a) for a in removed
            ],
        ))
        events.append(DcopEvent(f"d{e}", delay=delay))
    events.append(DcopEvent("end_delay", delay=end_delay))
    return Scenario(events)


def generate_factor_scenario(
    dcop,
    evts_count: int,
    seed: Optional[int] = None,
    change_weight: float = 0.7,
    churn_weight: float = 0.2,
    grow_weight: float = 0.1,
    cost_range: int = 10,
) -> Scenario:
    """Seeded problem-mutation scenario over ``dcop``'s binary
    constraints.

    Each event holds one action, drawn by weight: ``change_factor``
    (fresh integer cost table, same scope — the in-shape path the
    session plane serves with zero recompiles), ``churn`` (a
    remove_factor followed next event by an add_factor reusing the
    name — the slack-row ladder), or ``grow`` (add_variable + a
    factor tying it in — the recompile-carrying-messages path).
    Tables are integer-valued so replay comparisons can demand exact
    cost equality."""
    rng = np.random.default_rng(seed)
    binary = [
        c for c in dcop.constraints.values()
        if c.arity == 2 and hasattr(c, "matrix")
    ]
    if not binary:
        raise ValueError(
            "generate_factor_scenario needs binary matrix "
            "constraints to mutate")
    removed: List = []
    events: List[DcopEvent] = []
    new_var_count = 0
    var_names = [v.name for v in dcop.variables.values()]
    weights = np.asarray(
        [change_weight, churn_weight, grow_weight], float)
    weights = weights / weights.sum()
    for e in range(evts_count):
        kind = rng.choice(3, p=weights)
        if removed and (kind == 1 or len(binary) == 0):
            # Re-add a previously removed factor under its old name
            # (name-reuse on a freed slack row) with a fresh table.
            c = removed.pop(0)
            d0, d1 = (len(v.domain) for v in c.dimensions)
            table = rng.integers(
                0, cost_range, size=(d0, d1)).astype(float)
            events.append(DcopEvent(f"e{e}", actions=[EventAction(
                "add_factor", name=c.name,
                variables=[v.name for v in c.dimensions],
                table=table.tolist())]))
            binary.append(c)
        elif kind == 1 and len(binary) > 1:
            c = binary.pop(int(rng.integers(len(binary))))
            removed.append(c)
            events.append(DcopEvent(f"e{e}", actions=[EventAction(
                "remove_factor", name=c.name)]))
        elif kind == 2:
            dom = list(dcop.variables.values())[0].domain
            name = f"sv{new_var_count}"
            new_var_count += 1
            anchor = var_names[int(rng.integers(len(var_names)))]
            d = len(dom)
            table = rng.integers(
                0, cost_range, size=(d, d)).astype(float)
            events.append(DcopEvent(f"e{e}", actions=[
                EventAction("add_variable", name=name,
                            domain=list(dom.values)),
                EventAction("add_factor", name=f"sc_{name}",
                            variables=[anchor, name],
                            table=table.tolist()),
            ]))
            var_names.append(name)
        else:
            c = binary[int(rng.integers(len(binary)))]
            d0, d1 = (len(v.domain) for v in c.dimensions)
            table = rng.integers(
                0, cost_range, size=(d0, d1)).astype(float)
            events.append(DcopEvent(f"e{e}", actions=[EventAction(
                "change_factor", name=c.name,
                variables=[v.name for v in c.dimensions],
                table=table.tolist())]))
    return Scenario(events)

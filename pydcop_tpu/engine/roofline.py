"""Achieved-FLOPs / HBM-traffic accounting for the device engine.

The VERDICT-mandated honesty layer for benchmark claims: given a
compiled graph we count, from the bucket shapes alone, the arithmetic
and memory traffic one MaxSum superstep performs (ops/maxsum.py
superstep), so bench results can report achieved FLOP/s, an MFU against
the chip's matmul peak, and — the meaningful roofline for this op mix —
HBM bandwidth utilization.

The counts are *models*, not profiler measurements: they assume XLA
fuses elementwise chains (each logical array is read/written once per
use) and count one FLOP per add/multiply/compare.  MaxSum's op mix is
min-plus gather/scatter on tiny minor dimensions, so it cannot use the
MXU at all; the MFU-vs-matmul-peak number is included because the
benchmark contract asks for it, and it is honestly tiny.  The binding
resource is HBM bandwidth (every superstep streams all factor tables
and messages), which is why `hbm_util` is the headline efficiency
number.

Peak numbers come from public chip specs, keyed on
`jax.devices()[0].device_kind` so each TPU generation gets its own
roofline; unknown kinds (and CPU backends) get `None` peaks and the
bench reports achieved numbers without a utilization claim.
"""

from typing import Dict, Optional, Tuple

from pydcop_tpu.engine.compile import CompiledFactorGraph

V5E_PEAK_FLOPS_BF16 = 197e12
V5E_HBM_BYTES_PER_S = 819e9

# device_kind -> (peak bf16 matmul FLOP/s, HBM bytes/s), public specs.
TPU_PEAKS: Dict[str, Tuple[float, float]] = {
    "TPU v4": (275e12, 1.2e12),
    "TPU v5 lite": (V5E_PEAK_FLOPS_BF16, V5E_HBM_BYTES_PER_S),
    "TPU v5e": (V5E_PEAK_FLOPS_BF16, V5E_HBM_BYTES_PER_S),
    "TPU v5": (459e12, 2.765e12),
    "TPU v5p": (459e12, 2.765e12),
    "TPU v6 lite": (918e12, 1.64e12),
    "TPU v6e": (918e12, 1.64e12),
}


def maxsum_superstep_flops(graph: CompiledFactorGraph) -> int:
    """Arithmetic ops in one superstep (adds + mins + compares).

    Derivation per bucket of F factors, arity a, padded domain D
    (ops/maxsum.py superstep):

    - factor→var: broadcast-add a messages into the [F, D^a] table
      (a·F·D^a), then per position a min-reduction over the table
      (a·F·D^a) and a subtract (a·F·D).
    - damping on both sides: damped = d·old + (1-d)·new → 3 ops per
      element over two [F, a, D] arrays.
    - belief segment-sum: one add per message element (F·a·D) plus the
      var-cost add over [V, D].
    - var→factor: two subtracts, masked mean (sum + divide ≈ 2), and
      the normalization subtract → ≈5 ops per [F, a, D] element.
    - convergence test: |Δ|, |Σ|, two compares on both message arrays
      → ≈8 ops per element, twice.
    """
    v_plus_1, d = graph.var_costs.shape
    total = v_plus_1 * d  # belief var-cost add
    for b in graph.buckets:
        f, a = b.var_ids.shape
        table = b.costs.size  # F * D^a
        total += 2 * a * table          # broadcast adds + min reductions
        per_msg = f * a * d
        total += per_msg * (1 + 6 + 1 + 5 + 16)  # sub, damp, seg, v2f, conv
    return int(total)


def maxsum_superstep_bytes(graph: CompiledFactorGraph) -> int:
    """HBM traffic (bytes) one fused superstep must move at minimum:
    read every factor cost table once, read old + write new messages on
    both sides (4 × [F, a, D]), read/write the [V, D] belief/sum
    tables a handful of times."""
    itemsize = graph.var_costs.dtype.itemsize
    total = 4 * graph.var_costs.size * itemsize
    for b in graph.buckets:
        f, a = b.var_ids.shape
        d = graph.var_costs.shape[1]
        total += b.costs.size * itemsize          # cost tables (read)
        total += 6 * f * a * d * itemsize         # v2f/f2v old+new
        total += b.var_ids.size * 4               # gather indices
    return int(total)


def roofline_report(graph: CompiledFactorGraph, cycles_per_s: float,
                    platform: str,
                    device_kind: Optional[str] = None,
                    ) -> Dict[str, Optional[float]]:
    """Achieved FLOP/s + utilizations for a measured superstep rate.

    Utilization claims (mfu/hbm_util) are made only when the concrete
    chip is recognized in TPU_PEAKS; `platform == "tpu"` with an
    unknown `device_kind` reports achieved numbers with `None`
    utilizations rather than assuming some generation's peaks.
    """
    flops = maxsum_superstep_flops(graph)
    bytes_moved = maxsum_superstep_bytes(graph)
    achieved_flops = flops * cycles_per_s
    achieved_bw = bytes_moved * cycles_per_s
    peak_flops: Optional[float] = None
    peak_bw: Optional[float] = None
    if platform == "tpu" and device_kind in TPU_PEAKS:
        peak_flops, peak_bw = TPU_PEAKS[device_kind]
    return {
        "flops_per_cycle": float(flops),
        "bytes_per_cycle": float(bytes_moved),
        "achieved_gflops": round(achieved_flops / 1e9, 3),
        "achieved_gbps": round(achieved_bw / 1e9, 3),
        # Not rounded: on small graphs these are ~1e-9 and rounding
        # would collapse an honest tiny number to a dishonest zero.
        "mfu": (
            achieved_flops / peak_flops if peak_flops else None
        ),
        "hbm_util": (
            achieved_bw / peak_bw if peak_bw else None
        ),
    }

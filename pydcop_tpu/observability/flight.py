"""Always-on flight recorder: a bounded ring of trace events plus
anomaly postmortem bundles.

A crash, a ``RecoveryExhausted``, a poison request — by the time an
operator looks, the evidence is gone: file tracing is off in
production (it buffers everything), and the journal only says WHAT
was accepted, not what the process was doing.  The flight recorder is
the black box: a bounded per-process ring buffer that receives every
span/instant recorded through the tracer EVEN WHILE file tracing is
off (``tracer.set_flight``; sites guard on ``tracer.active``), so the
last-N events before an anomaly are always available.  Overhead is a
deque append per event at segment/request cadence — gated ≤ 5% on the
segmented-run benchmark in ``make perf-smoke``; the per-message hot
paths stay gated on ``tracer.enabled`` so the ring holds signal, not
message spam.

On an anomaly **trigger** — guard trip, ``RecoveryExhausted``, shard
loss, admission-breaker open, poison-bin isolation, journal-replay
start, or a shutdown signal — the recorder dumps a **postmortem
bundle** to disk: the ring tail (the triggering instant is recorded
into the ring first, so it is always in the tail), a metrics-registry
snapshot, the ``/healthz`` payload, env + accelerator-probe
diagnostics, and the pending-journal summary when a serve journal is
active.  Bundles are rate-limited (a trip storm produces one bundle,
not one per trip); ``pydcop debug bundle`` (or ``GET /debug/bundle``
on the telemetry endpoint) cuts one on demand.

Knobs: ``PYDCOP_FLIGHT_RECORDER`` — ``0`` disables, ``1``/unset
enables the default ring, any larger integer sets the ring size
(also ``--flight_recorder_events`` on ``pydcop serve`` / ``pydcop
solve``); ``PYDCOP_FLIGHT_DIR`` sets the bundle directory (default:
``<tmpdir>/pydcop_bundles_<uid>``, created 0700).  The default
recorder is installed at
import of :mod:`pydcop_tpu.observability`.
"""

import glob
import json
import logging
import os
import socket
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from pydcop_tpu.observability.trace import tracer

logger = logging.getLogger("pydcop.observability.flight")

DEFAULT_EVENTS = 2048
# Seconds between automatic bundle dumps: an anomaly storm (repeated
# guard trips walking the escalation ladder) yields one bundle whose
# tail shows the storm, not a bundle per trip.
MIN_DUMP_INTERVAL_S = 2.0
# Keep-last-N retention for the bundle directory (PYDCOP_FLIGHT_KEEP
# overrides; 0 = unlimited): every orchestrated restart cuts a
# fatal_signal bundle and trip storms add one per interval — without
# a cap a long-lived host fills its disk with routine shutdowns and
# buries the one bundle that matters.
DEFAULT_KEEP = 50

# Pending-journal summary source (the serve plane registers one while
# a journaled service runs) — mirrors the /healthz provider pattern.
_journal_provider: Optional[Callable[[], Dict[str, Any]]] = None
_provider_lock = threading.Lock()


def set_journal_provider(fn: Optional[Callable[[], Dict[str, Any]]]):
    """Register (or clear, with ``None``) the pending-journal summary
    source folded into postmortem bundles.  One slot, last writer
    wins — a process hosting several journaled services should clear
    with :func:`clear_journal_provider` so a stopping service never
    wipes a sibling's registration."""
    global _journal_provider
    with _provider_lock:
        _journal_provider = fn


def clear_journal_provider(fn: Callable[[], Dict[str, Any]]):
    """Clear the provider ONLY if ``fn`` is still the registered one
    (identity-guarded): a service stopping after a sibling registered
    must not strip the sibling's journal section from future
    bundles."""
    global _journal_provider
    with _provider_lock:
        if _journal_provider is fn:
            _journal_provider = None


def get_journal_provider():
    with _provider_lock:
        return _journal_provider


def ring_size_from_env(value: Optional[str] = None) -> Optional[int]:
    """Parse ``PYDCOP_FLIGHT_RECORDER``: ``0``/``off``/``false``/
    ``no``/``none``/``disabled`` or any value ≤ 0 → None (disabled —
    every plausible way an operator spells "off" must actually turn
    it off), ``1``/unset/unparsable garbage → the default ring size
    (fail-open: the black box should survive a typo'd size), N > 1 →
    a ring of N events."""
    if value is None:
        value = os.environ.get("PYDCOP_FLIGHT_RECORDER", "1")
    text = str(value).strip().lower()
    if text in ("0", "off", "false", "no", "none", "disabled"):
        return None
    try:
        n = int(text)
    except ValueError:
        return DEFAULT_EVENTS
    if n <= 0:
        return None
    return n if n > 1 else DEFAULT_EVENTS


def default_bundle_dir() -> str:
    """Per-user default under the tmpdir: a fixed shared path would
    let another local user pre-create it (blocking our bundle
    writes) or read bundles that carry env values and hostnames.
    The uid suffix plus 0700 creation (``write_bundle``) keeps each
    user's black box their own."""
    uid = getattr(os, "getuid", lambda: "u")()
    return os.environ.get(
        "PYDCOP_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), f"pydcop_bundles_{uid}"))


class FlightRecorder:
    """The ring + the bundle writer.

    ``record`` is the tracer-side sink (one bounded-deque append —
    atomic under the GIL, so the hot path takes no lock; the 5%
    overhead budget is gated in ``make perf-smoke``); ``snapshot``
    retries on ``deque mutated during iteration`` so a bundle cut on
    a busy process never loses its event tail to a concurrent
    append; ``trigger`` records the anomaly as a trace instant
    (which lands in the ring via the tracer) and dumps a bundle,
    rate limited; ``bundle`` builds/writes one unconditionally.
    """

    def __init__(self, events: int = DEFAULT_EVENTS,
                 bundle_dir: Optional[str] = None,
                 min_interval_s: float = MIN_DUMP_INTERVAL_S,
                 keep: Optional[int] = None):
        self.ring: "deque" = deque(maxlen=max(int(events), 2))
        self.bundle_dir = bundle_dir or default_bundle_dir()
        self.min_interval_s = min_interval_s
        if keep is None:
            try:
                keep = int(os.environ.get("PYDCOP_FLIGHT_KEEP",
                                          DEFAULT_KEEP))
            except ValueError:
                keep = DEFAULT_KEEP
        self.keep = max(int(keep), 0)
        self._lock = threading.Lock()
        self._last_dump = 0.0
        self._seq = 0
        self.dumped = 0
        self.suppressed = 0
        self.last_bundle_path: Optional[str] = None

    # -- recording ------------------------------------------------------ #

    def record(self, event: Dict[str, Any]) -> None:
        """Tracer sink: append one event to the ring (bounded —
        eviction is the deque's maxlen, never a scan; deque appends
        are atomic under the GIL, so the hot path takes no lock)."""
        self.ring.append(event)

    def snapshot(self) -> List[Dict[str, Any]]:
        """The ring's current contents, oldest first.  Copying the
        deque while another thread appends raises ``RuntimeError:
        deque mutated during iteration`` — and bundles are cut
        exactly when the process is busy — so retry (the copy runs
        within one GIL slice; a retry virtually always wins) with a
        per-element fallback (deque indexing never raises on
        concurrent mutation).

        Events (and their args dicts) are shallow-copied: the tracer
        hands the ring LIVE dicts, and at least one site mutates its
        args after the event is recorded (``timed_jit_call`` attaches
        measured XLA cost post-exit).  Serializing the live dict from
        the bundle writer while that mutation lands would raise
        mid-``json.dump`` — losing the black-box bundle at exactly
        the anomaly it exists to capture."""
        for _ in range(64):
            try:
                return [self._copy_event(e) for e in list(self.ring)]
            except RuntimeError:
                continue
        return [self._copy_event(self.ring[i])
                for i in range(len(self.ring))]

    @staticmethod
    def _copy_event(event: Dict[str, Any]) -> Dict[str, Any]:
        for _ in range(8):
            try:
                out = dict(event)
                args = out.get("args")
                if isinstance(args, dict):
                    out["args"] = dict(args)
                return out
            except RuntimeError:  # dict mutated during the copy
                continue
        return {"name": event.get("name"), "copy_error": True}

    # -- anomaly path --------------------------------------------------- #

    def trigger(self, kind: str, force: bool = False,
                **info) -> Optional[str]:
        """Anomaly hook: record the triggering instant (into the ring
        AND the session trace, when one is on) and dump a postmortem
        bundle.  Rate-limited unless ``force``; returns the bundle
        path, or None when suppressed or the dump failed.  Never
        raises — the anomaly path must not add a second failure."""
        try:
            tracer.instant("anomaly", "flight", kind=kind, **info)
        except Exception:  # noqa: BLE001 — never break the caller
            logger.exception("flight trigger instant failed")
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_dump \
                    < self.min_interval_s:
                self.suppressed += 1
                return None
            self._last_dump = now
        try:
            return self.bundle(kind, info)
        except Exception:  # noqa: BLE001 — never break the caller
            logger.exception("postmortem bundle dump failed")
            return None

    def make_bundle(self, kind: str,
                    info: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
        """The bundle document (not yet written): ring tail +
        registry snapshot + /healthz payload + env/probe diagnostics
        + pending-journal summary.  Every section is best-effort — a
        broken registry must not cost the event tail."""
        bundle: Dict[str, Any] = {
            "version": 1,
            "kind": kind,
            "info": dict(info or {}),
            "unix": time.time(),
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "ring_capacity": self.ring.maxlen,
            "events": self.snapshot(),
        }
        try:
            from pydcop_tpu.observability.metrics import registry

            bundle["metrics"] = registry.snapshot()
        except Exception as exc:  # noqa: BLE001
            bundle["metrics"] = {"error": str(exc)}
        try:
            from pydcop_tpu.observability.server import health_verdict

            bundle["healthz"] = health_verdict()
        except Exception as exc:  # noqa: BLE001
            bundle["healthz"] = {"error": str(exc)}
        bundle["env"] = {
            k: v for k, v in sorted(os.environ.items())
            if k.startswith(("PYDCOP_", "JAX_", "XLA_"))
        }
        try:
            from pydcop_tpu.utils.cleanenv import diag_events

            bundle["probe_diagnostics"] = list(diag_events())
        except Exception as exc:  # noqa: BLE001
            bundle["probe_diagnostics"] = [{"error": str(exc)}]
        # The on-disk probe HISTORY tail (BENCH_TPU_PROBELOG.jsonl /
        # record_diag format): the in-env diagnostics above cover only
        # this process tree; the probelog is the cross-run evidence of
        # tunnel health, so a postmortem says what backend the
        # anomalous run actually executed on (ISSUE 14).
        try:
            from pydcop_tpu.utils.cleanenv import probelog_tail

            tail = probelog_tail(20)
            if tail:
                bundle["probe_log_tail"] = tail
        except Exception as exc:  # noqa: BLE001
            bundle["probe_log_tail"] = [{"error": str(exc)}]
        # The efficiency rollup (observability/efficiency.py): the
        # postmortem's "was the device even doing useful work, and on
        # which backend" section — backend identity, attainment and
        # the where-the-time-went ledger at the moment of the
        # anomaly.
        try:
            from pydcop_tpu.observability.efficiency import tracker

            bundle["efficiency"] = tracker.rollup(top_n=5)
        except Exception as exc:  # noqa: BLE001
            bundle["efficiency"] = {"error": str(exc)}
        provider = get_journal_provider()
        if provider is not None:
            try:
                bundle["journal"] = provider()
            except Exception as exc:  # noqa: BLE001
                bundle["journal"] = {"error": str(exc)}
        return bundle

    def bundle(self, kind: str,
               info: Optional[Dict[str, Any]] = None) -> str:
        """Build + atomically write one bundle; returns its path."""
        return self.write_bundle(self.make_bundle(kind, info))

    def write_bundle(self, doc: Dict[str, Any]) -> str:
        """Atomically write a built bundle document; returns its
        path."""
        kind = doc.get("kind", "bundle")
        os.makedirs(self.bundle_dir, mode=0o700, exist_ok=True)
        with self._lock:
            self._seq += 1
            seq = self._seq
        name = (f"bundle_{kind}_{os.getpid()}_"
                f"{int(doc['unix'])}_{seq}.json")
        path = os.path.join(self.bundle_dir, name)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        self.dumped += 1
        self.last_bundle_path = path
        self._prune()
        try:
            from pydcop_tpu.observability.metrics import registry

            registry.counter(
                "pydcop_flight_bundles_total",
                "Postmortem bundles written, by trigger kind",
            ).inc(kind=kind)
        except Exception:  # noqa: BLE001 — accounting is best-effort
            pass
        logger.warning("postmortem bundle (%s): %s", kind, path)
        return path


    def _prune(self):
        """Keep-last-N retention over the bundle directory (mtime
        order, all processes' bundles — the directory is the unit an
        operator's disk cares about).  Best-effort: a pruning failure
        must never cost the bundle that was just written."""
        if not self.keep:
            return
        try:
            bundles = sorted(
                glob.glob(os.path.join(self.bundle_dir,
                                       "bundle_*.json")),
                key=lambda p: os.path.getmtime(p))
            for stale in bundles[:-self.keep]:
                os.remove(stale)
        except OSError:
            pass


def get_flight() -> Optional[FlightRecorder]:
    """The recorder currently attached to the process tracer."""
    return tracer.flight


def install(events: Optional[int] = None,
            bundle_dir: Optional[str] = None
            ) -> Optional[FlightRecorder]:
    """Attach a flight recorder to the process tracer (replacing any
    existing one).  ``events=None`` reads ``PYDCOP_FLIGHT_RECORDER``;
    explicit values use the SAME semantics (≤ 0 detaches, 1 means
    the default size — ``--flight_recorder_events 1`` and
    ``PYDCOP_FLIGHT_RECORDER=1`` must not disagree).  Returns the
    recorder, or None when disabled."""
    size = ring_size_from_env(
        None if events is None else str(int(events)))
    if size is None:
        tracer.set_flight(None)
        return None
    recorder = FlightRecorder(events=size, bundle_dir=bundle_dir)
    tracer.set_flight(recorder)
    return recorder


def trigger(kind: str, force: bool = False, **info) -> Optional[str]:
    """Module-level anomaly hook: no-op (None) when no recorder is
    attached, so call sites need no guard."""
    recorder = tracer.flight
    if recorder is None:
        return None
    return recorder.trigger(kind, force=force, **info)

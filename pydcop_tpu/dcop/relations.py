"""Constraint algebra — the numeric core of the modeling layer.

Reference parity: pydcop/dcop/relations.py (RelationProtocol :48,
ZeroAryRelation :218, UnaryFunctionRelation :270, UnaryBooleanRelation
:380, NAryFunctionRelation :456, AsNAryFunctionRelation :639,
NAryMatrixRelation :672, NeutralRelation :909, ConditionalRelation :948,
assignment_matrix :1155, constraint_from_str :1275,
constraint_from_external_definition :1314, find_optimum :1367,
generate_assignment_as_dict :1452, assignment_cost :1479,
find_arg_optimal :1554, optimal_cost_value :1641, join :1672,
projection :1717).

Design notes (TPU-first): every constraint — intentional (expression) or
extensional (table) — can materialize a dense **cost hypercube**
(`to_array()`: one axis per variable, axis length = domain size, C-order,
axis order = `dimensions` order).  The hypercube is *the* canonical device
form: the engine compiler stacks these per (arity, shape) bucket, and
`join`/`projection` — DPOP's entire math — are numpy/JAX broadcast-add and
axis-reductions over it rather than per-assignment Python loops.
Materialization is capped (`MAX_MATERIALIZED_ELEMENTS`) because ``d^arity``
explodes; algorithms that can work factored (SyncBB) never call it.
"""

import ast
import itertools
import re
from typing import (
    Any, Callable, Dict, Hashable, Iterable, List, Optional, Union,
)

import numpy as np

from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.utils.expressionfunction import ExpressionFunction
from pydcop_tpu.utils.simple_repr import SimpleRepr, simple_repr, from_repr

DEFAULT_TYPE = np.float64

# Materialization guard: refuse to enumerate cost hypercubes bigger than
# this many elements (2**26 f64 = 512 MiB).
MAX_MATERIALIZED_ELEMENTS = 2 ** 26


class Constraint(SimpleRepr):
    """Base class for all constraints (cost/utility relations).

    A constraint has a name, an ordered list of variables (`dimensions`)
    and yields a numeric cost for every assignment of those variables.
    """

    def __init__(self, name: str):
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    @property
    def dimensions(self) -> List[Variable]:
        raise NotImplementedError

    @property
    def arity(self) -> int:
        return len(self.dimensions)

    @property
    def scope_names(self) -> List[str]:
        return [v.name for v in self.dimensions]

    @property
    def shape(self):
        return tuple(len(v.domain) for v in self.dimensions)

    def __call__(self, *args, **kwargs) -> float:
        raise NotImplementedError

    def get_value_for_assignment(self, assignment) -> float:
        """Cost for an assignment given as dict {var_name: value} or list
        of values in `dimensions` order."""
        if isinstance(assignment, dict):
            return self(**assignment)
        return self(*assignment)

    def to_array(self) -> np.ndarray:
        """Dense cost hypercube: one axis per dimension, C-order."""
        self._check_materializable()
        shape = self.shape
        dims = self.dimensions
        out = np.empty(shape, dtype=DEFAULT_TYPE)
        for idx in np.ndindex(*shape) if shape else [()]:
            assignment = {
                v.name: v.domain[i] for v, i in zip(dims, idx)
            }
            out[idx] = self(**assignment)
        return out

    def _check_materializable(self) -> None:
        shape = self.shape
        n = int(np.prod(shape)) if shape else 1
        if n > MAX_MATERIALIZED_ELEMENTS:
            raise MemoryError(
                f"Refusing to materialize constraint {self.name}: "
                f"{n} elements (> {MAX_MATERIALIZED_ELEMENTS})"
            )

    def table_signature(self) -> Optional[Hashable]:
        """A hashable key equal for constraints whose ``to_array()``
        tables are provably identical, or None when no cheap proof
        exists.  The engine compiler (engine/compile.py) memoizes
        bucket-table evaluation on this key, so 10k structurally
        identical expression factors (e.g. generated graph-coloring
        edges, whose expressions differ only in variable *names*) cost
        ONE table evaluation instead of 10k."""
        return None

    def slice(self, partial: Dict[str, Any]) -> "Constraint":
        """Constraint over the remaining dims with `partial` frozen."""
        remaining = [v for v in self.dimensions if v.name not in partial]
        return NAryFunctionRelation(
            lambda **kw: self(**{**partial, **kw}),
            remaining,
            name=f"{self.name}_sliced",
            f_kwargs=True,
        )

    def __eq__(self, other):
        return (
            type(self) is type(other)
            and self.name == other.name
            and self.scope_names == other.scope_names
        )

    def __hash__(self):
        return hash((type(self).__name__, self._name, tuple(self.scope_names)))

    def __repr__(self):
        return f"{type(self).__name__}({self._name!r}, {self.scope_names})"


# The reference exposes the same concept under this name.
RelationProtocol = Constraint


class ZeroAryRelation(Constraint):
    """A constant-cost relation with no variables."""

    def __init__(self, name: str, value: float):
        super().__init__(name)
        self._value = value

    @property
    def dimensions(self) -> List[Variable]:
        return []

    def __call__(self, *args, **kwargs) -> float:
        return self._value

    def to_array(self) -> np.ndarray:
        return np.array(self._value, dtype=DEFAULT_TYPE)


class UnaryFunctionRelation(Constraint):
    """Cost from a single-argument function of one variable."""

    def __init__(self, name: str, variable: Variable,
                 rel_function: Union[Callable, str]):
        super().__init__(name)
        self._variable = variable
        if isinstance(rel_function, str):
            rel_function = ExpressionFunction(rel_function)
        self._rel_function = rel_function

    @property
    def dimensions(self) -> List[Variable]:
        return [self._variable]

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def expression(self) -> Optional[str]:
        if isinstance(self._rel_function, ExpressionFunction):
            return self._rel_function.expression
        return None

    def __call__(self, *args, **kwargs) -> float:
        if kwargs:
            val = kwargs[self._variable.name]
        else:
            (val,) = args
        if isinstance(self._rel_function, ExpressionFunction):
            names = list(self._rel_function.variable_names)
            if names:
                return self._rel_function(**{names[0]: val})
            return self._rel_function()
        return self._rel_function(val)


class UnaryBooleanRelation(Constraint):
    """Cost 1 when the variable's value is truthy, else 0."""

    def __init__(self, name: str, variable: Variable):
        super().__init__(name)
        self._variable = variable

    @property
    def dimensions(self) -> List[Variable]:
        return [self._variable]

    def __call__(self, *args, **kwargs) -> float:
        if kwargs:
            val = kwargs[self._variable.name]
        else:
            (val,) = args
        return 1 if val else 0


class NAryFunctionRelation(Constraint):
    """Cost from an arbitrary function over N variables.

    The function is called with keyword args (variable names) when it is
    an ExpressionFunction or `f_kwargs=True`, positionally otherwise.
    """

    def __init__(self, f: Union[Callable, str], variables: Iterable[Variable],
                 name: Optional[str] = None, f_kwargs: bool = False):
        if isinstance(f, str):
            f = ExpressionFunction(f)
        if name is None:
            name = getattr(f, "__name__", "relation")
        super().__init__(name)
        self._variables = list(variables)
        self._f = f
        self._f_kwargs = f_kwargs or isinstance(f, ExpressionFunction)

    @property
    def dimensions(self) -> List[Variable]:
        return list(self._variables)

    @property
    def function(self) -> Callable:
        return self._f

    @property
    def expression(self) -> Optional[str]:
        if isinstance(self._f, ExpressionFunction):
            return self._f.expression
        return None

    def __call__(self, *args, **kwargs) -> float:
        if args and not kwargs:
            kwargs = {v.name: a for v, a in zip(self._variables, args)}
        if self._f_kwargs:
            if isinstance(self._f, ExpressionFunction):
                needed = set(self._f.variable_names)
                kwargs = {k: v for k, v in kwargs.items() if k in needed}
            return self._f(**kwargs)
        return self._f(*[kwargs[v.name] for v in self._variables])

    def slice(self, partial: Dict[str, Any]) -> Constraint:
        if isinstance(self._f, ExpressionFunction):
            remaining = [
                v for v in self._variables if v.name not in partial
            ]
            return NAryFunctionRelation(
                self._f.partial(**partial), remaining,
                name=f"{self.name}_sliced",
            )
        return super().slice(partial)

    def to_array(self) -> np.ndarray:
        """Dense cost hypercube, evaluated vectorized when possible.

        Expression constraints are evaluated in ONE numpy call over an
        open meshgrid of the domain product instead of ``d^arity``
        python calls (the engine-compile hot path; see
        engine/compile.compile_factor_graph).  The numpy-elementwise
        rewrite (utils/expressionfunction._VectorizeTransform) is
        spot-checked against scalar evaluation at a few grid points;
        any failure or mismatch falls back to the reference
        per-assignment loop, so the vectorized path can only be
        faster, never different.
        """
        arr = self._vectorized_array()
        if arr is not None:
            return arr
        return super().to_array()

    def _vectorized_array(self) -> Optional[np.ndarray]:
        f = self._f
        if not isinstance(f, ExpressionFunction):
            return None
        if not f.supports_vectorized:
            return None
        self._check_materializable()
        dims = self.dimensions
        shape = self.shape
        if not shape:
            return None
        needed = set(f.variable_names)
        grids = {}
        for axis, v in enumerate(dims):
            if v.name not in needed:
                continue
            g_shape = [1] * len(dims)
            g_shape[axis] = len(v.domain)
            grids[v.name] = np.asarray(list(v.domain)).reshape(g_shape)
        try:
            out = f.vectorized(**grids)
            out = np.array(
                np.broadcast_to(np.asarray(out, dtype=DEFAULT_TYPE),
                                shape),
                dtype=DEFAULT_TYPE,
            )
        except Exception:
            f.mark_not_vectorizable()
            return None
        # Spot-check a few deterministic grid points against the
        # scalar path: the AST rewrite is semantics-preserving by
        # construction, but an expression can still mean something
        # different elementwise (e.g. a user callable smuggled into
        # scope) — a mismatch demotes this expression to the scalar
        # loop for the rest of the process.
        n = out.size
        for flat in {0, n - 1, n // 2, (n // 3) * 2}:
            idx = np.unravel_index(flat, shape)
            assignment = {
                v.name: v.domain[i] for v, i in zip(dims, idx)
            }
            try:
                ref = float(self(**assignment))
            except Exception:
                f.mark_not_vectorizable()
                return None
            if not np.isclose(out[idx], ref, rtol=1e-9, atol=1e-12,
                              equal_nan=True):
                f.mark_not_vectorizable()
                return None
        return out

    def table_signature(self) -> Optional[Hashable]:
        f = self._f
        if not isinstance(f, ExpressionFunction) or f.source_file:
            return None
        sig = getattr(self, "_table_sig", False)
        if sig is False:
            sig = _normalized_expression_key(
                f, [v.name for v in self._variables])
            if sig is not None:
                sig = (
                    sig,
                    tuple(tuple(v.domain) for v in self._variables),
                )
            self._table_sig = sig
        return sig

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "f": simple_repr(self._f),
            "variables": simple_repr(self._variables),
            "name": self._name,
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(
            from_repr(r["f"]), from_repr(r["variables"]), name=r.get("name")
        )


def AsNAryFunctionRelation(*variables):
    """Decorator turning a python function into an NAryFunctionRelation.

    >>> from pydcop_tpu.dcop.objects import Variable, Domain
    >>> d = Domain('d', 'd', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> @AsNAryFunctionRelation(x, y)
    ... def my_constraint(x, y):
    ...     return x + y
    >>> my_constraint(1, 1)
    2
    """

    def decorator(f):
        return NAryFunctionRelation(f, list(variables), name=f.__name__)

    return decorator


class NAryMatrixRelation(Constraint):
    """Extensional constraint: a dense numpy cost hypercube.

    One axis per variable (in `dimensions` order), axis length = domain
    size, entry = cost of the corresponding assignment.  This *is* the
    device form — `join` and `projection` operate on it directly.

    >>> from pydcop_tpu.dcop.objects import Variable, Domain
    >>> d = Domain('d', 'd', ['a', 'b'])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]))
    >>> r(x='b', y='a')
    3.0
    """

    def __init__(self, variables: Iterable[Variable],
                 matrix: Optional[np.ndarray] = None, name: str = ""):
        super().__init__(name)
        self._variables = list(variables)
        shape = tuple(len(v.domain) for v in self._variables)
        if matrix is None:
            matrix = np.zeros(shape, dtype=DEFAULT_TYPE)
        else:
            matrix = np.asarray(matrix, dtype=DEFAULT_TYPE)
            if matrix.shape != shape:
                raise ValueError(
                    f"Matrix shape {matrix.shape} does not match domains "
                    f"{shape} for constraint {name}"
                )
        self._m = matrix

    @property
    def dimensions(self) -> List[Variable]:
        return list(self._variables)

    @property
    def matrix(self) -> np.ndarray:
        return self._m

    def _indices(self, kwargs: Dict[str, Any]):
        return tuple(
            v.domain.index(kwargs[v.name]) for v in self._variables
        )

    def __call__(self, *args, **kwargs) -> float:
        if args and not kwargs:
            kwargs = {v.name: a for v, a in zip(self._variables, args)}
        return float(self._m[self._indices(kwargs)])

    def to_array(self) -> np.ndarray:
        return self._m

    def get_value_for_assignment(self, assignment) -> float:
        if isinstance(assignment, dict):
            return self(**assignment)
        return float(
            self._m[tuple(v.domain.index(a)
                          for v, a in zip(self._variables, assignment))]
        )

    def set_value_for_assignment(self, assignment: Dict[str, Any],
                                 value: float) -> "NAryMatrixRelation":
        """Return a new relation with one entry changed (immutable style)."""
        m = self._m.copy()
        m[self._indices(assignment)] = value
        return NAryMatrixRelation(self._variables, m, self._name)

    def slice(self, partial: Dict[str, Any]) -> "NAryMatrixRelation":
        idx = tuple(
            v.domain.index(partial[v.name]) if v.name in partial
            else slice(None)
            for v in self._variables
        )
        remaining = [v for v in self._variables if v.name not in partial]
        return NAryMatrixRelation(remaining, self._m[idx], self._name)

    @classmethod
    def from_func_relation(cls, rel: Constraint) -> "NAryMatrixRelation":
        return cls(rel.dimensions, rel.to_array(), rel.name)

    def __eq__(self, other):
        return (
            isinstance(other, NAryMatrixRelation)
            and self.name == other.name
            and self.scope_names == other.scope_names
            and np.array_equal(self._m, other._m)
        )

    def __hash__(self):
        return hash((self._name, tuple(self.scope_names)))

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "variables": simple_repr(self._variables),
            "matrix": self._m.tolist(),
            "name": self._name,
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(
            from_repr(r["variables"]),
            np.array(r["matrix"], dtype=DEFAULT_TYPE),
            r.get("name", ""),
        )


class NeutralRelation(Constraint):
    """All-zero relation, useful as a join identity."""

    def __init__(self, variables: Iterable[Variable], name: str = "neutral"):
        super().__init__(name)
        self._variables = list(variables)

    @property
    def dimensions(self) -> List[Variable]:
        return list(self._variables)

    def __call__(self, *args, **kwargs) -> float:
        return 0

    def to_array(self) -> np.ndarray:
        return np.zeros(self.shape, dtype=DEFAULT_TYPE)


class ConditionalRelation(Constraint):
    """Applies `relation` only when `condition` is truthy, else 0."""

    def __init__(self, condition: Constraint, relation: Constraint,
                 name: str = "conditional", return_default: float = 0):
        super().__init__(name)
        self._condition = condition
        self._relation = relation
        self._default = return_default

    @property
    def condition(self) -> Constraint:
        return self._condition

    @property
    def relation(self) -> Constraint:
        return self._relation

    @property
    def dimensions(self) -> List[Variable]:
        dims = list(self._condition.dimensions)
        for v in self._relation.dimensions:
            if v not in dims:
                dims.append(v)
        return dims

    def __call__(self, *args, **kwargs) -> float:
        if args and not kwargs:
            kwargs = {v.name: a for v, a in zip(self.dimensions, args)}
        cond_args = {
            v.name: kwargs[v.name] for v in self._condition.dimensions
        }
        if self._condition(**cond_args):
            rel_args = {
                v.name: kwargs[v.name] for v in self._relation.dimensions
            }
            return self._relation(**rel_args)
        return self._default


# Standalone identifiers (not attribute accesses): the shared scan
# behind the _normalized_expression_key fast path.
_IDENT_RE = re.compile(r"(?<![\w.])[A-Za-z_]\w*")


class _RenameVars(ast.NodeTransformer):
    def __init__(self, mapping: Dict[str, str]):
        self._mapping = mapping

    def visit_Name(self, node: ast.Name) -> ast.AST:
        new = self._mapping.get(node.id)
        if new is not None:
            return ast.Name(id=new, ctx=node.ctx)
        return node


def _normalized_expression_key(f: ExpressionFunction,
                               scope_names: List[str],
                               ) -> Optional[Hashable]:
    """Expression text with scope variable names replaced by their
    POSITION in the constraint's dimensions — e.g. both
    ``10 if v12 == v37 else 0`` and ``10 if v3 == v8 else 0``
    normalize to ``10 if __v0__ == __v1__ else 0``, proving the two
    cost tables are identical whenever the (positional) domains also
    match.  None when the expression is not a pure function of its
    scope (random/source/function bodies) or the fixed vars are not
    hashable."""
    expr = f.expression
    if "random" in expr or "source" in expr:
        # Conservative substring test (also rejects e.g. a variable
        # named "randomize"): a missed memo costs one extra eval, a
        # wrong hit would corrupt a cost table.
        return None
    try:
        fixed = tuple(sorted(f.fixed_vars.items()))
        hash(fixed)
    except TypeError:
        return None
    mapping = {n: f"__v{i}__" for i, n in enumerate(scope_names)}
    if '"' not in expr and "'" not in expr:
        # Fast path (a few µs/constraint — this runs once per factor
        # on the compile hot path): one precompiled identifier scan,
        # renaming scope names and leaving everything else (including
        # attribute positions like ``x.v1``, excluded by the
        # lookbehind).  Exact because without string literals every
        # standalone occurrence of an identifier is a Name node.
        normalized = _IDENT_RE.sub(
            lambda m: mapping.get(m.group(0), m.group(0)), expr)
        return (normalized, fixed)
    # String literals present: only the AST rename can distinguish a
    # quoted occurrence of a variable name from a real Name node.
    try:
        tree = ast.parse(expr.strip(), mode="eval")
    except SyntaxError:
        return None  # function-body form: not normalizable cheaply
    tree = _RenameVars(mapping).visit(tree)
    try:
        normalized = ast.unparse(tree)
    except AttributeError:
        return None
    return (normalized, fixed)


def constraint_from_str(name: str, expression: str,
                        all_variables: Iterable[Variable]) -> Constraint:
    """Build an intentional constraint from a python expression string.

    The constraint's dimensions are the variables (from `all_variables`)
    whose names appear free in the expression.
    """
    f = ExpressionFunction(expression)
    by_name = {v.name: v for v in all_variables}
    dims = []
    for n in f.variable_names:
        if n not in by_name:
            raise ValueError(
                f"Unknown variable {n!r} in constraint {name}: {expression}"
            )
        dims.append(by_name[n])
    return NAryFunctionRelation(f, dims, name=name)


def constraint_from_external_definition(
        name: str, source_file: str, expression: str,
        all_variables: Iterable[Variable]) -> Constraint:
    """Intentional constraint whose expression calls into a python file,
    exposed as `source` (e.g. ``source.my_fn(v1, v2)``)."""
    f = ExpressionFunction(expression, source_file=source_file)
    by_name = {v.name: v for v in all_variables}
    dims = [by_name[n] for n in f.variable_names]
    return NAryFunctionRelation(f, dims, name=name)


def assignment_matrix(variables: List[Variable],
                      default_value: float = 0) -> np.ndarray:
    """A cost hypercube over `variables` filled with `default_value`."""
    shape = tuple(len(v.domain) for v in variables)
    return np.full(shape, default_value, dtype=DEFAULT_TYPE)


def generate_assignment(variables: List[Variable]):
    """Lazily yield all assignments as value-lists (last var fastest)."""
    domains = [list(v.domain) for v in variables]
    for combo in itertools.product(*domains):
        yield list(combo)


def generate_assignment_as_dict(variables: List[Variable]):
    """Lazily yield all assignments as {name: value} (last var fastest)."""
    names = [v.name for v in variables]
    domains = [list(v.domain) for v in variables]
    for combo in itertools.product(*domains):
        yield dict(zip(names, combo))


def count_var_match(variables: Iterable[str], constraint: Constraint) -> int:
    scope = set(constraint.scope_names)
    return sum(1 for v in variables if v in scope)


def assignment_cost(assignment: Dict[str, Any],
                    constraints: Iterable[Constraint],
                    infinity: float = float("inf")) -> float:
    """Total cost of `assignment` over `constraints`.

    Raises ValueError if any constraint yields `infinity` (hard violation),
    matching the reference's hard-constraint detection convention.
    """
    cost = 0
    for c in constraints:
        c_cost = c(**{v.name: assignment[v.name] for v in c.dimensions})
        if abs(c_cost) == infinity:
            raise ValueError(
                f"Hard constraint {c.name} violated by assignment"
            )
        cost += c_cost
    return cost


def find_optimum(constraint: Constraint, mode: str) -> float:
    """Min (or max) cost over all assignments of the constraint."""
    arr = constraint.to_array()
    return float(arr.min() if mode == "min" else arr.max())


def find_optimal(variable: Variable, assignment: Dict[str, Any],
                 constraints: Iterable[Constraint], mode: str):
    """Best value(s) for `variable` given a partial assignment of the
    other variables in the constraints' scopes.

    Returns (list-of-optimal-values-in-domain-order, optimal_cost).
    """
    best_cost, best_vals = None, []
    better = (lambda a, b: a < b) if mode == "min" else (lambda a, b: a > b)
    for val in variable.domain:
        asst = dict(assignment)
        asst[variable.name] = val
        cost = 0
        for c in constraints:
            cost += c(**{v.name: asst[v.name] for v in c.dimensions})
        if best_cost is None or better(cost, best_cost):
            best_cost, best_vals = cost, [val]
        elif cost == best_cost:
            best_vals.append(val)
    return best_vals, best_cost


def find_arg_optimal(variable: Variable, relation: Constraint, mode: str):
    """Optimal value(s) of `variable` for a unary relation over it.

    Returns (list of optimal values in domain order, optimal cost) — taking
    ``values[0]`` gives the reference's first-optimum tie-breaking.
    """
    if relation.arity != 1 or relation.dimensions[0] != variable:
        raise ValueError(
            f"find_arg_optimal requires a unary relation on {variable.name}"
        )
    arr = np.asarray(
        [relation(**{variable.name: v}) for v in variable.domain],
        dtype=DEFAULT_TYPE,
    )
    opt = arr.min() if mode == "min" else arr.max()
    vals = [v for v, c in zip(variable.domain, arr) if c == opt]
    return vals, float(opt)


def optimal_cost_value(variable: Variable, mode: str = "min"):
    """(value, cost) minimizing (or maximizing) the variable's own cost."""
    costs = [variable.cost_for_val(v) for v in variable.domain]
    arr = np.asarray(costs, dtype=DEFAULT_TYPE)
    i = int(arr.argmin() if mode == "min" else arr.argmax())
    return variable.domain[i], float(arr[i])


def join(r1: Constraint, r2: Constraint) -> NAryMatrixRelation:
    """Pointwise sum of two relations over the union of their dims.

    This is DPOP's UTIL accumulation: the result's hypercube is the
    broadcast-add of the two inputs aligned on shared variables
    (reference semantics: relations.py:1672; here it is a pure numpy
    broadcast instead of per-assignment enumeration).
    """
    dims1, dims2 = r1.dimensions, r2.dimensions
    union = list(dims1) + [v for v in dims2 if v not in dims1]
    a1 = np.asarray(r1.to_array(), dtype=DEFAULT_TYPE)
    a2 = np.asarray(r2.to_array(), dtype=DEFAULT_TYPE)
    # Align each array to the union axis order via transpose + reshape.
    a1_aligned = _align(a1, dims1, union)
    a2_aligned = _align(a2, dims2, union)
    return NAryMatrixRelation(
        union, a1_aligned + a2_aligned, name=f"joined_{r1.name}_{r2.name}"
    )


def _align(arr: np.ndarray, dims: List[Variable],
           union: List[Variable]) -> np.ndarray:
    """Transpose/expand `arr` (axes=dims) to broadcast along `union`."""
    if not dims:
        return arr
    order = [dims.index(v) for v in union if v in dims]
    arr_t = np.transpose(arr, order)
    shape = tuple(
        len(v.domain) if v in dims else 1 for v in union
    )
    return arr_t.reshape(shape)


def projection(relation: Constraint, variable: Variable,
               mode: str = "min") -> NAryMatrixRelation:
    """Eliminate `variable` by min- (or max-) reducing its axis.

    DPOP's UTIL projection (reference semantics: relations.py:1717).
    """
    dims = relation.dimensions
    if variable not in dims:
        raise ValueError(
            f"Cannot project {variable.name} out of {relation.name}: "
            "not in dimensions"
        )
    axis = dims.index(variable)
    arr = np.asarray(relation.to_array(), dtype=DEFAULT_TYPE)
    reduced = arr.min(axis=axis) if mode == "min" else arr.max(axis=axis)
    remaining = [v for v in dims if v != variable]
    return NAryMatrixRelation(remaining, reduced, name=relation.name)


def add_var_to_rel(name: str, relation: Constraint, variable: Variable,
                   f: Callable) -> Constraint:
    """Extend a relation with an extra variable combined via ``f(rel, v)``."""
    dims = relation.dimensions + [variable]

    def extended(**kwargs):
        rel_args = {
            v.name: kwargs[v.name] for v in relation.dimensions
        }
        return f(relation(**rel_args), kwargs[variable.name])

    return NAryFunctionRelation(extended, dims, name=name, f_kwargs=True)

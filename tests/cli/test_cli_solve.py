"""CLI tests: spawn the real CLI as a subprocess and parse JSON results.

Mirrors the reference's test strategy (tests/dcop_cli/test_solve.py:33-60).
"""

import json
import os
import subprocess
import sys

import pytest

from fixtures_paths import LOCAL_INSTANCES as INSTANCES
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def run_cli(args, timeout=120):
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli"] + args,
        timeout=timeout, env=ENV,
    )
    return json.loads(out)


def test_solve_maxsum_graph_coloring():
    result = run_cli([
        "solve", "--algo", "maxsum",
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    assert result["status"] in ("FINISHED", "TIMEOUT")
    assert result["violation"] == 0
    assert result["cost"] == pytest.approx(-0.6)
    assert set(result["assignment"]) == {"w1", "w2", "w3", "w4"}


def test_solve_with_algo_params():
    result = run_cli([
        "solve", "--algo", "maxsum",
        "--algo_params", "damping:0.7",
        "--algo_params", "stability:0.01",
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    assert result["cost"] == pytest.approx(-0.6)


def test_solve_bad_algo_param_fails():
    with open(os.devnull, "w") as devnull:
        code = subprocess.call(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli",
             "solve", "--algo", "maxsum", "--algo_params", "bogus:1",
             os.path.join(INSTANCES, "coloring_chain.yaml")],
            stdout=devnull, stderr=devnull, timeout=60, env=ENV,
        )
    assert code != 0


def test_graph_command():
    result = run_cli([
        "graph", "--graph", "factor_graph",
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    assert result["nodes"] == 7  # 4 vars + 3 constraints
    assert result["edges"] == 6


def test_solve_device_profile_writes_trace(tmp_path):
    """--profile wraps the device solve in a JAX profiler trace; the
    dump directory must exist and the result must be unaffected."""
    prof = tmp_path / "prof"
    result = run_cli([
        "solve", "--algo", "maxsum", "-c", "50",
        "--profile", str(prof),
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    assert result["cost"] == pytest.approx(-0.6)
    dumps = list((prof / "plugins" / "profile").iterdir())
    assert len(dumps) == 1


def test_solve_delay_throttles_messages():
    """--delay inserts a per-message delivery delay (reference solve
    --delay): cycle throughput collapses accordingly."""
    slow = run_cli([
        "-t", "2", "solve", "--algo", "maxsum", "-m", "thread",
        "-d", "adhoc", "--delay", "0.1",
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    fast = run_cli([
        "-t", "2", "solve", "--algo", "maxsum", "-m", "thread",
        "-d", "adhoc",
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    # 0.1 s per message bounds the delayed run to a handful of cycles;
    # the undelayed run does hundreds even on a loaded machine.  Avoid
    # a fixed throughput ratio — it encodes machine speed (review).
    assert slow["cycle"] < 50
    assert slow["cycle"] < fast["cycle"]

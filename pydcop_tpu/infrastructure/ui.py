"""Per-agent websocket server for live observability (GUI clients).

Reference parity: pydcop/infrastructure/ui.py (UiServer :43 — one
websocket server per agent forwarding event-bus topics and answering
agent/computation/value queries).

The reference uses the third-party ``websocket-server`` package, which
is not available here; this is a dependency-free RFC 6455 server
(stdlib socket + hashlib/base64) supporting the subset GUI clients
need: text frames, server push, small request/response commands.

Protocol (JSON text frames):
- client -> server: {"cmd": "agent"} | {"cmd": "computations"}
  | {"cmd": "value", "computation": <name>}
- server -> client: {"topic": <event topic>, "data": ...} for every
  event-bus emission, plus {"reply": <cmd>, ...} answers.
"""

import base64
import hashlib
import json
import logging
import socket
import struct
import threading
from typing import List, Optional

from pydcop_tpu.infrastructure.events import event_bus

logger = logging.getLogger("pydcop.ui")

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def _accept_key(client_key: str) -> str:
    digest = hashlib.sha1(
        (client_key + WS_GUID).encode("ascii")).digest()
    return base64.b64encode(digest).decode("ascii")


def encode_text_frame(payload: str) -> bytes:
    """Server-to-client text frame (FIN + opcode 0x1, unmasked)."""
    data = payload.encode("utf-8")
    header = b"\x81"
    n = len(data)
    if n < 126:
        header += struct.pack("!B", n)
    elif n < 65536:
        header += struct.pack("!BH", 126, n)
    else:
        header += struct.pack("!BQ", 127, n)
    return header + data


def decode_frame(sock: socket.socket):
    """Read one client frame; returns (opcode, payload) or None on
    EOF.  Client frames are masked per RFC 6455 §5.3."""
    head = _read_exact(sock, 2)
    if head is None:
        return None
    opcode = head[0] & 0x0F
    masked = bool(head[1] & 0x80)
    length = head[1] & 0x7F
    if length == 126:
        ext = _read_exact(sock, 2)
        if ext is None:
            return None
        length = struct.unpack("!H", ext)[0]
    elif length == 127:
        ext = _read_exact(sock, 8)
        if ext is None:
            return None
        length = struct.unpack("!Q", ext)[0]
    mask = b""
    if masked:
        mask = _read_exact(sock, 4)
        if mask is None:
            return None
    payload = _read_exact(sock, length) if length else b""
    if payload is None:
        return None
    if masked:
        payload = bytes(
            b ^ mask[i % 4] for i, b in enumerate(payload)
        )
    return opcode, payload


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    data = b""
    while len(data) < n:
        try:
            chunk = sock.recv(n - len(data))
        except OSError:
            return None
        if not chunk:
            return None
        data += chunk
    return data


class UiServer:
    """Websocket server attached to one agent."""

    def __init__(self, agent, port: int):
        self.agent = agent
        self.port = port
        self._server_sock: Optional[socket.socket] = None
        self._clients: List[socket.socket] = []
        self._clients_lock = threading.Lock()
        self._running = False
        self._forwarder = None

    def start(self):
        self._server_sock = socket.socket(
            socket.AF_INET, socket.SOCK_STREAM)
        self._server_sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server_sock.bind(("127.0.0.1", self.port))
        self._server_sock.listen(5)
        self._running = True
        threading.Thread(
            target=self._accept_loop, name=f"ui_{self.port}",
            daemon=True,
        ).start()
        # Forward the whole computations.* topic space to clients
        # (reference ui.py:68-74).
        self._forwarder = event_bus.subscribe(
            "computations.*", self._on_event
        )
        logger.info(
            "UI server for agent %s on port %s",
            self.agent.name, self.port,
        )

    def stop(self):
        self._running = False
        if self._forwarder is not None:
            event_bus.unsubscribe(self._forwarder)
        if self._server_sock is not None:
            try:
                self._server_sock.close()
            except OSError:
                pass
        with self._clients_lock:
            for client in self._clients:
                try:
                    client.close()
                except OSError:
                    pass
            self._clients.clear()

    # -- connections --------------------------------------------------- #

    def _accept_loop(self):
        while self._running:
            try:
                client, _ = self._server_sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._client_loop, args=(client,),
                daemon=True,
            ).start()

    def _client_loop(self, client: socket.socket):
        if not self._handshake(client):
            client.close()
            return
        with self._clients_lock:
            self._clients.append(client)
        try:
            while self._running:
                frame = decode_frame(client)
                if frame is None:
                    break
                opcode, payload = frame
                if opcode == 0x8:  # close
                    break
                if opcode == 0x9:  # ping -> pong
                    client.sendall(
                        b"\x8a" + bytes([len(payload)]) + payload)
                    continue
                if opcode == 0x1:
                    self._on_command(client, payload)
        finally:
            with self._clients_lock:
                if client in self._clients:
                    self._clients.remove(client)
            try:
                client.close()
            except OSError:
                pass

    def _handshake(self, client: socket.socket) -> bool:
        try:
            request = client.recv(4096).decode("latin-1")
        except OSError:
            return False
        key = None
        for line in request.split("\r\n"):
            if line.lower().startswith("sec-websocket-key:"):
                key = line.split(":", 1)[1].strip()
        if key is None:
            return False
        response = (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {_accept_key(key)}\r\n\r\n"
        )
        client.sendall(response.encode("latin-1"))
        return True

    # -- push + commands ----------------------------------------------- #

    def _on_event(self, topic: str, data):
        # The bus is process-global: only forward events for
        # computations this agent actually hosts.
        comp = topic.rsplit(".", 1)[-1]
        if not self.agent.has_computation(comp):
            return
        try:
            payload = json.dumps(
                {"topic": topic, "data": _jsonable(data)}
            )
        except Exception:
            return
        self._broadcast(payload)

    def _broadcast(self, payload: str):
        frame = encode_text_frame(payload)
        with self._clients_lock:
            clients = list(self._clients)
        for client in clients:
            try:
                client.sendall(frame)
            except OSError:
                with self._clients_lock:
                    if client in self._clients:
                        self._clients.remove(client)

    def _on_command(self, client: socket.socket, payload: bytes):
        try:
            request = json.loads(payload.decode("utf-8"))
            cmd = request.get("cmd")
        except Exception:
            return
        if cmd == "agent":
            reply = {
                "reply": "agent",
                "agent": self.agent.name,
                "computations": [
                    c.name for c in self.agent.computations
                ],
            }
        elif cmd == "computations":
            reply = {
                "reply": "computations",
                "computations": {
                    c.name: {
                        "running": c.is_running,
                        "value": getattr(c, "current_value", None),
                    }
                    for c in self.agent.computations
                    if not c.name.startswith("_")
                },
            }
        elif cmd == "value":
            name = request.get("computation")
            value = None
            if self.agent.has_computation(name):
                value = getattr(
                    self.agent.computation(name),
                    "current_value", None,
                )
            reply = {
                "reply": "value", "computation": name,
                "value": _jsonable(value),
            }
        else:
            reply = {"reply": "error", "error": f"unknown cmd {cmd}"}
        try:
            client.sendall(encode_text_frame(json.dumps(reply)))
        except OSError:
            pass


def _jsonable(data):
    try:
        json.dumps(data)
        return data
    except (TypeError, ValueError):
        return repr(data)

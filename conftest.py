"""Root conftest: the same CPU-backend forcing tests/conftest.py does,
applied repo-wide so ``pytest --doctest-modules pydcop_tpu`` (the
doctest gate, reference Makefile:6) runs the package's docstring
examples under the 8-virtual-device CPU platform instead of trying to
reach the TPU tunnel."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

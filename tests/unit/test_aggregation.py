"""Aggregation-strategy equivalence (ops/maxsum.aggregate_beliefs).

The scatter path is the parity default; sorted/boundary are the
HBM-regime options (engine/compile.build_aggregation_arrays).  All
three compute the same per-variable sums up to float reassociation, and
full solves must select the same assignment on a well-separated
problem.
"""

import jax
import numpy as np
import pytest
from functools import partial

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.ops import maxsum as ops


def _coloring(n_vars=300, seed=5):
    rng = np.random.default_rng(seed)
    dom = Domain("colors", "color", [0, 1, 2])
    dcop = DCOP("agg_gc", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    eq = np.eye(3, dtype=np.float64)
    seen = set()
    for k in range(int(n_vars * 1.5)):
        i, j = rng.choice(n_vars, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], eq, f"c{k}"))
    return dcop


@pytest.mark.parametrize("strategy", ["sorted", "boundary", "ell"])
def test_aggregate_matches_scatter(strategy):
    dcop = _coloring()
    g_sc, _ = compile_dcop(dcop, noise_level=0.01)
    g_st, _ = compile_dcop(dcop, noise_level=0.01,
                           aggregation=strategy)
    state = ops.init_state(g_sc)
    # a few real supersteps so messages are non-trivial
    step = jax.jit(partial(
        ops.superstep, damping=0.5, damp_vars=True, damp_factors=True,
        stability=0.1))
    for _ in range(3):
        state = step(state, g_sc)
    b_sc, s_sc = ops.aggregate_beliefs(g_sc, state.f2v)
    b_st, s_st = ops.aggregate_beliefs(g_st, state.f2v)
    np.testing.assert_allclose(
        np.asarray(s_sc), np.asarray(s_st), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(b_sc), np.asarray(b_st), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("strategy", ["sorted", "ell"])
def test_full_solve_same_assignment(strategy):
    from pydcop_tpu.api import solve

    dcop = _coloring(n_vars=150, seed=9)
    base = solve(dcop, "maxsum", max_cycles=60)
    alt = solve(dcop, "maxsum", max_cycles=60,
                algo_params={"aggregation": strategy})
    assert alt["cost"] == base["cost"]
    assert alt["assignment"] == base["assignment"]


@pytest.mark.parametrize(
    "algo", ["dsa", "adsa", "mgm", "dba", "gdba", "mgm2", "mixeddsa"])
def test_local_search_ell_bit_parity(algo):
    """With integer constraint costs, the ell sums are exact, so the
    local-search trajectory (and final assignment) must be
    bit-identical to the scatter path for every algorithm exposing
    the param."""
    from pydcop_tpu.api import solve

    dcop = _coloring(n_vars=120, seed=7)
    base = solve(dcop, algo, max_cycles=40, algo_params={"seed": 3})
    alt = solve(dcop, algo, max_cycles=40,
                algo_params={"seed": 3, "aggregation": "ell"})
    assert alt["cost"] == base["cost"]
    assert alt["assignment"] == base["assignment"]


@pytest.mark.parametrize("strategy", ["sorted", "ell"])
def test_non_scatter_aggregation_rejected_on_mesh(strategy):
    """shard_graph drops the agg_* arrays, so a non-scatter strategy
    on a mesh would silently measure scatter — build_engine must
    refuse loudly instead."""
    from pydcop_tpu.api import solve

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device backend")
    dcop = _coloring(n_vars=64, seed=2)
    with pytest.raises(ValueError, match="single-device"):
        solve(dcop, "maxsum", max_cycles=5, n_devices=2,
              algo_params={"aggregation": strategy})


def test_ell_lists_cover_every_real_edge_once():
    """Structural invariant behind the dense-gather path: every real
    edge index appears in exactly one variable's list, every dummy
    slot holds E, and the sentinel row is all-dummy."""
    dcop = _coloring(n_vars=80, seed=4)
    graph, _ = compile_dcop(dcop, aggregation="ell")
    seg = np.concatenate(
        [b.var_ids.reshape(-1) for b in graph.buckets])
    n_edges = seg.size
    ell = np.asarray(graph.agg_ell)
    assert ell.shape[0] == graph.var_costs.shape[0]
    assert (ell[-1] == n_edges).all()          # sentinel row: dummies
    real_entries = ell[ell < n_edges]
    # Each real edge appears exactly once, in its own variable's row.
    assert sorted(real_entries.tolist()) == list(range(n_edges))
    rows, _ = np.nonzero(ell < n_edges)
    np.testing.assert_array_equal(
        seg[real_entries], rows.astype(seg.dtype))


def test_ell_max_degree_matches_k():
    dcop = _coloring(n_vars=80, seed=4)
    graph, _ = compile_dcop(dcop, aggregation="ell")
    seg = np.concatenate(
        [b.var_ids.reshape(-1) for b in graph.buckets])
    counts = np.bincount(seg, minlength=graph.var_costs.shape[0])
    assert graph.agg_ell.shape[1] == counts[:-1].max()


def test_boundary_not_a_solve_option():
    """'boundary' is experiment-only (f32 prefix-sum cancellation at
    scale — ops/maxsum.aggregate_beliefs docstring); the maxsum param
    validator must reject it."""
    from pydcop_tpu.api import solve

    dcop = _coloring(n_vars=20, seed=3)
    with pytest.raises(Exception, match="aggregation"):
        solve(dcop, "maxsum", max_cycles=5,
              algo_params={"aggregation": "boundary"})


def test_sharded_graph_drops_sort_arrays():
    from pydcop_tpu.engine.sharding import make_mesh, shard_graph

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device backend")
    dcop = _coloring(n_vars=64, seed=2)
    mesh = make_mesh(2)
    graph, _ = compile_dcop(dcop, pad_to=2, aggregation="sorted")
    assert graph.agg_perm is not None
    sharded = shard_graph(graph, mesh)
    assert sharded.agg_perm is None  # scatter path on meshes


def test_decimation_composes_with_ell():
    """run_decimated clamps var_costs rows via graph._replace, which
    must preserve the ell lists — the decimated rounds aggregate
    through them."""
    from pydcop_tpu.api import solve

    dcop = _coloring(n_vars=60, seed=5)
    base = solve(dcop, "maxsum", max_cycles=120,
                 algo_params={"decimation": 10})
    alt = solve(dcop, "maxsum", max_cycles=120,
                algo_params={"decimation": 10, "aggregation": "ell"})
    assert alt["cost"] == base["cost"]
    assert alt["assignment"] == base["assignment"]


def test_ell_hub_guard():
    """A power-law hub makes K = max degree explode the [V+1, K]
    lists; the builder must refuse with guidance instead of OOMing
    (exercised via a synthetic bucket so no giant graph is built)."""
    import numpy as np

    from pydcop_tpu.engine.compile import (
        FactorBucket,
        build_aggregation_arrays,
    )

    n_vars = 2_000_000
    # 600k binary factors all touching variable 0 (the hub).
    ids = np.zeros((600_000, 2), np.int32)
    ids[:, 1] = np.arange(600_000) % (n_vars - 1) + 1
    bucket = FactorBucket(np.zeros((600_000, 2, 2), np.float32), ids)
    with pytest.raises(ValueError, match="hub"):
        build_aggregation_arrays((bucket,), n_vars + 1, "ell")


def test_unknown_aggregation_rejected():
    dcop = _coloring(n_vars=10, seed=1)
    with pytest.raises(ValueError):
        compile_dcop(dcop, aggregation="nope")

"""CSV metrics output, preserving the reference's column schema.

Reference parity: pydcop/commands/solve.py:386-443 (csv writers used by
--run_metrics / --end_metrics with --collect_on).
"""

import csv
import os
from typing import Dict

COLUMNS = [
    "time", "cycle", "cost", "violation", "msg_count", "msg_size",
    "status",
]


def add_csvline(path: str, collect_on: str, metrics: Dict):
    exists = os.path.exists(path)
    with open(path, "a", newline="", encoding="utf-8") as f:
        writer = csv.writer(f)
        if not exists:
            writer.writerow(COLUMNS)
        writer.writerow([metrics.get(c, "") for c in COLUMNS])

"""Roofline accounting tests: FLOP/byte counts come from bucket shapes
alone and must match hand-computed values on a known graph."""

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.compile import compile_factor_graph
from pydcop_tpu.engine.roofline import (
    V5E_HBM_BYTES_PER_S,
    V5E_PEAK_FLOPS_BF16,
    maxsum_superstep_bytes,
    maxsum_superstep_flops,
    roofline_report,
)


def _graph(n_vars=4, arity2=3):
    d = Domain("d", "", [0, 1, 2])
    vs = [Variable(f"v{i}", d) for i in range(n_vars)]
    cs = [
        constraint_from_str(f"c{i}", f"v{i} + v{i + 1}",
                            [vs[i], vs[i + 1]])
        for i in range(arity2)
    ]
    graph, _ = compile_factor_graph(vs, cs)
    return graph


def test_flops_formula_matches_hand_count():
    graph = _graph()
    # V+1=5 rows, D=3, one bucket: F=3, a=2, D^a=9.
    # var-cost add: 5*3 = 15
    # hypercube: 2*a*F*D^a = 2*2*3*9 = 108
    # per-message term: F*a*D * 29 = 3*2*3*29 = 522
    assert maxsum_superstep_flops(graph) == 15 + 108 + 522


def test_bytes_formula_matches_hand_count():
    graph = _graph()
    # var tables: 4 * (5*3) * 4B = 240
    # cost tables: 3*9*4 = 108
    # messages: 6 * 3*2*3 * 4 = 432
    # indices: 3*2 * 4 = 24
    assert maxsum_superstep_bytes(graph) == 240 + 108 + 432 + 24


def test_report_tpu_vs_cpu():
    graph = _graph()
    tpu = roofline_report(graph, cycles_per_s=1000.0, platform="tpu",
                          device_kind="TPU v5 lite")
    assert tpu["mfu"] is not None and 0 < tpu["mfu"] < 1
    expected_mfu = (
        maxsum_superstep_flops(graph) * 1000.0 / V5E_PEAK_FLOPS_BF16
    )
    assert abs(tpu["mfu"] - expected_mfu) < 1e-9
    # The tiny test graph fits in VMEM: no HBM-utilization claim.
    assert tpu["vmem_resident"] is True
    assert tpu["hbm_util"] is None and tpu["achieved_gbps"] is None

    cpu = roofline_report(graph, cycles_per_s=1000.0, platform="cpu")
    assert cpu["mfu"] is None and cpu["hbm_util"] is None
    assert cpu["vmem_resident"] is None
    assert cpu["achieved_gbps"] is not None
    assert cpu["achieved_gflops"] == tpu["achieved_gflops"]


def test_hbm_util_claimed_only_when_not_vmem_resident(monkeypatch):
    """A working set larger than half VMEM gets a real hbm_util; the
    threshold logic is exercised by shrinking the VMEM table rather
    than allocating a >64 MiB graph."""
    import pydcop_tpu.engine.roofline as rl

    graph = _graph()
    monkeypatch.setattr(rl, "TPU_VMEM_BYTES", 2)
    rep = rl.roofline_report(graph, cycles_per_s=1000.0,
                             platform="tpu",
                             device_kind="TPU v5 lite")
    assert rep["vmem_resident"] is False
    expected_bw = (
        maxsum_superstep_bytes(graph) * 1000.0 / V5E_HBM_BYTES_PER_S
    )
    assert abs(rep["hbm_util"] - expected_bw) < 1e-6
    assert rep["achieved_gbps"] is not None


def test_working_set_accounts_state_and_graph():
    from pydcop_tpu.engine.roofline import working_set_bytes

    graph = _graph()
    # var tables: costs 5*3*4 + valid 5*3*1 = 75
    # bucket: costs 3*9*4=108, ids 3*2*4=24, msgs 2*3*2*3*4=144,
    # counters 2*3*2*1=12 (int8 — ops/maxsum.init_state)
    assert working_set_bytes(graph) == 75 + 108 + 24 + 144 + 12


def test_report_no_utilization_claim_for_unknown_tpu_kind():
    """An unrecognized TPU generation must not borrow v5e peaks
    (ADVICE r2): achieved numbers only, utilizations None."""
    graph = _graph()
    for kind in (None, "TPU v99"):
        rep = roofline_report(graph, cycles_per_s=1000.0,
                              platform="tpu", device_kind=kind)
        assert rep["mfu"] is None and rep["hbm_util"] is None
        assert rep["achieved_gflops"] > 0

    v4 = roofline_report(graph, cycles_per_s=1000.0, platform="tpu",
                         device_kind="TPU v4")
    v5e = roofline_report(graph, cycles_per_s=1000.0, platform="tpu",
                          device_kind="TPU v5 lite")
    # Same achieved rate → lower utilization on the bigger chip.
    assert v4["mfu"] < v5e["mfu"]


def test_counts_scale_with_buckets():
    small = _graph(n_vars=4, arity2=3)
    big = _graph(n_vars=4, arity2=3)
    assert maxsum_superstep_flops(small) == maxsum_superstep_flops(big)
    wider = _graph(n_vars=6, arity2=5)
    assert maxsum_superstep_flops(wider) > maxsum_superstep_flops(small)


def test_rejects_lane_graph():
    """A lane-major graph has every axis transposed; the positional
    shape unpacking would count ~1e6x-off garbage silently, so the
    report must refuse it (isinstance, so a rename breaks this test
    rather than silently disabling the guard)."""
    import pytest

    from pydcop_tpu.ops.maxsum_lane import to_lane_graph

    lane = to_lane_graph(_graph(n_vars=4, arity2=3))
    with pytest.raises(TypeError, match="edge-major"):
        roofline_report(lane, cycles_per_s=1000.0, platform="cpu")


def test_ell_graph_counts_list_traffic():
    """An ell graph's byte model must charge the edge-list reads and
    the padded gather (V*K rows, padding waste included) in place of
    one scatter message pass, and carry the lists in the working
    set."""
    from pydcop_tpu.engine.compile import build_aggregation_arrays
    from pydcop_tpu.engine.roofline import working_set_bytes

    graph = _graph(n_vars=6, arity2=5)
    _, _, _, _, ell = build_aggregation_arrays(
        graph.buckets, graph.var_costs.shape[0], "ell")
    g_ell = graph._replace(agg_ell=ell)
    d = graph.var_costs.shape[1]
    itemsize = graph.var_costs.dtype.itemsize
    delta = maxsum_superstep_bytes(g_ell) - maxsum_superstep_bytes(graph)
    f, a = graph.buckets[0].var_ids.shape
    expected = (ell.size * 4 + ell.size * d * itemsize
                - f * a * d * itemsize)
    assert delta == expected
    assert (working_set_bytes(g_ell) - working_set_bytes(graph)
            == ell.size * 4)

"""Performance-regression gate for the flagship device kernels
(maxsum superstep, dsa, mgm, dpop sweep).

Motivation (round-3 verdict): the bench's absolute CPU cycles/s drifted
927 -> 755 -> 665 across rounds.  Investigation showed the r1->r2 step
was a real feature cost (exact-parity send-suppression landed between
BENCH_r01 and r02) and the rest was machine load — the r1 tree re-run on
the r4 machine measures the same as the r4 tree.  An absolute wall-clock
budget would therefore false-alarm on load and miss nothing; instead
each live kernel races a FROZEN copy of itself (golden_*.py) in the
same process and must stay within its RATIO_TOL of it.  A slowdown
beyond the tolerance fails here regardless of machine speed.

The parity tests double as semantics freezes: each live kernel must
produce its golden copy's exact seeded trajectory so "optimizations"
cannot silently change semantics.

Tolerance ratchet: maxsum's gate has a round of stability history
(r4 -> r5) and runs at 1.25; the dsa/mgm/dpop gates are new this round
and start at 1.35 — tighten them toward 1.2 once they too have a
stable round behind them.
"""

import time
from functools import partial

import jax
import numpy as np
import pytest

from tests.unit import golden_dpop_r5 as golden_dpop
from tests.unit import golden_localsearch_r5 as golden_ls
from tests.unit import golden_maxsum_kernel as golden

N_VARS = 2_000
N_COLORS = 3
CYCLES = 100
RATIO_TOL = 1.25
NEW_GATE_TOL = 1.35  # dsa/mgm/dpop: first round, no stability history
REPEATS = 5


@pytest.fixture(scope="module")
def problem():
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.engine.compile import compile_dcop

    rng = np.random.default_rng(11)
    dom = Domain("colors", "color", list(range(N_COLORS)))
    dcop = DCOP("perf_gc", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(N_VARS)]
    for v in variables:
        dcop.add_variable(v)
    eq = np.eye(N_COLORS, dtype=np.float64)
    seen = set()
    for k in range(int(N_VARS * 1.5)):
        i, j = rng.choice(N_VARS, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], eq, f"c{k}"))
    graph, meta = compile_dcop(dcop, noise_level=0.01)
    return jax.device_put(graph)


def _best_time(fn, graph):
    from pydcop_tpu.engine.timing import sync, timed_call

    sync(fn(graph))  # compile + warm (true completion, not a partial
    #                  sync — engine/timing.py; on the CPU test
    #                  backend the two are equivalent)
    best = float("inf")
    for _ in range(REPEATS):
        _, elapsed = timed_call(fn, graph)
        best = min(best, elapsed)
    return best


def test_superstep_not_slower_than_golden(problem):
    from pydcop_tpu.ops import maxsum as ops

    live = jax.jit(partial(
        ops.run_maxsum, max_cycles=CYCLES, stop_on_convergence=False))
    gold = jax.jit(partial(golden.run_maxsum, max_cycles=CYCLES))
    t_live = _best_time(live, problem)
    t_gold = _best_time(gold, problem)
    ratio = t_live / t_gold
    assert ratio <= RATIO_TOL, (
        f"live superstep is {ratio:.2f}x the frozen r4 baseline "
        f"({t_live*1e3:.2f} ms vs {t_gold*1e3:.2f} ms for {CYCLES} "
        f"cycles) — a real kernel regression, not machine noise "
        f"(both timed in this process)"
    )


def test_superstep_semantics_frozen(problem):
    from pydcop_tpu.ops import maxsum as ops

    live = jax.jit(partial(
        ops.run_maxsum, max_cycles=CYCLES, stop_on_convergence=False))
    gold = jax.jit(partial(golden.run_maxsum, max_cycles=CYCLES))
    s_live, v_live = live(problem)
    s_gold, v_gold = gold(problem)
    assert (np.asarray(v_live) == np.asarray(v_gold)).all()
    assert bool(s_live.stable) == bool(s_gold.stable)
    np.testing.assert_array_equal(
        np.asarray(s_live.f2v[0]), np.asarray(s_gold.f2v[0]))


# ---- dsa / mgm kernel gates (VERDICT r4 next #5) ---------------------- #


@pytest.fixture(scope="module")
def hypergraph_problem():
    """Same random coloring, compiled WITHOUT noise: the local-search
    kernels' trajectories must be exactly reproducible from the seed."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.engine.compile import compile_dcop

    rng = np.random.default_rng(17)
    dom = Domain("colors", "color", list(range(N_COLORS)))
    dcop = DCOP("perf_ls", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(N_VARS)]
    for v in variables:
        dcop.add_variable(v)
    eq = np.eye(N_COLORS, dtype=np.float64)
    seen = set()
    for k in range(int(N_VARS * 1.5)):
        i, j = rng.choice(N_VARS, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], eq, f"c{k}"))
    graph, meta = compile_dcop(dcop)
    return jax.device_put(graph)


def test_dsa_kernel_not_slower_than_golden(hypergraph_problem):
    from pydcop_tpu.ops import dsa as ops

    live = jax.jit(partial(
        ops.run_dsa, max_cycles=CYCLES, variant="B", seed=3))
    gold = jax.jit(partial(
        golden_ls.run_dsa, max_cycles=CYCLES, variant="B", seed=3))
    t_live = _best_time(live, hypergraph_problem)
    t_gold = _best_time(gold, hypergraph_problem)
    ratio = t_live / t_gold
    assert ratio <= NEW_GATE_TOL, (
        f"live dsa kernel is {ratio:.2f}x the frozen r5 baseline "
        f"({t_live*1e3:.2f} ms vs {t_gold*1e3:.2f} ms)"
    )


def test_dsa_kernel_semantics_frozen(hypergraph_problem):
    from pydcop_tpu.ops import dsa as ops

    for variant in ("A", "B", "C"):
        v_live, c_live, _ = jax.jit(partial(
            ops.run_dsa, max_cycles=CYCLES, variant=variant, seed=3
        ))(hypergraph_problem)
        v_gold, c_gold, _ = jax.jit(partial(
            golden_ls.run_dsa, max_cycles=CYCLES, variant=variant,
            seed=3,
        ))(hypergraph_problem)
        np.testing.assert_array_equal(
            np.asarray(v_live), np.asarray(v_gold),
            err_msg=f"dsa variant {variant} trajectory changed",
        )
        assert float(c_live) == float(c_gold)


def test_mgm_kernel_not_slower_than_golden(hypergraph_problem):
    from pydcop_tpu.ops import mgm as ops

    n = int(hypergraph_problem.var_costs.shape[0])
    ranks = jax.numpy.arange(n, dtype=jax.numpy.float32)
    live = jax.jit(partial(
        ops.run_mgm, max_cycles=CYCLES, lexic_ranks=ranks, seed=3))
    gold = jax.jit(partial(
        golden_ls.run_mgm, max_cycles=CYCLES, lexic_ranks=ranks,
        seed=3))
    t_live = _best_time(live, hypergraph_problem)
    t_gold = _best_time(gold, hypergraph_problem)
    ratio = t_live / t_gold
    assert ratio <= NEW_GATE_TOL, (
        f"live mgm kernel is {ratio:.2f}x the frozen r5 baseline "
        f"({t_live*1e3:.2f} ms vs {t_gold*1e3:.2f} ms)"
    )


def test_mgm_kernel_semantics_frozen(hypergraph_problem):
    from pydcop_tpu.ops import mgm as ops

    n = int(hypergraph_problem.var_costs.shape[0])
    ranks = jax.numpy.arange(n, dtype=jax.numpy.float32)
    for break_mode in ("lexic", "random"):
        v_live, c_live, _ = jax.jit(partial(
            ops.run_mgm, max_cycles=CYCLES, lexic_ranks=ranks,
            break_mode=break_mode, seed=3,
        ))(hypergraph_problem)
        v_gold, c_gold, _ = jax.jit(partial(
            golden_ls.run_mgm, max_cycles=CYCLES, lexic_ranks=ranks,
            break_mode=break_mode, seed=3,
        ))(hypergraph_problem)
        np.testing.assert_array_equal(
            np.asarray(v_live), np.asarray(v_gold),
            err_msg=f"mgm break_mode {break_mode} trajectory changed",
        )
        assert float(c_live) == float(c_gold)


# ---- dpop sweep gate (VERDICT r4 next #5) ----------------------------- #


@pytest.fixture(scope="module")
def dpop_tree():
    """A 1500-variable random tree-ish coloring whose pseudo-tree the
    level-batched sweep must solve fast (host-driven, so the race times
    the full compile_tree + UTIL + VALUE pipeline end to end)."""
    from pydcop_tpu.computations_graph.pseudotree import (
        build_computation_graph,
    )
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(23)
    dom = Domain("colors", "color", list(range(N_COLORS)))
    dcop = DCOP("perf_dpop", objective="min")
    n = 1_500
    variables = [Variable(f"v{i}", dom) for i in range(n)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(1, n):
        p = int(rng.integers(0, i))
        dcop.add_constraint(NAryMatrixRelation(
            [variables[p], variables[i]],
            rng.random((N_COLORS, N_COLORS)).round(3), f"c{i}"))
    return build_computation_graph(dcop)


def _best_time_host(fn, *args):
    fn(*args)  # compile + warm the kernel caches
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        fn(*args)
        best = min(best, time.perf_counter() - t0)
    return best


def test_dpop_sweep_not_slower_than_golden(dpop_tree):
    from pydcop_tpu.ops import dpop as ops

    t_live = _best_time_host(ops.solve_sweep, dpop_tree)
    t_gold = _best_time_host(golden_dpop.solve_sweep, dpop_tree)
    ratio = t_live / t_gold
    assert ratio <= NEW_GATE_TOL, (
        f"live dpop sweep is {ratio:.2f}x the frozen r5 baseline "
        f"({t_live*1e3:.1f} ms vs {t_gold*1e3:.1f} ms end to end)"
    )


def test_dpop_sweep_semantics_frozen(dpop_tree):
    from pydcop_tpu.ops import dpop as ops

    live, _stats = ops.solve_sweep(dpop_tree)
    gold = golden_dpop.solve_sweep(dpop_tree)
    assert live == gold

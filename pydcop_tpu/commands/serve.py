"""``pydcop serve``: run the multi-tenant solve service.

No reference analogue — the reference runs one problem per process
(``pydcop solve``) or per subprocess (``pydcop batch``); this serves
a *stream* of problems over HTTP, stacking same-structure requests
into single device dispatches (docs/serving.md).
"""

import logging

logger = logging.getLogger("pydcop.cli.serve")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "serve",
        help="serve solve requests over HTTP with structure-binned "
             "device batching")
    parser.add_argument("--port", type=int, default=8080,
                        help="HTTP port (0 = OS-assigned, printed on "
                             "stderr)")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address")
    parser.add_argument("--max_queue", "--max-queue", type=int,
                        default=256,
                        help="request queue bound; also the default "
                             "admission high-water mark")
    parser.add_argument("--high_water", "--high-water", type=int,
                        default=None,
                        help="queue depth past which submits get 429 "
                             "(default: --max_queue)")
    parser.add_argument("--batch_window", "--batch-window",
                        type=float, default=0.02, metavar="SECONDS",
                        help="how long the scheduler lingers after "
                             "the first request collecting "
                             "same-structure batch-mates")
    parser.add_argument("--max_batch", "--max-batch", type=int,
                        default=16,
                        help="largest number of instances stacked "
                             "into one device dispatch")
    parser.add_argument("--breaker_failures", type=int, default=3,
                        help="consecutive dispatch failures before "
                             "the admission breaker opens (503s)")
    parser.add_argument("--breaker_reset", type=float, default=5.0,
                        metavar="SECONDS",
                        help="seconds the breaker stays open before "
                             "a half-open probe dispatch")
    parser.add_argument("--cycles", type=int, default=200,
                        help="default max_cycles for requests that "
                             "don't set params.max_cycles")
    parser.add_argument("--damping", type=float, default=0.5,
                        help="default MaxSum damping for requests")
    parser.add_argument("--params_json", "--params-json",
                        default=None, metavar="JSON",
                        help="service-wide solver-parameter defaults "
                             "as a JSON object (any serving/binning "
                             "PARAM_KEYS key: stability, noise, "
                             "damping_nodes, prune, ...); merged over "
                             "--cycles/--damping — how the fleet "
                             "router forwards api.serve's full "
                             "default_params to every worker")
    parser.add_argument("--result_keep", type=int, default=4096,
                        help="completed results retained for "
                             "GET /result/<id> (oldest evicted)")
    parser.add_argument("--journal_dir", "--journal-dir",
                        default=None, metavar="DIR",
                        help="durable request journal directory: "
                             "every 202 is journaled before it is "
                             "returned, so a crash loses zero "
                             "acknowledged requests")
    parser.add_argument("--recover", action="store_true",
                        help="replay accepted-but-unfinished journal "
                             "entries through the queue on startup "
                             "(requires --journal_dir; torn journal "
                             "tails are truncated past the last "
                             "valid record)")
    parser.add_argument("--journal_sync", "--journal-sync",
                        action="store_true",
                        help="fsync the journal per record "
                             "(machine-crash durability; the default "
                             "flush already survives a process kill)")
    parser.add_argument("--no_envelope", "--no-envelope",
                        action="store_true",
                        help="disable the envelope batching tier: "
                             "different-structure requests always "
                             "dispatch solo (docs/serving.md "
                             "\"Envelope batching\")")
    parser.add_argument("--envelope_overhead_ms",
                        "--envelope-overhead-ms",
                        type=float, default=None, metavar="MS",
                        help="modeled per-dispatch fixed cost the "
                             "envelope pack-vs-solo decision weighs "
                             "against padding waste (default 0.3; "
                             "raise to pack more aggressively)")
    parser.add_argument("--no_pipeline", "--no-pipeline",
                        action="store_true",
                        help="disable pipelined flush decode: every "
                             "dispatch waits for its results before "
                             "the next one launches (docs/"
                             "performance.md \"Closed-loop "
                             "efficiency\")")
    parser.add_argument("--no_speculate", "--no-speculate",
                        action="store_true",
                        help="disable speculative envelope "
                             "compilation: programs compile on the "
                             "request path, on first use only")
    parser.add_argument("--flight_recorder_events",
                        "--flight-recorder-events",
                        type=int, default=None, metavar="N",
                        help="size of the always-on flight-recorder "
                             "ring (trace events kept for anomaly "
                             "postmortem bundles; 0 disables; "
                             "default: PYDCOP_FLIGHT_RECORDER or "
                             "2048 — docs/observability.md)")
    parser.add_argument("--session_max", "--session-max", type=int,
                        default=64,
                        help="live stateful sessions allowed at once "
                             "(each keeps a warm engine; opens past "
                             "it get 429 — docs/sessions.md)")
    parser.add_argument("--session_segment_cycles",
                        "--session-segment-cycles",
                        type=int, default=None, metavar="CYCLES",
                        help="session anytime-segment granularity: "
                             "cycles per engine segment between SSE "
                             "updates (default 50; smaller = fresher "
                             "streams, more host syncs)")
    parser.add_argument("--session_checkpoint_every",
                        "--session-checkpoint-every",
                        type=int, default=8, metavar="EVENTS",
                        help="event batches between session "
                             "engine-state checkpoints (journaled "
                             "services; smaller = faster --recover, "
                             "more snapshot writes; 0 disables)")
    parser.add_argument("--session_certify_after",
                        "--session-certify-after",
                        type=float, default=None, metavar="SECONDS",
                        help="exact-inference oracle tier: after a "
                             "session's event stream quiesces for "
                             "this many seconds, a background DPOP "
                             "solve certifies (or improves) the warm "
                             "fixpoint and publishes the certified-"
                             "cost delta (default: off — "
                             "docs/sessions.md)")
    parser.add_argument("--replicas", type=int, default=1,
                        help="worker replicas: N > 1 spawns N serve "
                             "worker processes (each its own "
                             "scheduler/journal segment/metrics) "
                             "behind a structure-affinity router on "
                             "--port (docs/serving.md \"Fleet-scale "
                             "serving\")")
    parser.add_argument("--affinity",
                        choices=("structure", "round_robin"),
                        default="structure",
                        help="fleet routing policy: 'structure' "
                             "rendezvous-hashes the admission-time "
                             "structure key so same-structure "
                             "traffic lands where the compiled "
                             "program is warm; 'round_robin' is the "
                             "A/B baseline")
    parser.add_argument("--compile_cache_dir", "--compile-cache-dir",
                        default=None, metavar="DIR",
                        help="persistent AOT compile cache: XLA "
                             "executables persist to DIR across "
                             "processes, so a fresh worker serves "
                             "its first same-structure request "
                             "without recompiling (enabled BEFORE "
                             "the first jit — the set-after-jit "
                             "config latch is handled internally; "
                             "fleet workers inherit the directory)")
    parser.add_argument("--heartbeat", type=float, default=0.25,
                        metavar="SECONDS",
                        help="fleet router heartbeat cadence; a "
                             "replica silent for ~8 expected beats "
                             "(phi-accrual model) is declared dead "
                             "and restarted on its journal segment")
    parser.add_argument("--probe_timeout_s", "--probe-timeout-s",
                        type=float, default=None, metavar="SECONDS",
                        help="liveness probe timeout (default: "
                             "max(4x heartbeat, 1.0)); raise it when "
                             "links are slow so latency reads as "
                             "GRAY degradation on /healthz instead "
                             "of false-killing replicas")
    parser.add_argument("--spill_slack", "--spill-slack", type=int,
                        default=4,
                        help="affinity spillover threshold: a "
                             "structure-warm replica more than this "
                             "many requests deeper in flight than "
                             "the idlest one loses the request to it")
    parser.add_argument("--hosts", type=int, default=1,
                        help="simulated host identities the local "
                             "fleet's replicas stripe over (host-kill "
                             "chaos + CI two-host topologies; replica "
                             "k gets host id 'host<k %% hosts>')")
    parser.add_argument("--join", default=None, metavar="ROUTER_URL",
                        help="single-replica remote fleet member: "
                             "after binding, announce this worker's "
                             "URL to the fleet router at ROUTER_URL "
                             "via POST /fleet/join (incompatible "
                             "with --replicas > 1)")
    parser.add_argument("--host_id", "--host-id", default=None,
                        help="host identity announced with --join "
                             "(default: PYDCOP_HOST_ID or the "
                             "machine hostname)")
    parser.add_argument("--slo_p99_ms", "--slo-p99-ms", type=float,
                        default=None, metavar="MS",
                        help="autoscaling SLO: with --max_replicas, "
                             "the router grows the fleet when rolling "
                             "p99 latency or queue depth breaches "
                             "this target and drains back when quiet "
                             "(docs/serving.md \"Elastic fleet\")")
    parser.add_argument("--min_replicas", "--min-replicas", type=int,
                        default=None,
                        help="autoscale floor (default: 1)")
    parser.add_argument("--max_replicas", "--max-replicas", type=int,
                        default=None,
                        help="autoscale ceiling; must be >= "
                             "--replicas (autoscaling is armed only "
                             "when both this and --slo_p99_ms are "
                             "set)")
    parser.add_argument("--fleet_trace", "--fleet-trace",
                        action="store_true", dest="fleet_trace",
                        default=None,
                        help="force fleet-wide causal tracing ON: "
                             "the router mints a trace context per "
                             "admission, stamps it on every forward, "
                             "and collects replica spans for "
                             "/fleet/forensics (default: on unless "
                             "PYDCOP_FLEET_TRACE=0)")
    parser.add_argument("--no_fleet_trace", "--no-fleet-trace",
                        action="store_false", dest="fleet_trace",
                        help="disable fleet tracing (headers, span "
                             "shipping and the router collector; "
                             "sets PYDCOP_FLEET_TRACE=0 for spawned "
                             "workers too)")
    parser.add_argument("--port_file", "--port-file", default=None,
                        metavar="PATH",
                        help="atomically write the bound port to "
                             "PATH once listening (with --port 0: "
                             "how wrappers and the fleet router "
                             "learn the assignment)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    # FIRST, before anything that could jit (probe, api import side
    # effects): the persistent compile cache's directory config
    # silently no-ops once a jit has run (engine/aotcache latch).
    # Spawned fleet workers arrive here with the router's directory
    # in PYDCOP_COMPILE_CACHE_DIR.
    from pydcop_tpu.engine import aotcache

    if args.compile_cache_dir:
        aotcache.enable_persistent_compile_cache(
            args.compile_cache_dir)
    else:
        aotcache.maybe_enable_from_env()

    from pydcop_tpu.api import serve

    if args.recover and not args.journal_dir:
        logger.error("--recover requires --journal_dir")
        return 2
    if args.replicas > 1 and args.recover:
        logger.error("--recover is per-worker in a fleet: the router "
                     "always recovers journaled replica segments")
        return 2
    if args.join and args.replicas > 1:
        logger.error("--join is for single-replica remote workers; "
                     "a local fleet (--replicas > 1) IS the router — "
                     "point remote workers' --join at its URL")
        return 2
    if args.flight_recorder_events is not None:
        from pydcop_tpu.observability import flight

        flight.install(events=args.flight_recorder_events)
    default_params = {
        "max_cycles": args.cycles,
        "damping": args.damping,
    }
    if args.params_json:
        import json

        try:
            extra = json.loads(args.params_json)
            if not isinstance(extra, dict):
                raise ValueError("--params_json must be a JSON "
                                 "object")
        except ValueError as exc:
            logger.error("bad --params_json: %s", exc)
            return 2
        default_params.update(extra)
    serve(
        port=args.port, host=args.host,
        max_queue=args.max_queue, high_water=args.high_water,
        batch_window_s=args.batch_window, max_batch=args.max_batch,
        breaker_failures=args.breaker_failures,
        breaker_reset_s=args.breaker_reset,
        default_params=default_params,
        result_keep=args.result_keep,
        journal_dir=args.journal_dir,
        journal_sync=args.journal_sync,
        recover=args.recover,
        envelope_packing=not args.no_envelope,
        envelope_overhead_ms=args.envelope_overhead_ms,
        pipeline=not args.no_pipeline,
        speculate=not args.no_speculate,
        session_max=args.session_max,
        session_segment_cycles=args.session_segment_cycles,
        session_checkpoint_every_events=args.session_checkpoint_every,
        session_certify_after=args.session_certify_after,
        replicas=args.replicas,
        affinity=args.affinity,
        compile_cache_dir=(args.compile_cache_dir
                           or aotcache.cache_dir()),
        heartbeat_s=args.heartbeat,
        probe_timeout_s=args.probe_timeout_s,
        spill_slack=args.spill_slack,
        hosts=args.hosts,
        slo_p99_ms=args.slo_p99_ms,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        join=args.join,
        host_id=args.host_id,
        fleet_trace=args.fleet_trace,
        port_file=args.port_file,
        block=True,
    )
    return 0

"""Transport layer: communication layers + per-agent messaging queues.

Reference parity: pydcop/infrastructure/communication.py
(ComputationMessage :51, CommunicationLayer :56, InProcessCommunicationLayer
:207, HttpCommunicationLayer :313, Messaging :500, priorities :495-497).

Message priorities order queue pops: discovery (5) < management (10) <
value (15) < algo (20) — lower value pops first.
"""

import json
import logging
import os
import queue
import random
import threading
import time
from collections import namedtuple
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib import request as urlrequest

from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.resilience.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryExhaustedError,
    RetryPolicy,
)
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

MSG_DISCOVERY = 5
MSG_MGT = 10
MSG_VALUE = 15
MSG_ALGO = 20

# Wakes a next_msg() blocked on an empty queue the moment shutdown is
# called: lower than every real priority, so it pops first.
_SHUTDOWN_PRIO = -1

ComputationMessage = namedtuple(
    "ComputationMessage", ["src_comp", "dest_comp", "msg", "msg_type"]
)

logger = logging.getLogger("pydcop.communication")


class UnknownComputation(Exception):
    pass


class UnreachableAgent(Exception):
    pass


def mark_agent_dead(discovery, dest_agent: str, reason: str) -> bool:
    """Publish ``dest_agent``'s removal through discovery — the signal
    the orchestrator's reparation path repairs from.  Shared by every
    transport-level failure detector so their guards cannot drift:

    - the DIRECTORY agent is never marked: an agent cannot repair its
      own control plane, and nothing ever re-publishes the directory's
      arrival, so the mark would permanently blacklist it over one
      slow bootstrap;
    - an agent the local cache never learned is never marked: delivery
      failed for want of an address, not because the agent is dead,
      and publishing its removal could evict a live agent whose
      registration simply has not propagated here yet.

    Returns True when the removal was actually published."""
    if discovery is None or not hasattr(discovery, "unregister_agent"):
        return False
    if dest_agent == getattr(discovery, "directory_agent", None):
        logger.warning(
            "Directory agent %s unreachable (%s); NOT marking the "
            "control plane dead", dest_agent, reason,
        )
        return False
    if hasattr(discovery, "agents") and \
            dest_agent not in discovery.agents():
        logger.warning(
            "Agent %s undeliverable but never locally discovered "
            "(%s); not publishing a removal for it", dest_agent, reason,
        )
        return False
    try:
        discovery.unregister_agent(dest_agent)
        return True
    except Exception:
        logger.exception("Dead-agent mark of %s failed", dest_agent)
        return False


class CommunicationLayer:
    """Protocol: transport between agents."""

    def __init__(self):
        self.messaging: Optional["Messaging"] = None
        self.discovery = None

    def on_agent_change(self, event: str, agent_name: str):
        """Hook fired by discovery on agent add/remove (see
        Discovery.agent_change_hooks); transports with retry queues
        override it to purge traffic for departed agents."""

    @property
    def address(self):
        raise NotImplementedError

    def send_msg(self, src_agent: str, dest_agent: str,
                 msg: ComputationMessage, on_error=None):
        raise NotImplementedError

    def receive_msg(self, src_agent: str, dest_agent: str,
                    msg: ComputationMessage):
        """Deliver an incoming message to the local messaging queue."""
        self.messaging.post_local(msg)

    def shutdown(self):
        pass


class InProcessCommunicationLayer(CommunicationLayer):
    """Address = the layer object itself; send = direct method call
    (reference communication.py:207-294)."""

    @property
    def address(self):
        return self

    def send_msg(self, src_agent: str, dest_agent: str,
                 msg: ComputationMessage, on_error=None):
        address = self.discovery.agent_address(dest_agent)
        address.receive_msg(src_agent, dest_agent, msg)

    def __repr__(self):
        return f"InProcessCommunicationLayer({id(self):x})"


class Messaging:
    """Per-agent priority message queue + routing.

    Local destinations go straight to the queue; remote ones through the
    communication layer.  Messages to not-yet-known computations are
    parked and retried when discovery learns the destination (reference
    communication.py:636-726).
    """

    # Remote sends retry briefly on the agent thread before the message
    # is dropped (an agent thread must NEVER die on a peer's failure);
    # env-tunable via PYDCOP_MSG_RETRY_*.  Cheap by design: the HTTP
    # layer has its own background retry queue, so this policy only
    # really fires for in-process sends to departed agents.
    DEFAULT_SEND_POLICY = dict(
        max_attempts=3, base_delay=0.02, max_delay=0.1, jitter=0.0,
    )

    def __init__(self, agent_name: str, comm: CommunicationLayer,
                 delay: float = 0,
                 retry_policy: Optional[RetryPolicy] = None):
        self._agent_name = agent_name
        self._comm = comm
        self._retry_policy = retry_policy or RetryPolicy.from_env(
            "PYDCOP_MSG_RETRY_", **self.DEFAULT_SEND_POLICY
        )
        comm.messaging = self
        self._queue: "queue.PriorityQueue" = queue.PriorityQueue()
        self._local_computations: Dict[str, bool] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._delay = delay
        self._shutdown = False
        # Metrics (reference :542-577):
        self.count_ext_msg: Dict[str, int] = {}
        self.size_ext_msg: Dict[str, int] = {}
        self.msg_queue_count = 0
        # Parked messages waiting for discovery: comp -> list of msgs.
        self._parked: Dict[str, list] = {}
        # Registry-backed outbound totals (observability.metrics):
        # the send path bumps plain attributes (no shared locks on the
        # hot path — the disabled-cost contract); ext_msg_totals()
        # folds the deltas into the registry counters on read, same
        # pattern as Agent._publish_metrics.
        self._out_count = 0
        self._out_bytes = 0
        self._m_out_published = [0, 0]
        self._m_out = metrics_registry.counter(
            "pydcop_agent_messages_sent_total",
            "Remote messages sent by the agent").bind(agent=agent_name)
        self._m_out_bytes = metrics_registry.counter(
            "pydcop_agent_message_bytes_sent_total",
            "Total size of remote messages sent by the agent"
        ).bind(agent=agent_name)
        self._m_q_depth = metrics_registry.gauge(
            "pydcop_queue_depth",
            "Pending messages in the agent's priority queue"
        ).bind(agent=agent_name)

    @property
    def communication(self) -> CommunicationLayer:
        return self._comm

    @property
    def discovery(self):
        return self._comm.discovery

    def register_computation(self, name: str):
        with self._lock:
            self._local_computations[name] = True

    def unregister_computation(self, name: str):
        with self._lock:
            self._local_computations.pop(name, None)

    def post_msg(self, src_comp: str, dest_comp: str, msg: Message,
                 prio: int = MSG_ALGO, on_error=None):
        cmsg = ComputationMessage(src_comp, dest_comp, msg, prio)
        if dest_comp in self._local_computations:
            self.post_local(cmsg)
            return
        # Remote: resolve the hosting agent through discovery.
        try:
            dest_agent = self.discovery.computation_agent(dest_comp)
        except KeyError:
            with self._lock:
                self._parked.setdefault(dest_comp, []).append(cmsg)
            self.discovery.subscribe_computation(
                dest_comp, self._on_computation_discovered
            )
            return
        self._send_remote(dest_agent, cmsg)

    def ext_msg_totals(self):
        """(count, size) of remote sends by THIS messaging instance;
        folds the deltas into the registry counters so the canonical
        export is current at every read."""
        count, size = self._out_count, self._out_bytes
        delta = (count - self._m_out_published[0],
                 size - self._m_out_published[1])
        self._m_out_published = [count, size]
        if delta[0]:
            self._m_out.inc(delta[0])
        if delta[1]:
            self._m_out_bytes.inc(delta[1])
        return count, size

    def _send_remote(self, dest_agent: str, cmsg: ComputationMessage):
        self.count_ext_msg[cmsg.src_comp] = (
            self.count_ext_msg.get(cmsg.src_comp, 0) + 1
        )
        self.size_ext_msg[cmsg.src_comp] = (
            self.size_ext_msg.get(cmsg.src_comp, 0) + cmsg.msg.size
        )
        self._out_count += 1
        self._out_bytes += cmsg.msg.size
        if metrics_registry.active:
            # Per-type detail is opt-in: the label-key build per
            # message is only paid when metrics were requested.
            metrics_registry.counter(
                "pydcop_messages_by_type_total",
                "Remote messages by message type",
            ).inc(type=cmsg.msg.type, direction="out")
        if tracer.enabled:
            tracer.instant(
                "message_send", "comm", agent=self._agent_name,
                src=cmsg.src_comp, dest_comp=cmsg.dest_comp,
                dest_agent=dest_agent, type=cmsg.msg.type,
                size=cmsg.msg.size,
            )
        try:
            self._retry_policy.call(
                self._comm.send_msg, self._agent_name, dest_agent, cmsg,
            )
        except (RetryExhaustedError, CircuitOpenError) as e:
            # Repeated delivery failure: mark the destination dead in
            # discovery (triggering transport purges and — on the
            # orchestrator — the reparation path) and drop the message
            # instead of raising through the agent thread.
            logger.warning(
                "Dropping %s -> %s after retries, marking %s dead: %s",
                cmsg.src_comp, cmsg.dest_comp, dest_agent, e,
            )
            mark_agent_dead(self.discovery, dest_agent, str(e))

    def _on_computation_discovered(self, event: str, computation: str,
                                   agent: str):
        if event != "computation_added":
            return
        with self._lock:
            parked = self._parked.pop(computation, [])
        for cmsg in parked:
            if computation in self._local_computations:
                self.post_local(cmsg)
            else:
                self._send_remote(agent, cmsg)

    def post_local(self, cmsg: ComputationMessage):
        if self._delay:
            time.sleep(self._delay)
        with self._lock:
            self._seq += 1
            self.msg_queue_count += 1
            self._queue.put((cmsg.msg_type, self._seq, cmsg))
        if metrics_registry.active:
            self._m_q_depth.set(self._queue.qsize())

    def next_msg(self, timeout: float = 0.05
                 ) -> Optional[ComputationMessage]:
        """Pop the next message by priority.

        Clean-termination contract (with :meth:`shutdown`): no message
        is silently dropped and no caller waits past shutdown.  A
        blocked ``next_msg`` wakes immediately when ``shutdown()`` runs
        (the sentinel below — without it the old code slept out its
        full timeout, the race this contract fixes); after shutdown,
        already-queued messages keep draining in priority order and
        only an EMPTY queue answers None, without blocking.
        """
        block = not self._shutdown
        while True:
            try:
                _, _, cmsg = self._queue.get(
                    block=block, timeout=timeout if block else None
                )
            except queue.Empty:
                return None
            if cmsg is None:
                # Shutdown sentinel: stop waiting, drain what's left.
                block = False
                continue
            if metrics_registry.active:
                self._m_q_depth.set(self._queue.qsize())
            return cmsg

    def shutdown(self):
        """Stop the transport; queued messages stay poppable (drain
        semantics, see :meth:`next_msg`)."""
        with self._lock:
            already = self._shutdown
            self._shutdown = True
            if not already:
                self._seq += 1
                self._queue.put((_SHUTDOWN_PRIO, self._seq, None))
        self._comm.shutdown()


# --------------------------------------------------------------------- #
# HTTP transport (process / multi-machine modes)


class HttpCommunicationLayer(CommunicationLayer):
    """JSON-over-HTTP transport: one HTTP server thread per agent,
    messages POSTed with simple_repr bodies (reference :313-492).

    Delivery hardening: failed sends park in a retry queue swept by a
    background thread with per-message exponential backoff
    (``retry_policy``, env-tunable via ``PYDCOP_HTTP_RETRY_*``), a
    per-destination :class:`CircuitBreaker` skips the connect timeout
    to destinations that just failed repeatedly
    (``PYDCOP_HTTP_BREAKER_*``), and a message still undeliverable
    after ``RETRY_WINDOW`` seconds is dropped AND its destination
    marked dead through discovery — the signal the orchestrator's
    reparation path repairs from — instead of raising anywhere near
    the agent thread.
    """

    # Undeliverable messages are retried for this long before being
    # dropped (covers agents starting before their orchestrator —
    # reference communication.py:66-78 on_error retry semantics).
    RETRY_WINDOW = 30.0
    # Messages to the DIRECTORY agent get a longer window: they are
    # the bootstrap (agent_ready, register_agent) — dropping one
    # strands the agent outside the run forever, and under heavy load
    # an orchestrator's interpreter+jax start alone can eat the
    # standard window.
    DIRECTORY_RETRY_WINDOW = 120.0
    # Sweep cadence of the retry thread (per-message backoff decides
    # whether a due sweep actually re-attempts a given message).
    RETRY_INTERVAL = 0.5

    def __init__(self, address_port: Tuple[str, int],
                 retry_policy: Optional[RetryPolicy] = None):
        super().__init__()
        self._host, self._port = address_port
        self._server: Optional[ThreadingHTTPServer] = None
        # Backoff cap stays SMALL relative to RETRY_WINDOW: the prime
        # retry scenario is an agent booting before its orchestrator,
        # where delivery must land within a couple of seconds of the
        # peer's socket opening — a long cap would idle past a
        # just-opened endpoint and fall off the window cliff.
        self.retry_policy = retry_policy or RetryPolicy.from_env(
            "PYDCOP_HTTP_RETRY_",
            max_attempts=None, base_delay=0.25,
            max_delay=2.0, jitter=0.1,
        )
        self._breaker_threshold = int(os.environ.get(
            "PYDCOP_HTTP_BREAKER_THRESHOLD", "5"))
        self._breaker_reset = float(os.environ.get(
            "PYDCOP_HTTP_BREAKER_RESET", "1.0"))
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._rng = random.Random(0x5EED)
        self._retry_lock = threading.Lock()
        # Entries: [expire_time, src, dest, cmsg, attempt, next_due,
        # enqueued_at] (dest stays at index 2: the purge path keys on
        # it; enqueued_at feeds the stale-namesake check).
        self._retry_queue = []
        self._retry_thread: Optional[threading.Thread] = None
        # Agents known to have departed: their traffic is dropped
        # instead of lingering in the retry queue for RETRY_WINDOW
        # (and possibly re-delivering to a re-added namesake).
        self._removed_agents: set = set()
        # Last removal time per agent name — never cleared on re-add,
        # so retry entries enqueued before a removal are dropped even
        # when the name is re-registered within one retry sweep.
        self._removed_at: Dict[str, float] = {}
        self._shutdown = False
        self._start_server()

    def on_agent_change(self, event: str, agent_name: str):
        if event == "agent_removed":
            with self._retry_lock:
                self._removed_agents.add(agent_name)
                self._removed_at[agent_name] = time.monotonic()
                before = len(self._retry_queue)
                self._retry_queue = [
                    entry for entry in self._retry_queue
                    if entry[2] != agent_name
                ]
                purged = before - len(self._retry_queue)
            if purged:
                logger.info(
                    "Purged %d queued messages for departed agent %s",
                    purged, agent_name,
                )
        elif event == "agent_added":
            with self._retry_lock:
                self._removed_agents.discard(agent_name)
                # A re-added namesake is a fresh endpoint: forget the
                # old one's failure history.
                self._breakers.pop(agent_name, None)

    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    def _start_server(self):
        layer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    data = json.loads(body.decode("utf-8"))
                    msg = from_repr(data["msg"])
                    cmsg = ComputationMessage(
                        data["src_comp"], data["dest_comp"], msg,
                        data.get("msg_type", MSG_ALGO),
                    )
                except Exception as e:  # malformed message
                    self.send_response(400)
                    self.end_headers()
                    logger.warning("Malformed message: %s", e)
                    return
                layer.receive_msg(
                    self.headers.get("sender-agent", "?"),
                    self.headers.get("dest-agent", "?"), cmsg,
                )
                self.send_response(204)
                self.end_headers()

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer(
            (self._host, self._port), Handler
        )
        t = threading.Thread(
            target=self._server.serve_forever,
            name=f"http_comm_{self._port}", daemon=True,
        )
        t.start()

    def send_msg(self, src_agent: str, dest_agent: str,
                 msg: ComputationMessage, on_error=None):
        with self._retry_lock:
            removed = dest_agent in self._removed_agents
        if removed:
            logger.debug(
                "Dropping message to departed agent %s", dest_agent
            )
            return
        error = self._try_send(src_agent, dest_agent, msg)
        if error is not None:
            if on_error == "fail":
                raise UnreachableAgent(dest_agent)
            self._schedule_retry(src_agent, dest_agent, msg, error)

    def _breaker_for(self, dest_agent: str) -> CircuitBreaker:
        with self._retry_lock:
            breaker = self._breakers.get(dest_agent)
            if breaker is None:
                breaker = CircuitBreaker(
                    self._breaker_threshold, self._breaker_reset,
                    name=dest_agent,
                )
                self._breakers[dest_agent] = breaker
            return breaker

    def _try_send(self, src_agent: str, dest_agent: str,
                  msg: ComputationMessage) -> Optional[str]:
        """Attempt one delivery; returns an error string on failure.

        An unknown address is a discovery race, not a transport
        failure, so it never trips the breaker; repeated CONNECTION
        failures open the destination's breaker and later attempts
        return immediately instead of eating the 2 s connect timeout
        per queued message."""
        try:
            dest_address = self.discovery.agent_address(dest_agent)
        except Exception as e:
            return f"unknown agent: {e}"
        breaker = self._breaker_for(dest_agent)
        if not breaker.allow():
            return f"circuit open for {dest_agent}"
        host, port = dest_address
        body = json.dumps({
            "src_comp": msg.src_comp,
            "dest_comp": msg.dest_comp,
            "msg": simple_repr(msg.msg),
            "msg_type": msg.msg_type,
        }).encode("utf-8")
        req = urlrequest.Request(
            f"http://{host}:{port}/pydcop",
            data=body,
            headers={
                "Content-Type": "application/json",
                "sender-agent": src_agent,
                "dest-agent": dest_agent,
            },
        )
        try:
            if tracer.enabled:
                with tracer.span("http_send", "comm",
                                 src=src_agent, dest=dest_agent,
                                 type=msg.msg.type):
                    urlrequest.urlopen(req, timeout=2.0)
            else:
                urlrequest.urlopen(req, timeout=2.0)
            breaker.record_success()
            return None
        except Exception as e:
            breaker.record_failure()
            return f"{host}:{port} unreachable: {e}"

    def _is_stale(self, enqueued: float, dest: str) -> bool:
        """True when the entry targets a currently-removed agent, or
        was enqueued before the agent's last removal (delivery would
        reach a re-added namesake).  Call with _retry_lock held."""
        if dest in self._removed_agents:
            return True
        removed_at = self._removed_at.get(dest)
        return removed_at is not None and enqueued <= removed_at

    def _schedule_retry(self, src_agent: str, dest_agent: str,
                        msg: ComputationMessage, error: str):
        logger.debug(
            "Send to %s failed (%s); will retry for up to %.0fs",
            dest_agent, error, self.RETRY_WINDOW,
        )
        now = time.monotonic()
        window = self.RETRY_WINDOW
        disco = self.discovery
        if disco is not None and \
                dest_agent == getattr(disco, "directory_agent", None):
            window = max(window, self.DIRECTORY_RETRY_WINDOW)
        with self._retry_lock:
            if dest_agent in self._removed_agents:
                return
            self._retry_queue.append(
                (now + window, src_agent, dest_agent, msg,
                 1, now + self.retry_policy.delay_for(1, self._rng),
                 now)
            )
            if self._retry_thread is None or \
                    not self._retry_thread.is_alive():
                self._retry_thread = threading.Thread(
                    target=self._retry_loop,
                    name=f"http_retry_{self._port}", daemon=True,
                )
                self._retry_thread.start()

    def _mark_agent_dead(self, dest: str, error: str):
        """The retry window is exhausted: the destination is dead.
        Publishing the removal (module-level :func:`mark_agent_dead`,
        with its directory and never-discovered exemptions) fires the
        agent-change hooks — purging its queued traffic here — and
        lets the orchestrator's reparation path migrate its
        computations."""
        if mark_agent_dead(self.discovery, dest, error):
            logger.warning(
                "Marked agent %s dead after failed delivery: %s",
                dest, error,
            )

    def _retry_loop(self):
        while not self._shutdown:
            time.sleep(self.RETRY_INTERVAL)
            with self._retry_lock:
                pending, self._retry_queue = self._retry_queue, []
                if not pending:
                    # Drained: clear the thread ref under the lock so a
                    # concurrent _schedule_retry starts a fresh thread
                    # instead of relying on this dying one.
                    self._retry_thread = None
                    return
            still_failing = []
            dead: Dict[str, str] = {}
            for (expire, src, dest, cmsg, attempt, next_due,
                 enqueued) in pending:
                with self._retry_lock:
                    if self._is_stale(enqueued, dest):
                        # The agent departed after this entry was
                        # enqueued (and possibly re-registered since);
                        # a purge cannot see swapped-out entries, so
                        # drop them here.
                        continue
                now = time.monotonic()
                if now < next_due and now < expire:
                    # Backoff not elapsed: keep without re-attempting.
                    still_failing.append(
                        (expire, src, dest, cmsg, attempt, next_due,
                         enqueued))
                    continue
                error = self._try_send(src, dest, cmsg)
                if error is None:
                    continue
                if time.monotonic() >= expire:
                    logger.warning(
                        "Dropping message to %s after %.0fs of "
                        "retries: %s", dest,
                        time.monotonic() - enqueued, error,
                    )
                    dead[dest] = error
                else:
                    attempt += 1
                    still_failing.append(
                        (expire, src, dest, cmsg, attempt,
                         time.monotonic() + self.retry_policy.delay_for(
                             attempt, self._rng),
                         enqueued)
                    )
            if still_failing:
                with self._retry_lock:
                    self._retry_queue.extend(
                        entry for entry in still_failing
                        if not self._is_stale(entry[6], entry[2])
                    )
            for dest, error in dead.items():
                self._mark_agent_dead(dest, error)

    def shutdown(self):
        self._shutdown = True
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def __repr__(self):
        return f"HttpCommunicationLayer(({self._host!r}, {self._port}))"

"""``pydcop`` command-line interface.

Reference parity: pydcop/dcop_cli.py (:62-130) — subcommands solve, run,
distribute, graph, agent, orchestrator, generate, replica_dist, batch,
consolidate; global ``--timeout``, ``--output``, verbosity flags.
"""

import argparse
import logging
import sys


def _configure_logs(level: int):
    if level >= 3:
        log_level = logging.DEBUG
    elif level == 2:
        log_level = logging.INFO
    elif level == 1:
        log_level = logging.WARNING
    else:
        log_level = logging.ERROR
    logging.basicConfig(
        level=log_level,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
        stream=sys.stderr,
    )


def make_parser() -> argparse.ArgumentParser:
    from pydcop_tpu.commands import (
        agent,
        batch,
        consolidate,
        debug,
        distribute,
        fleet,
        generate,
        graph,
        orchestrator,
        profile,
        replica_dist,
        run,
        serve,
        solve,
        trace,
    )

    parser = argparse.ArgumentParser(
        prog="pydcop",
        description="TPU-native DCOP solver with pyDCOP capabilities",
    )
    parser.add_argument(
        "-t", "--timeout", type=float, default=None,
        help="global timeout in seconds",
    )
    parser.add_argument(
        "--output", default=None, help="output file for results"
    )
    parser.add_argument(
        "-v", "--verbosity", type=int, default=0,
        help="verbosity: 0 error, 1 warning, 2 info, 3 debug",
    )
    parser.add_argument(
        "--version", action="store_true", help="print version and exit"
    )
    subparsers = parser.add_subparsers(title="commands", dest="command")
    for cmd in (solve, run, distribute, graph, agent, orchestrator,
                generate, replica_dist, batch, consolidate, trace,
                serve, debug, profile, fleet):
        cmd.set_parser(subparsers)
    return parser


def cli(args=None):
    """Console-script entry point (NOT for in-process use).

    Agent-mode runs leave daemon threads behind (agents, HTTP servers,
    websocket servers, JAX clients); interpreter teardown can race them
    into an abort after the result is already printed.  Flush and exit
    hard — all user-visible work is done.  Programmatic callers should
    use :func:`main`, which returns normally.
    """
    rc = main(args)
    sys.stdout.flush()
    sys.stderr.flush()
    import os
    import threading

    if any(
        t.daemon and t.is_alive() and t is not threading.main_thread()
        for t in threading.enumerate()
    ):
        os._exit(rc)
    sys.exit(rc)


# CLI commands that execute on the device backend: a wedged
# accelerator tunnel hangs jax backend init FOREVER (C++-level, not
# interruptible), which would turn `pydcop solve` into a silent hang.
_DEVICE_COMMANDS = ("solve", "run", "batch", "serve")


def _guard_backend(command: str) -> None:
    """Probe the accelerator backend before a device-running command
    and fall back to a scrubbed CPU env when it is unresponsive (same
    recipe the benchmarks use — utils/cleanenv).  Skipped entirely
    when no accelerator plugin is configured (plain CPU installs pay
    nothing) or inside an already-scrubbed fallback child."""
    import os

    if command not in _DEVICE_COMMANDS:
        return
    if "PALLAS_AXON_POOL_IPS" not in os.environ:
        return
    from pydcop_tpu.utils.cleanenv import ensure_live_backend

    ensure_live_backend(tag=f"cli_{command}", retries=1,
                        probe_timeout=float(os.environ.get(
                            "PYDCOP_CLI_PROBE_TIMEOUT", "60")))


def main(args=None) -> int:
    parser = make_parser()
    parsed = parser.parse_args(args)
    _configure_logs(parsed.verbosity)
    if parsed.version:
        import pydcop_tpu

        print(f"pydcop-tpu {pydcop_tpu.__version__}")
        return 0
    if not getattr(parsed, "func", None):
        parser.print_help()
        return 2
    _guard_backend(parsed.command)
    try:
        return parsed.func(parsed) or 0
    except ModuleNotFoundError as e:
        # Plugin-style lookups (algorithm / distribution / graph model
        # names map to module imports): name the valid options.  NOTE:
        # a bare `raise` here would escape the whole try statement
        # (later handlers never apply once one is entered), so the
        # generic path is handled inline.
        name = str(e).rsplit(".", 1)[-1].rstrip("'")
        if "pydcop_tpu.algorithms." in str(e):
            from pydcop_tpu.algorithms import list_available_algorithms

            print(
                f"Error: unknown algorithm {name!r}; available: "
                f"{', '.join(list_available_algorithms())}",
                file=sys.stderr,
            )
            return 2
        if "pydcop_tpu.distribution." in str(e):
            print(
                f"Error: unknown distribution method {name!r}",
                file=sys.stderr,
            )
            return 2
        if "pydcop_tpu.computations_graph." in str(e):
            import pkgutil

            import pydcop_tpu.computations_graph as cg_pkg

            models = sorted(
                n for _, n, ispkg in pkgutil.iter_modules(cg_pkg.__path__)
                if not ispkg and not n.startswith("_") and n != "objects"
            )
            print(
                f"Error: unknown graph model {name!r}; available: "
                f"{', '.join(models)}",
                file=sys.stderr,
            )
            return 2
        if parsed.verbosity >= 3:
            raise
        if "pydcop_tpu" not in str(e):
            # A missing THIRD-PARTY module is a broken install, not a
            # user error (ADVICE r2): distinct exit code + -vvv hint.
            print(
                f"Error: missing dependency: {e}. This looks like a "
                "broken installation; rerun with -vvv for the full "
                "traceback.",
                file=sys.stderr,
            )
            return 3
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except FileNotFoundError as e:
        print(f"Error: file not found: {e.filename}", file=sys.stderr)
        return 2
    except Exception as e:  # clean one-line errors for users, not tracebacks
        if parsed.verbosity >= 3:
            raise
        print(f"Error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    cli()

"""Fleet router: N solve-service worker replicas behind one HTTP port.

One scheduler thread owning one device cannot serve the ROADMAP's
"millions of users" north star (open item 2).  This module scales the
serve plane OUT: ``pydcop serve --replicas N`` (api.serve(replicas=N))
spawns N worker processes — each a full ``pydcop serve`` instance with
its own SolveService scheduler thread, its own journal segment
(``<journal_dir>/replica-<k>/``), its own /metrics — behind a
stdlib-HTTP router that speaks the existing wire protocol unchanged:
clients POST /solve and poll /result/<id> exactly as against a single
service and never know the fleet exists.

**Structure-affinity routing.**  The router computes the structure
bin key at admission (serving/binning.affinity_key — the PR-3/6
structure signature without the cost-table fill) and routes by
RENDEZVOUS HASHING on it: every replica scores
``sha1(key || replica_id)`` and the highest healthy scorer wins, so
same-structure traffic deterministically lands where the compiled
program (and the batch-mates to coalesce with) is already warm —
cache-affinity beats round-robin, and the bench proves it
(bench.py bench_serving_fleet, ``affinity_hit_fraction`` in /stats).
Rendezvous keeps the map stable under membership change: a replica
death remaps ONLY the keys it owned.  Two escape hatches keep
affinity from becoming a liability: **least-loaded spillover** (a
primary more than ``spill_slack`` requests deeper in flight than the
idlest healthy replica loses the request to it — hot-spot structures
overflow instead of queueing) and **breaker-aware shedding** (a
replica whose admission breaker reports open is dropped from the
candidate set; if every replica sheds, the router answers 503 like a
single service would).

**Fleet lifecycle.**  A heartbeat prober GETs every replica's
/healthz on a short cadence and scores silence with the PR-4
phi-accrual estimator (resilience/health.PhiAccrualEstimator):
suspicion is advisory, ``dead_misses`` expected intervals of silence
(or the worker process exiting) is the death verdict.  A dead
replica's journal segment is handed to its replacement: the router
respawns worker k on ``<journal_dir>/replica-<k>/`` with
``--recover``, so every request the dead worker acknowledged replays
through the PR-8 machinery — SIGKILL mid-burst loses zero
acknowledged requests (tools/chaos_soak.py ``replica_kill``).
Requests are PINNED: the router mints the request id, remembers which
replica owns it, and routes /result polls there (a restarted replica
answers for its predecessor's journal).  Sessions pin the same way.
Fleet SIGTERM drains every worker (each drains its own queue, journals
the rest replayable) and exits 0.

The router process itself never jits: compile work lives in the
workers, warmed across restarts by the persistent AOT compile cache
(engine/aotcache.py) whose directory the router exports to every
worker it spawns.
"""

import hashlib
import http.client
import itertools
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.server import (
    TelemetryServer,
    _Handler,
    get_health_provider,
    set_health_provider,
)

logger = logging.getLogger("pydcop.serving.router")

# Wire limits mirror the single-service front end (serving/http.py).
MAX_BODY_BYTES = 8 << 20
# Forward timeout headroom over the client's own wait window.
FORWARD_TIMEOUT_S = 330.0
# Bounded pin tables: oldest request pins evicted first (the same
# retention philosophy as SolveService.result_keep).
PIN_KEEP = 65536

UP = "up"
STARTING = "starting"
RESTARTING = "restarting"
DOWN = "down"


class FleetUnavailable(Exception):
    """No healthy, non-shedding replica can take the request (503)."""


class Replica:
    """One worker process slot: the process handle, its URL, health
    bookkeeping and the warm-structure set affinity accounting reads.
    A slot survives its process — a restarted worker reuses the slot
    (same index, same journal segment), which is what keeps request
    pins valid across a replica death."""

    def __init__(self, index: int, journal_dir: Optional[str],
                 log_path: str):
        self.index = index
        self.journal_dir = journal_dir
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.status = STARTING
        self.estimator = None           # PhiAccrualEstimator, set on up
        self.anchor = 0.0
        self.breaker_open = False
        self.queue_depth = 0
        self.in_flight = 0
        self.forwarded = 0
        self.errors = 0
        self.restarts = 0
        self.warm: set = set()

    @property
    def url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://127.0.0.1:{self.port}"

    def summary(self) -> Dict[str, Any]:
        return {
            "index": self.index,
            "url": self.url,
            "status": self.status,
            "pid": self.proc.pid if self.proc else None,
            "breaker_open": self.breaker_open,
            "queue_depth": self.queue_depth,
            "in_flight": self.in_flight,
            "forwarded": self.forwarded,
            "errors": self.errors,
            "restarts": self.restarts,
            "warm_structures": len(self.warm),
            "journal_dir": self.journal_dir,
        }


def _rendezvous_score(digest: str, index: int) -> int:
    """Highest-random-weight score of one (structure, replica) pair —
    deterministic across processes and restarts (hash() is seeded per
    process and would reshuffle the whole map on every router
    restart, defeating the disk-warmed affinity)."""
    h = hashlib.sha1(f"{digest}|{index}".encode()).digest()
    return int.from_bytes(h[:8], "big")


class FleetRouter:
    """Spawn, monitor and route over N serve-worker replicas.

    ``worker_args`` is the raw ``pydcop serve`` CLI argument tail
    every worker is spawned with (batching/admission/session knobs —
    built by api.serve from its kwargs, so the single-service and
    fleet paths cannot drift).  ``journal_dir`` enables per-replica
    durable journals (``replica-<k>/`` segments) and crash handoff;
    ``compile_cache_dir`` is exported to every worker as the
    persistent AOT compile cache.  ``affinity`` is ``"structure"``
    (rendezvous on the bin key, the default) or ``"round_robin"``
    (the A/B baseline the bench measures against)."""

    def __init__(self, replicas: int = 2,
                 worker_args: Optional[List[str]] = None,
                 journal_dir: Optional[str] = None,
                 compile_cache_dir: Optional[str] = None,
                 affinity: str = "structure",
                 heartbeat_s: float = 0.25,
                 dead_misses: float = 8.0,
                 spill_slack: int = 4,
                 restart_dead: bool = True,
                 worker_ready_timeout_s: float = 120.0,
                 default_params: Optional[Dict[str, Any]] = None):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if affinity not in ("structure", "round_robin"):
            raise ValueError(
                f"affinity must be 'structure' or 'round_robin', "
                f"got {affinity!r}")
        self.n_replicas = int(replicas)
        self.worker_args = list(worker_args or [])
        self.journal_dir = journal_dir
        self.compile_cache_dir = compile_cache_dir
        self.affinity = affinity
        self.heartbeat_s = float(heartbeat_s)
        self.dead_misses = float(dead_misses)
        self.spill_slack = int(spill_slack)
        self.restart_dead = bool(restart_dead)
        self.worker_ready_timeout_s = float(worker_ready_timeout_s)
        # The fleet's service-wide solver defaults: the affinity key
        # must normalize request params exactly the way the WORKERS
        # will (their SolveService merges over these same defaults).
        # Hashing against the module defaults instead would split
        # same-bin traffic whenever a client spells a service default
        # explicitly — e.g. params={} vs params={"max_cycles": 60}
        # on a --cycles 60 fleet.
        self.default_params = dict(default_params or {})
        self.replicas: List[Replica] = []
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._pins: "OrderedDict[str, int]" = OrderedDict()
        self._session_pins: "OrderedDict[str, int]" = OrderedDict()
        self._monitor: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._started = False
        self._run_dir: Optional[str] = None
        # Routing ledger (all mirrored on /stats).
        self.routed = 0
        self.affinity_hits = 0
        self.spillovers = 0
        self.shed = 0
        self.reroutes = 0
        self.deaths = 0
        reg = metrics_registry
        self._routed_total = reg.counter(
            "pydcop_router_requests_total",
            "Requests routed to replicas, by outcome")
        self._affinity_total = reg.counter(
            "pydcop_router_affinity_hits_total",
            "Routed requests that landed on a structure-warm replica")
        self._up_gauge = reg.gauge(
            "pydcop_router_replicas_up",
            "Live (heartbeat-passing) worker replicas")
        self._restarts_total = reg.counter(
            "pydcop_router_replica_restarts_total",
            "Worker replicas restarted after a death verdict")

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> "FleetRouter":
        import tempfile

        if self._started:
            return self
        self._was_active = metrics_registry.active
        metrics_registry.active = True
        self._run_dir = tempfile.mkdtemp(prefix="pydcop_fleet_")
        try:
            for k in range(self.n_replicas):
                journal = (os.path.join(self.journal_dir,
                                        f"replica-{k}")
                           if self.journal_dir else None)
                replica = Replica(
                    k, journal,
                    os.path.join(self._run_dir, f"replica-{k}.log"))
                self.replicas.append(replica)
                self._spawn(replica, recover=False)
            deadline = time.monotonic() + self.worker_ready_timeout_s
            for replica in self.replicas:
                self._wait_ready(replica, deadline)
        except BaseException:
            # Partial startup must not orphan detached workers: one
            # replica failing to come up kills every one already
            # spawned (stop() is a no-op before _started flips).
            for replica in self.replicas:
                if replica.proc is not None \
                        and replica.proc.poll() is None:
                    try:
                        replica.proc.kill()
                        replica.proc.wait(timeout=10.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
            self.replicas = []
            metrics_registry.active = self._was_active
            raise
        self._stopping.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="pydcop-fleet-monitor",
            daemon=True)
        self._monitor.start()
        self._started = True
        self._up_gauge.set(self.up_count())
        return self

    def stop(self, drain: bool = True,
             timeout: float = 120.0) -> Dict[str, Any]:
        """Drain and stop the whole fleet: SIGTERM every worker (each
        drains its queue and journals leftovers replayable — the
        single-service contract), wait for clean exits, reap
        stragglers.  Returns per-worker exit codes."""
        if not self._started:
            return {"workers": []}
        self._stopping.set()
        if self._monitor is not None:
            self._monitor.join(timeout=max(self.heartbeat_s * 4, 2.0))
            self._monitor = None
        sig = signal.SIGTERM if drain else signal.SIGKILL
        for replica in self.replicas:
            if replica.proc is not None and replica.proc.poll() is None:
                try:
                    replica.proc.send_signal(sig)
                except OSError:
                    pass
        exits = []
        deadline = time.monotonic() + timeout
        for replica in self.replicas:
            code = None
            if replica.proc is not None:
                try:
                    code = replica.proc.wait(
                        timeout=max(deadline - time.monotonic(), 1.0))
                except subprocess.TimeoutExpired:
                    replica.proc.kill()
                    try:
                        code = replica.proc.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        code = None
            replica.status = DOWN
            exits.append({"index": replica.index, "exit": code,
                          "restarts": replica.restarts})
        # Final sweep: a restart thread that raced the signal loop
        # above may have spawned a replacement after its slot was
        # signaled — nothing it spawns may outlive the fleet.
        for replica in self.replicas:
            if replica.proc is not None \
                    and replica.proc.poll() is None:
                try:
                    replica.proc.kill()
                    replica.proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        self._started = False
        metrics_registry.active = self._was_active
        return {"workers": exits}

    def _spawn(self, replica: Replica, recover: bool) -> None:
        """Start (or restart) worker k.  ``recover`` replays the
        slot's journal segment — the handoff: the restarted process
        owns its predecessor's acknowledged requests."""
        port_file = os.path.join(self._run_dir,
                                 f"replica-{replica.index}.port")
        try:
            os.unlink(port_file)
        except OSError:
            pass
        cmd = [sys.executable, "-m", "pydcop_tpu.dcop_cli", "serve",
               "--port", "0", "--host", "127.0.0.1",
               "--port_file", port_file]
        if replica.journal_dir:
            cmd += ["--journal_dir", replica.journal_dir]
            if recover or os.path.exists(os.path.join(
                    replica.journal_dir, "requests.jnl")):
                cmd += ["--recover"]
        cmd += self.worker_args
        env = dict(os.environ)
        if self.compile_cache_dir:
            # The worker enables the persistent AOT cache at spawn,
            # before its first jit (engine/aotcache latch).
            env["PYDCOP_COMPILE_CACHE_DIR"] = self.compile_cache_dir
        log = open(replica.log_path, "ab")
        try:
            replica.proc = subprocess.Popen(
                cmd, env=env, stdout=log, stderr=log,
                start_new_session=True)
        finally:
            log.close()
        replica.port = None
        replica.status = STARTING if replica.restarts == 0 \
            else RESTARTING
        replica.breaker_open = False
        # A fresh process is NOT warm, whatever its predecessor
        # compiled: affinity hit accounting must restart from zero
        # (the disk compile cache softens the restarted replica's
        # cold calls, but a disk retrieval is still not a warm jit
        # cache — counting it as a hit would inflate
        # affinity_hit_fraction after every death).
        replica.warm = set()
        logger.info("replica %d spawned (pid %d%s)", replica.index,
                    replica.proc.pid,
                    ", recover" if recover else "")

    def _wait_ready(self, replica: Replica, deadline: float) -> None:
        port_file = os.path.join(self._run_dir,
                                 f"replica-{replica.index}.port")
        while time.monotonic() < deadline:
            if replica.proc.poll() is not None:
                raise RuntimeError(
                    f"fleet worker {replica.index} died on startup "
                    f"(exit {replica.proc.returncode}); log: "
                    f"{replica.log_path}")
            try:
                with open(port_file, encoding="utf-8") as f:
                    replica.port = int(f.read().strip())
            except (OSError, ValueError):
                time.sleep(0.05)
                continue
            try:
                status, _ctype, _body = self._forward(
                    replica, "GET", "/healthz", None, timeout=2.0)
            except OSError:
                time.sleep(0.05)
                continue
            if status in (200, 503):
                from pydcop_tpu.resilience.health import (
                    PhiAccrualEstimator,
                )

                now = time.monotonic()
                replica.estimator = PhiAccrualEstimator(
                    expected=self.heartbeat_s)
                replica.anchor = now
                replica.estimator.beat(now)
                replica.status = UP
                logger.info("replica %d ready on %s", replica.index,
                            replica.url)
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"fleet worker {replica.index} never became ready; "
            f"log: {replica.log_path}")

    # -- health & restarts --------------------------------------------- #

    def up_count(self) -> int:
        return sum(1 for r in self.replicas if r.status == UP)

    def _monitor_loop(self) -> None:
        while not self._stopping.wait(self.heartbeat_s):
            for replica in self.replicas:
                if self._stopping.is_set():
                    return
                try:
                    self._probe(replica)
                except Exception:  # noqa: BLE001 — the prober must
                    # outlive any single replica's weirdness.
                    logger.exception("heartbeat probe crashed for "
                                     "replica %d", replica.index)
            self._up_gauge.set(self.up_count())

    def _probe(self, replica: Replica) -> None:
        if replica.status not in (UP, DOWN):
            return  # mid-(re)start — the restart path owns it
        proc_dead = (replica.proc is not None
                     and replica.proc.poll() is not None)
        beat_ok = False
        if not proc_dead and replica.port is not None:
            try:
                status, _ctype, body = self._forward(
                    replica, "GET", "/healthz", None,
                    timeout=max(self.heartbeat_s * 2, 1.0))
                beat_ok = status in (200, 503)
                if beat_ok:
                    doc = json.loads(body)
                    serving = doc.get("serving") or {}
                    replica.breaker_open = (
                        serving.get("breaker_state") == "open")
                    replica.queue_depth = int(
                        serving.get("queue_depth") or 0)
            except (OSError, ValueError):
                beat_ok = False
        now = time.monotonic()
        if beat_ok:
            if replica.status == DOWN:
                # A replica marked down on a forward error but whose
                # process lived: it answered again — back in service.
                replica.status = UP
            replica.estimator.beat(now)
            return
        missed = (replica.estimator.missed(now, replica.anchor)
                  if replica.estimator else float("inf"))
        if proc_dead or missed >= self.dead_misses:
            self._declare_dead(replica, proc_dead=proc_dead,
                               missed=missed)

    def _declare_dead(self, replica: Replica, proc_dead: bool,
                      missed: float) -> None:
        if replica.status == RESTARTING or self._stopping.is_set():
            # A fleet mid-shutdown SIGTERMs its own workers; the
            # monitor must not mistake those exits for deaths and
            # restart what stop() is draining.
            return
        self.deaths += 1
        logger.warning(
            "replica %d declared dead (%s, %.1f expected heartbeats "
            "silent)", replica.index,
            "process exited" if proc_dead else "heartbeat silence",
            missed if missed != float("inf") else -1.0)
        replica.status = RESTARTING
        if replica.proc is not None and replica.proc.poll() is None:
            try:
                replica.proc.kill()
                replica.proc.wait(timeout=10.0)
            except (OSError, subprocess.TimeoutExpired):
                pass
        if not self.restart_dead:
            replica.status = DOWN
            return
        replica.restarts += 1
        self._restarts_total.inc()
        # Restart OFF the monitor thread: a replacement worker takes
        # seconds to import and become ready, and the prober must keep
        # watching the OTHER replicas meanwhile (a second simultaneous
        # death must still be detected within the advertised bound).
        # The status is already RESTARTING, so the monitor skips this
        # slot until the restart thread resolves it to UP or DOWN.
        threading.Thread(
            target=self._restart, args=(replica,),
            name=f"pydcop-fleet-restart-{replica.index}",
            daemon=True).start()

    def _restart(self, replica: Replica) -> None:
        if self._stopping.is_set():
            replica.status = DOWN
            return
        try:
            # The journal handoff: --recover replays the dead
            # worker's acknowledged-but-unfinished requests and open
            # sessions through the fresh process.
            self._spawn(replica, recover=True)
            self._wait_ready(
                replica,
                time.monotonic() + self.worker_ready_timeout_s)
        except Exception:  # noqa: BLE001
            logger.exception("replica %d restart failed",
                             replica.index)
            replica.status = DOWN

    # -- routing -------------------------------------------------------- #

    def candidates(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.status == UP and not r.breaker_open]

    def pick(self, digest: Optional[str]) -> Tuple[Replica, bool]:
        """Choose the replica for one admission.  Returns
        ``(replica, affinity_hit)``; raises :class:`FleetUnavailable`
        when every replica is down or shedding."""
        with self._lock:
            live = self.candidates()
            if not live:
                self.shed += 1
                self._routed_total.inc(outcome="shed")
                raise FleetUnavailable(
                    "no healthy replica available (all down or "
                    "breaker-open)")
            if self.affinity == "round_robin" or digest is None:
                chosen = live[next(self._rr) % len(live)]
                spilled = False
            else:
                ranked = sorted(
                    live, key=lambda r: _rendezvous_score(
                        digest, r.index),
                    reverse=True)
                chosen = ranked[0]
                idlest = min(live, key=lambda r: r.in_flight)
                spilled = (chosen.in_flight
                           >= idlest.in_flight + self.spill_slack)
                if spilled:
                    # Hot-spot overflow: a structure-warm replica
                    # deep in flight loses to the idlest one — the
                    # cold compile there costs less than queueing
                    # behind the backlog (and warms a second home for
                    # the structure while it's hot).
                    chosen = idlest
                    self.spillovers += 1
            hit = digest is not None and digest in chosen.warm
            if digest is not None:
                chosen.warm.add(digest)
            chosen.in_flight += 1
            chosen.forwarded += 1
            self.routed += 1
            if hit:
                self.affinity_hits += 1
        self._routed_total.inc(outcome="spillover" if spilled
                               else "affinity" if hit else "routed")
        if hit:
            self._affinity_total.inc()
        return chosen, hit

    def release(self, replica: Replica) -> None:
        with self._lock:
            replica.in_flight = max(replica.in_flight - 1, 0)

    def pin(self, request_id: str, replica: Replica,
            table: Optional["OrderedDict[str, int]"] = None) -> None:
        table = self._pins if table is None else table
        with self._lock:
            table[request_id] = replica.index
            while len(table) > PIN_KEEP:
                table.popitem(last=False)

    def pinned(self, request_id: str,
               table: Optional["OrderedDict[str, int]"] = None
               ) -> Optional[Replica]:
        table = self._pins if table is None else table
        with self._lock:
            index = table.get(request_id)
        return self.replicas[index] if index is not None else None

    def mark_forward_error(self, replica: Replica) -> None:
        """A live forward failed at the socket: stop routing there
        NOW; the heartbeat prober (or the process reaper) confirms
        death and owns the restart."""
        with self._lock:
            replica.errors += 1
            if replica.status == UP:
                replica.status = DOWN

    # -- plumbing ------------------------------------------------------- #

    def _forward(self, replica: Replica, method: str, path: str,
                 body: Optional[bytes],
                 timeout: float = FORWARD_TIMEOUT_S
                 ) -> Tuple[int, str, bytes]:
        conn = http.client.HTTPConnection("127.0.0.1", replica.port,
                                          timeout=timeout)
        try:
            headers = {}
            if body is not None:
                headers["Content-Type"] = "application/json"
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            return (resp.status,
                    resp.getheader("Content-Type",
                                   "application/json"),
                    payload)
        finally:
            conn.close()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            routed = self.routed
            hits = self.affinity_hits
            doc = {
                "replicas": self.n_replicas,
                "up": self.up_count(),
                "affinity": self.affinity,
                "routed": routed,
                "affinity_hits": hits,
                "affinity_hit_fraction": (round(hits / routed, 4)
                                          if routed else None),
                "spillovers": self.spillovers,
                "shed": self.shed,
                "reroutes": self.reroutes,
                "deaths": self.deaths,
                "spill_slack": self.spill_slack,
                "heartbeat_s": self.heartbeat_s,
                "pinned_requests": len(self._pins),
                "pinned_sessions": len(self._session_pins),
                "workers": [r.summary() for r in self.replicas],
            }
        from pydcop_tpu.engine import aotcache

        doc["compile_cache"] = (
            {"dir": self.compile_cache_dir}
            if self.compile_cache_dir else {"dir": None})
        if aotcache.enabled():
            doc["compile_cache"] = aotcache.stats()
        return doc

    def health_summary(self) -> Dict[str, Any]:
        """The fleet /healthz: failing (503) only when NOTHING can
        serve; degraded while any replica is down/restarting."""
        up = self.up_count()
        status = ("failing" if up == 0
                  else "degraded" if up < self.n_replicas else "ok")
        return {"status": status, "fleet": {
            "replicas": self.n_replicas, "up": up,
            "workers": [r.summary() for r in self.replicas],
        }}


class _RouterHandler(_Handler):
    """The fleet's client-facing wire protocol — same routes as the
    single-service front end (serving/http.py), implemented by
    admission-time routing + forwarding."""

    def _json(self, code: int, payload: Dict[str, Any],
              close: bool = False):
        self._reply(code, json.dumps(payload, default=str).encode(),
                    "application/json", close=close)

    def _read_body(self) -> Optional[bytes]:
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > MAX_BODY_BYTES:
            self._json(400, {"error": "body required (JSON, "
                                      f"<= {MAX_BODY_BYTES} bytes)"},
                       close=True)
            return None
        return self.rfile.read(length)

    @property
    def router(self) -> FleetRouter:
        return self.telemetry.router

    def _proxy(self, replica: Replica, method: str, path: str,
               body: Optional[bytes],
               timeout: float = FORWARD_TIMEOUT_S) -> None:
        try:
            status, ctype, payload = self.router._forward(
                replica, method, path, body, timeout=timeout)
        except OSError as exc:
            self.router.mark_forward_error(replica)
            self._json(503, {
                "error": f"replica {replica.index} unreachable "
                         f"({exc}); recovering — retry",
                "status": "rejected", "retry": True})
            return
        self._reply(status, payload, ctype)

    # -- request plane -------------------------------------------------- #

    def do_POST(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if path == "/solve":
            self._route_solve()
        elif path == "/session":
            self._route_session_open()
        else:
            self._json(404, {"error": "unknown path"}, close=True)

    def _admission_key(self, raw: bytes
                       ) -> Tuple[Optional[dict], Optional[str]]:
        """Parse the body far enough to route: returns (body json,
        affinity digest).  Malformed bodies get their 4xx HERE — the
        router is the client's first contact and must speak the same
        validation language as a worker."""
        try:
            body = json.loads(raw)
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as exc:
            self._json(400, {"error": f"bad request body: {exc}"})
            return None, None
        yaml_src = body.get("dcop")
        if not isinstance(yaml_src, str) or not yaml_src.strip():
            self._json(400, {"error": "bad request body: body needs "
                                      "a 'dcop' key holding the "
                                      "problem as a dcop yaml string"})
            return None, None
        digest = None
        try:
            from pydcop_tpu.dcop.yamldcop import load_dcop
            from pydcop_tpu.serving import binning

            merged = dict(self.router.default_params)
            merged.update(body.get("params") or {})
            digest = binning.affinity_key(load_dcop(yaml_src),
                                          merged)
        except Exception as exc:  # noqa: BLE001 — malformed problem
            self._json(400, {"error": f"bad problem: {exc}"})
            return None, None
        return body, digest

    def _route_solve(self):
        raw = self._read_body()
        if raw is None:
            return
        body, digest = self._admission_key(raw)
        if body is None:
            return
        # The router ALWAYS mints the id (a client-supplied one is
        # ignored): worker-local counters collide across replicas,
        # the pin table needs a fleet-unique handle before the worker
        # ever answers, and an externally chosen id could clobber
        # another request's pin — duplicate-id rejection is
        # per-worker, so two replicas would happily accept the same
        # spoofed id.
        rid = f"f{uuid.uuid4().hex[:16]}"
        body["request_id"] = rid
        payload = json.dumps(body).encode()
        tried: set = set()
        while True:
            try:
                replica, _hit = self.router.pick(digest)
            except FleetUnavailable as exc:
                self._json(503, {"error": str(exc),
                                 "status": "rejected", "retry": True})
                return
            if replica.index in tried:
                # pick() charged this replica's in_flight; this exit
                # path never forwards, so it must release here or the
                # slot leaks and the spillover heuristic sees a
                # permanently-busier replica.
                self.router.release(replica)
                self._json(503, {
                    "error": "every healthy replica failed the "
                             "forward; retry",
                    "status": "rejected", "retry": True})
                return
            tried.add(replica.index)
            self.router.pin(rid, replica)
            try:
                status, ctype, out = self.router._forward(
                    replica, "POST", "/solve", payload)
            except OSError:
                # Nothing was acked by the worker: re-routing the
                # identical body is safe (the id travels with it).
                self.router.mark_forward_error(replica)
                with self.router._lock:
                    self.router.reroutes += 1
                continue
            finally:
                self.router.release(replica)
            self._reply(status, out, ctype)
            return

    # -- result / stats / sessions -------------------------------------- #

    def do_GET(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if path.startswith("/result/"):
            rid = path[len("/result/"):]
            replica = self.router.pinned(rid)
            if replica is None:
                self._json(404, {"error": f"unknown request {rid!r}"})
                return
            if replica.status != UP:
                self._json(503, {
                    "error": f"replica {replica.index} recovering; "
                             "retry", "retry": True})
                return
            self._proxy(replica, "GET", path, None, timeout=30.0)
        elif path.startswith("/session/"):
            sid = path[len("/session/"):].split("/", 1)[0]
            replica = self.router.pinned(
                sid, self.router._session_pins)
            if replica is None:
                self._json(404, {"error": f"unknown session {sid!r}"})
                return
            if path.endswith("/events"):
                self._proxy_sse(replica, path)
            else:
                self._proxy(replica, "GET", path, None, timeout=30.0)
        elif path == "/stats":
            self._fleet_stats()
        else:
            super().do_GET()

    def _fleet_stats(self):
        """Router stats + a live per-worker /stats fetch: ONE surface
        that answers both "how is traffic spread" and "what is each
        replica doing"."""
        doc = self.router.stats()
        for worker in doc["workers"]:
            replica = self.router.replicas[worker["index"]]
            if replica.status != UP:
                continue
            try:
                status, _ctype, body = self.router._forward(
                    replica, "GET", "/stats", None, timeout=10.0)
                if status == 200:
                    worker["stats"] = json.loads(body)
            except (OSError, ValueError):
                pass
        self._json(200, doc)

    def _route_session_open(self):
        raw = self._read_body()
        if raw is None:
            return
        body, digest = self._admission_key(raw)
        if body is None:
            return
        try:
            replica, _hit = self.router.pick(digest)
        except FleetUnavailable as exc:
            self._json(503, {"error": str(exc), "status": "rejected",
                             "retry": True})
            return
        try:
            status, ctype, out = self.router._forward(
                replica, "POST", "/session", json.dumps(body).encode())
        except OSError as exc:
            self.router.mark_forward_error(replica)
            self._json(503, {"error": f"replica unreachable ({exc}); "
                                      "retry", "retry": True})
            return
        finally:
            self.router.release(replica)
        if status == 201:
            try:
                sid = json.loads(out).get("session_id")
                if sid:
                    # Sessions are stateful: every later PATCH/GET/
                    # DELETE must land on the replica holding the
                    # warm engine.
                    self.router.pin(sid, replica,
                                    self.router._session_pins)
            except ValueError:
                pass
        self._reply(status, out, ctype)

    def _session_replica(self, path: str) -> Optional[Replica]:
        sid = path[len("/session/"):].split("/", 1)[0]
        replica = self.router.pinned(sid, self.router._session_pins)
        if replica is None:
            self._json(404, {"error": f"unknown session {sid!r}"},
                       close=True)
            return None
        return replica

    def do_PATCH(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if not (path.startswith("/session/")
                and path.endswith("/events")):
            self._json(404, {"error": "unknown path"}, close=True)
            return
        raw = self._read_body()
        if raw is None:
            return
        replica = self._session_replica(path)
        if replica is not None:
            self._proxy(replica, "PATCH", path, raw)

    def do_DELETE(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if not path.startswith("/session/"):
            self._json(404, {"error": "unknown path"}, close=True)
            return
        replica = self._session_replica(path)
        if replica is not None:
            self._proxy(replica, "DELETE", path, None)

    def _proxy_sse(self, replica: Replica, path: str):
        """Stream a worker's per-session SSE through: chunks are
        relayed as they arrive until either side closes."""
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", replica.port, timeout=FORWARD_TIMEOUT_S)
            conn.request("GET", path)
            resp = conn.getresponse()
        except OSError as exc:
            self._json(503, {"error": f"replica unreachable ({exc})"})
            return
        if resp.status != 200:
            self._reply(resp.status, resp.read(),
                        resp.getheader("Content-Type",
                                       "application/json"))
            conn.close()
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            while not self.telemetry._stopping.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    break
                self.wfile.write(chunk)
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # either side went away — normal SSE termination
        finally:
            conn.close()


class RouterFrontEnd(TelemetryServer):
    """The fleet's single client-facing HTTP server.  Mounts the
    router wire protocol over the telemetry routes; while running,
    the fleet health summary feeds the process-wide /healthz
    provider (zero live replicas → 503, like a single service's open
    breaker)."""

    handler_class = _RouterHandler

    def __init__(self, router: FleetRouter, port: int = 0,
                 host: str = "127.0.0.1", registry=None):
        super().__init__(port=port, host=host, registry=registry)
        self.router = router
        self._prior_provider = None

    def start(self) -> "RouterFrontEnd":
        super().start()
        self._prior_provider = get_health_provider()
        set_health_provider(self.router.health_summary)
        return self

    def stop(self):
        set_health_provider(self._prior_provider)
        self._prior_provider = None
        super().stop()

"""The batching scheduler: drain, bin, dispatch.

One daemon thread owns every device dispatch (JAX work stays on a
single thread; concurrency lives in the batch axis, not in racing
dispatches).  The loop:

1. Block on the service queue for the next request.
2. Linger ``batch_window_s`` draining more requests into per-bin
   lists — this is the coalescing window that turns a burst of N
   same-structure requests into one vmapped dispatch.  The window is
   latency the *first* request pays to buy batch-mates; under
   sustained load the queue is never empty and the window barely
   waits.
3. Dispatch each bin (largest first — most amortization per compile)
   in ``max_batch``-sized chunks through
   :meth:`~pydcop_tpu.serving.service.SolveService.dispatch`.

Different bins collected in one window still dispatch separately —
the two-structures-never-share-a-dispatch invariant lives in the bin
key (serving/binning.py), not in scheduler timing.
"""

import logging
import queue
import threading
import time
from typing import Dict, List

from pydcop_tpu.serving.sessions import SessionWork

logger = logging.getLogger("pydcop.serving.scheduler")

# Queue sentinel: wakes the loop for shutdown.
_STOP = object()


class BinScheduler:
    """Daemon scheduler thread for one SolveService."""

    def __init__(self, service, batch_window_s: float = 0.02,
                 max_batch: int = 16):
        self.service = service
        self.batch_window_s = batch_window_s
        self.max_batch = max(int(max_batch), 1)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="pydcop-serve-scheduler",
            daemon=True)

    def start(self):
        self._thread.start()

    def thread_ident(self):
        """The scheduler thread's ident — the one thread allowed to
        own a device dispatch (the speculation battery asserts
        background compiles never run on it)."""
        return self._thread.ident

    def shutdown(self, timeout: float = 30.0):
        self._stop.set()
        # Unblock a waiting get() immediately.
        try:
            self.service._queue.put_nowait(_STOP)
        except queue.Full:
            pass
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            logger.warning("scheduler thread did not stop in %.1fs",
                           timeout)

    # -- loop ---------------------------------------------------------- #

    def _run(self):
        q = self.service._queue
        while not self._stop.is_set():
            try:
                first = q.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is _STOP:
                continue
            # Session work (stateful sessions, serving/sessions.py)
            # runs between request flushes on this same thread — one
            # thread owns every device dispatch, batched or session.
            if isinstance(first, SessionWork):
                self.service.run_session_work(first)
                continue
            # Deadline enforcement happens HERE, before binning: work
            # that expired while queued is dropped (terminal EXPIRED,
            # 504) instead of burning a device dispatch — and never
            # contaminates a batch whose other members are still
            # fresh.
            if self._expire(first):
                continue
            bins: Dict = {}
            bins.setdefault(first.bin, []).append(first)
            session_work: List = []
            self._collect(q, bins, session_work)
            self._dispatch_bins(bins)
            # Session work drained during the window runs AFTER the
            # flush (events apply between segments/dispatches by
            # design) but in its original queue order.
            for work in session_work:
                self.service.run_session_work(work)
        # Shutdown: the service fails anything still queued.

    def _expire(self, req) -> bool:
        """Drop overdue work before binning; guarded so a broken
        deadline check can never kill the scheduler thread."""
        try:
            return self.service.expire_if_overdue(req)
        except Exception:  # noqa: BLE001 — last line of defense
            logger.exception("deadline check crashed; dispatching "
                             "the request anyway")
            return False

    def _collect(self, q, bins: Dict,
                 session_work: List = None) -> None:
        """Linger up to the batch window, draining arrivals into
        per-bin lists.  Stops early once the largest bin can fill a
        whole dispatch — waiting longer would only add latency to a
        batch that is already full.  Session work drained mid-window
        is stashed (in order) for the caller to run after the flush —
        it must not block collection, and its engine mutations belong
        between dispatches."""
        deadline = time.monotonic() + self.batch_window_s
        while not self._stop.is_set():
            if max(len(v) for v in bins.values()) >= self.max_batch:
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            try:
                req = q.get(timeout=remaining)
            except queue.Empty:
                return
            if req is _STOP:
                return
            if isinstance(req, SessionWork):
                if session_work is not None:
                    session_work.append(req)
                else:
                    self.service.run_session_work(req)
                continue
            if self._expire(req):
                continue
            bins.setdefault(req.bin, []).append(req)

    def _dispatch_bins(self, bins: Dict) -> None:
        # The flush plan (serving/service.plan_flush): multi-request
        # bins keep the exact path; leftover singleton bins are
        # envelope-grouped and packed when the per-flush cost model
        # says one padded dispatch beats N solo ones.  Planner
        # crashes degrade INSIDE plan_flush (once-per-flush log +
        # one-plan-per-bin fallback) — this guard is only the last
        # line of defense against the wrapper itself breaking.
        try:
            plans = self.service.plan_flush(bins)
        except Exception:  # noqa: BLE001 — last line of defense
            logger.exception("flush planning crashed; dispatching "
                             "per bin")
            from pydcop_tpu.serving.service import DispatchPlan

            plans = [DispatchPlan(list(bins[k]))
                     for k in sorted(bins,
                                     key=lambda k: -len(bins[k]))]
        chunks: List = []
        for plan in plans:
            reqs: List = plan.reqs
            for i in range(0, len(reqs), self.max_batch):
                chunks.append((reqs[i:i + self.max_batch],
                               plan.envelope, plan.lane_d))
        # Pipelined flush (ISSUE 18 tentpole a): launch chunk k+1's
        # device call while chunk k's arrays are still in flight, and
        # drain completed dispatches in PICKUP order (a request's
        # terminal callbacks fire in the order the scheduler picked
        # its chunk up — the ordering tests rely on).  At most two
        # dispatches are in flight: deeper pipelines buy nothing
        # (one device) and hold more results hostage to a crash.
        launch = getattr(self.service, "launch_dispatch", None)
        collect = getattr(self.service, "collect_dispatch", None)
        pipelined = launch is not None and collect is not None
        pending: List = []
        for chunk, envelope, lane_d in chunks:
            pb = None
            if pipelined:
                try:
                    pb = launch(chunk, envelope=envelope,
                                lane_d=lane_d)
                except Exception:  # noqa: BLE001
                    logger.exception("pipelined launch crashed; "
                                     "falling back to synchronous "
                                     "dispatch")
                    pb = None
            if pb is not None:
                pending.append(pb)
                while len(pending) > 1:
                    self._collect_one(pending.pop(0), collect)
                continue
            # Synchronous chunk (pipelining off, cold program, DPOP,
            # or a stubbed device call): drain EVERY in-flight
            # dispatch first so terminal ordering stays pickup order.
            while pending:
                self._collect_one(pending.pop(0), collect)
            # Last line of defense: dispatch() fails batches
            # cleanly on engine errors, but NOTHING may kill this
            # thread — a dead scheduler turns the service into a
            # black hole that accepts work it will never do.
            try:
                if envelope is None and lane_d is None:
                    # Positional call on the exact path: test
                    # doubles stub dispatch(reqs).
                    self.service.dispatch(chunk)
                else:
                    self.service.dispatch(chunk,
                                          envelope=envelope,
                                          lane_d=lane_d)
            except Exception as exc:  # noqa: BLE001
                logger.exception("dispatch crashed")
                for req in chunk:
                    if not req.done.is_set():
                        self.service._finish_error(
                            req, f"internal dispatch error: {exc}")
        while pending:
            self._collect_one(pending.pop(0), collect)

    def _collect_one(self, pb, collect) -> None:
        """Drain one in-flight dispatch; collect_dispatch handles its
        own failures (synchronous re-run), so anything escaping here
        is a harness bug — fail the batch, never the thread."""
        try:
            collect(pb)
        except Exception as exc:  # noqa: BLE001
            logger.exception("pipelined collect crashed")
            for req in pb.reqs:
                if not req.done.is_set():
                    self.service._finish_error(
                        req, f"internal dispatch error: {exc}")

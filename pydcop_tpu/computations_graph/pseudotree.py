"""DFS pseudo-tree over the variable constraint graph.

Reference parity: pydcop/computations_graph/pseudotree.py (PseudoTreeLink
:51, PseudoTreeNode :122, get_dfs_relations :178, _generate_dfs_tree :325
— root = max-degree heuristic :349-355, _filter_relation_to_lowest_node
:452, build_computation_graph :472).  Used by: dpop, ncbb.

The traversal here is a deterministic iterative DFS (neighbors in name
order, root = max-degree, first name wins ties), so tree shape — and
therefore DPOP message content — is reproducible across runs and hosts.
Each constraint is assigned to the *lowest* node of its scope in the tree,
which is the node that joins it into its UTIL message.
"""

from typing import Dict, Iterable, List, Optional, Set

from pydcop_tpu.computations_graph.objects import (
    ComputationGraph,
    ComputationNode,
    Link,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import Constraint


class PseudoTreeLink(Link):
    """Directed tree relation between two nodes.

    link_type is one of: parent, children, pseudo_parent, pseudo_children.
    """

    def __init__(self, link_type: str, source: str, target: str):
        if link_type not in (
            "parent", "children", "pseudo_parent", "pseudo_children"
        ):
            raise ValueError(f"Invalid pseudo-tree link type {link_type}")
        super().__init__([source, target], link_type)
        self._source = source
        self._target = target

    @property
    def source(self) -> str:
        return self._source

    @property
    def target(self) -> str:
        return self._target

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "link_type": self.type,
            "source": self._source,
            "target": self._target,
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["link_type"], r["source"], r["target"])


class PseudoTreeNode(ComputationNode):
    def __init__(self, variable: Variable,
                 constraints: Iterable[Constraint],
                 links: Iterable[PseudoTreeLink]):
        super().__init__(variable.name, "PseudoTreeComputation", links)
        self._variable = variable
        self._constraints = list(constraints)

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        """Constraints assigned to this node (it is lowest in their scope)."""
        return list(self._constraints)

    def _links_of(self, link_type: str) -> List[str]:
        return [
            l.target for l in self.links
            if l.type == link_type and l.source == self.name
        ]

    @property
    def parent(self) -> Optional[str]:
        ps = self._links_of("parent")
        return ps[0] if ps else None

    @property
    def children(self) -> List[str]:
        return self._links_of("children")

    @property
    def pseudo_parents(self) -> List[str]:
        return self._links_of("pseudo_parent")

    @property
    def pseudo_children(self) -> List[str]:
        return self._links_of("pseudo_children")

    @property
    def is_root(self) -> bool:
        return self.parent is None


class ComputationPseudoTree(ComputationGraph):
    def __init__(self, nodes: Iterable[PseudoTreeNode]):
        super().__init__("pseudotree", nodes)

    @property
    def roots(self) -> List[PseudoTreeNode]:
        return [n for n in self.nodes if n.is_root]


def _adjacency(variables: List[Variable],
               constraints: List[Constraint]) -> Dict[str, Set[str]]:
    from pydcop_tpu.utils.graphs import constraint_adjacency

    return constraint_adjacency(variables, constraints)


def build_computation_graph(
        dcop: Optional[DCOP] = None,
        variables: Optional[Iterable[Variable]] = None,
        constraints: Optional[Iterable[Constraint]] = None,
) -> ComputationPseudoTree:
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    adj = _adjacency(variables, constraints)
    var_by_name = {v.name: v for v in variables}

    visited: Dict[str, int] = {}  # name -> dfs depth
    parent: Dict[str, Optional[str]] = {}
    children: Dict[str, List[str]] = {v.name: [] for v in variables}
    pseudo_parents: Dict[str, List[str]] = {v.name: [] for v in variables}
    pseudo_children: Dict[str, List[str]] = {v.name: [] for v in variables}

    remaining = set(adj)
    while remaining:
        # Root of next tree: max degree, first name on ties.
        root = max(
            sorted(remaining), key=lambda n: len(adj[n] & remaining)
        )
        parent[root] = None
        stack = [(root, iter(sorted(adj[root])))]
        visited[root] = 0
        remaining.discard(root)
        while stack:
            node, neighbors = stack[-1]
            advanced = False
            for nb in neighbors:
                if nb not in visited:
                    visited[nb] = len(stack)
                    parent[nb] = node
                    children[node].append(nb)
                    remaining.discard(nb)
                    stack.append((nb, iter(sorted(adj[nb]))))
                    advanced = True
                    break
                # Back edge to a strict ancestor (not the direct parent):
                # nb is a pseudo-parent of node.
                if (
                    nb != parent.get(node)
                    and nb not in children[node]
                    and visited[nb] < visited[node]
                    and nb not in pseudo_parents[node]
                ):
                    pseudo_parents[node].append(nb)
                    pseudo_children[nb].append(node)
            if not advanced:
                stack.pop()

    # Assign each constraint to the lowest node of its scope in the tree.
    assigned: Dict[str, List[Constraint]] = {v.name: [] for v in variables}
    for c in constraints:
        scope = [v.name for v in c.dimensions]
        lowest = max(scope, key=lambda n: visited.get(n, -1))
        assigned[lowest].append(c)

    nodes = []
    for v in variables:
        links = []
        if parent[v.name] is not None:
            links.append(PseudoTreeLink("parent", v.name, parent[v.name]))
        for ch in children[v.name]:
            links.append(PseudoTreeLink("children", v.name, ch))
        for pp in pseudo_parents[v.name]:
            links.append(PseudoTreeLink("pseudo_parent", v.name, pp))
        for pc in pseudo_children[v.name]:
            links.append(PseudoTreeLink("pseudo_children", v.name, pc))
        nodes.append(PseudoTreeNode(v, assigned[v.name], links))
    return ComputationPseudoTree(nodes)


def node_depths(graph: ComputationPseudoTree) -> Dict[str, int]:
    """Depth of every node (root = 0), memoized over parent links."""
    nodes = {n.name: n for n in graph.nodes}
    depth: Dict[str, int] = {}

    def _depth(name: str) -> int:
        if name not in depth:
            parent = nodes[name].parent
            depth[name] = 0 if parent is None else _depth(parent) + 1
        return depth[name]

    for name in nodes:
        _depth(name)
    return depth


def computation_memory(node: ComputationNode) -> float:
    """DPOP UTIL-table footprint upper bound: product of separator domain
    sizes (exponential in separator size)."""
    if not isinstance(node, PseudoTreeNode):
        raise TypeError(f"Unsupported node {node}")
    sep = set(node.pseudo_parents)
    if node.parent:
        sep.add(node.parent)
    size = 1.0
    for c in node.constraints:
        for v in c.dimensions:
            if v.name in sep:
                size *= len(v.domain)
    return size


def communication_load(src: ComputationNode, target: str) -> float:
    return computation_memory(src)

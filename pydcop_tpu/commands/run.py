"""``pydcop run`` — placeholder, implemented later this round.

Reference parity target: pydcop/commands/run.py.
"""


def set_parser(subparsers):
    parser = subparsers.add_parser("run", help="run (not yet implemented)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    print("pydcop run: not implemented yet in pydcop-tpu")
    return 3

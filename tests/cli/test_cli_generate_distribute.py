"""CLI tests for `pydcop generate` and `pydcop distribute` (reference
tests/dcop_cli covers these; ours previously exercised the generator
functions only through the library, not the CLI surface)."""

import json
import os
import subprocess
import sys

import pytest
import yaml

from fixtures_paths import LOCAL_INSTANCES as INSTANCES
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def cli(args, timeout=120):
    return subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli"] + args,
        timeout=timeout, env=ENV,
    ).decode()


def _load_as_dcop(text):
    from pydcop_tpu.dcop.yamldcop import load_dcop

    return load_dcop(text)


def test_generate_graph_coloring_yaml_roundtrips():
    out = cli([
        "generate", "graph_coloring", "-v", "12", "-c", "3",
        "-g", "random", "-p", "0.3", "--seed", "1",
        "--allow_subgraph",
    ])
    dcop = _load_as_dcop(out)
    assert len(dcop.variables) == 12
    assert dcop.constraints


def test_generate_ising_grid():
    out = cli([
        "generate", "ising", "--row_count", "3", "--col_count", "3",
        "--seed", "0",
    ])
    dcop = _load_as_dcop(out)
    assert len(dcop.variables) == 9
    # Grid ising: binary factors (right + down per cell, wrapping) and
    # one unary factor per variable.
    arities = [c.arity for c in dcop.constraints.values()]
    assert arities.count(2) == 18
    assert arities.count(1) == 9


def test_generate_secp_structure():
    out = cli([
        "generate", "secp", "--lights", "4", "--models", "2",
        "--rules", "2", "--seed", "3",
    ])
    dcop = _load_as_dcop(out)
    names = set(dcop.variables)
    assert {"l0", "l1", "l2", "l3", "m0", "m1"} <= names
    # Agents carry the hosting-cost pinning convention.
    a0 = dcop.agents["a0"]
    assert a0.hosting_cost("l0") == 0
    assert a0.hosting_cost("l1") > 0


def test_generate_meetings():
    out = cli([
        "generate", "meetings", "--slots_count", "4",
        "--events_count", "3", "--resources_count", "3",
        "--max_resources_event", "2", "--seed", "0",
    ])
    dcop = _load_as_dcop(out)
    assert dcop.variables and dcop.constraints


def test_generate_scenario():
    out = cli([
        "generate", "scenario", "--evts_count", "3",
        "--actions_count", "1", "--delay", "2",
        "--initial_delay", "1", "--seed", "0",
        "--dcop_files",
        os.path.join(INSTANCES, "coloring_4agents_10vars.yaml"),
    ])
    data = yaml.safe_load(out)
    assert "events" in data
    removes = [
        a for e in data["events"] for a in e.get("actions", [])
        if a["type"] == "remove_agent"
    ]
    assert removes


@pytest.mark.parametrize("method", ["adhoc", "gh_cgdp", "ilp_compref"])
def test_distribute_command_produces_full_distribution(method, tmp_path):
    out = cli([
        "distribute", "-d", method, "-a", "dsa",
        os.path.join(INSTANCES, "coloring_4agents_10vars.yaml"),
    ])
    data = json.loads(out)
    dist = data["distribution"]
    hosted = sorted(c for comps in dist.values() for c in comps)
    assert hosted == sorted(f"v{i:03d}" for i in range(10))
    assert "cost" in data


def test_distribute_respects_graph_for_maxsum():
    """Factor-graph algo: distribution covers variables AND factors."""
    out = cli([
        "distribute", "-d", "adhoc", "-a", "maxsum",
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    data = json.loads(out)
    hosted = sorted(
        c for comps in data["distribution"].values() for c in comps)
    assert "w1" in hosted
    assert any(h.startswith("clash") for h in hosted)


def test_solve_writes_run_metrics_csv(tmp_path):
    metrics = tmp_path / "metrics.csv"
    out = cli([
        "-t", "6", "solve", "--algo", "dsa", "--mode", "thread",
        "--collect_on", "cycle_change",
        "--run_metrics", str(metrics),
        "--algo_params", "stop_cycle:20",
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    result = json.loads(out)
    assert result["status"] in ("FINISHED", "TIMEOUT")
    lines = metrics.read_text().strip().splitlines()
    # Header + at least one cycle row.
    assert len(lines) >= 2
    assert "cycle" in lines[0]

def test_device_solve_writes_cycle_metrics(tmp_path):
    """Device mode produces the same per-cycle CSV schema thread mode
    streams live, reconstructed from the engine's cost trace."""
    metrics = tmp_path / "device_metrics.csv"
    out = cli([
        "solve", "--algo", "maxsum", "--mode", "device",
        "--cycles", "40",
        "--collect_on", "cycle_change",
        "--run_metrics", str(metrics),
        os.path.join(INSTANCES, "coloring_chain.yaml"),
    ])
    result = json.loads(out)
    assert result["backend"] == "device"
    lines = metrics.read_text().strip().splitlines()
    # Header + one row per cycle + the final summary row.
    assert len(lines) >= result["cycle"] + 1
    header = lines[0].split(",")
    assert "cycle" in header and "cost" in header
    # Costs in the trace end at the reported final cost.
    import csv as _csv

    rows = list(_csv.DictReader(metrics.read_text().splitlines()))
    cycle_rows = [r for r in rows if r["status"] == "RUNNING"]
    # f32 device trace vs f64 host cost: approximate equality.
    assert float(cycle_rows[-1]["cost"]) == pytest.approx(
        result["cost"], abs=1e-5)

"""Battery over dcop/scenario.py objects and structural properties of
the ising / meetingscheduling generators."""

import pytest

from pydcop_tpu.dcop.scenario import DcopEvent, EventAction, Scenario
from pydcop_tpu.generators.ising import generate_ising
from pydcop_tpu.generators.meetingscheduling import generate_meetings
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr


class TestScenarioObjects:
    def test_action_fields(self):
        a = EventAction("remove_agent", agent="a1")
        assert a.type == "remove_agent"
        assert a.args == {"agent": "a1"}

    def test_action_equality(self):
        assert EventAction("x", k=1) == EventAction("x", k=1)
        assert EventAction("x", k=1) != EventAction("x", k=2)
        assert EventAction("x") != EventAction("y")

    def test_action_wire_roundtrip(self):
        a = EventAction("add_agent", agent="a9", capacity=5)
        a2 = from_repr(simple_repr(a))
        assert a2 == a

    def test_delay_event(self):
        e = DcopEvent("e1", delay=2.5)
        assert e.is_delay and e.delay == 2.5
        assert e.actions is None

    def test_action_event(self):
        e = DcopEvent("e2", actions=[EventAction("remove_agent",
                                                 agent="a1")])
        assert not e.is_delay
        assert len(e.actions) == 1

    def test_event_wire_roundtrip(self):
        e = DcopEvent("e2", actions=[EventAction("remove_agent",
                                                 agent="a1")])
        e2 = from_repr(simple_repr(e))
        assert e2 == e

    def test_scenario_container(self):
        s = Scenario([DcopEvent("e1", delay=1.0)])
        s.add_event(DcopEvent("e2", delay=2.0))
        assert len(s) == 2
        assert [e.id for e in s] == ["e1", "e2"]
        assert s.events[0].is_delay


class TestIsingGenerator:
    def test_structure(self):
        dcop, var_map, fg_map = generate_ising(3, 4, seed=1)
        assert len(dcop.variables) == 12
        # toroidal grid: 2 binary constraints per cell + 1 unary each
        binary = [c for c in dcop.constraints.values() if c.arity == 2]
        unary = [c for c in dcop.constraints.values() if c.arity == 1]
        assert len(binary) == 24
        assert len(unary) == 12
        assert dcop.objective == "min"

    def test_deterministic_by_seed(self):
        d1, *_ = generate_ising(3, 3, seed=7)
        d2, *_ = generate_ising(3, 3, seed=7)
        binaries = [c for c in d1.constraints.values() if c.arity == 2]
        assert binaries, "expected binary couplings"
        checked = 0
        for c1 in binaries:
            c2 = d2.constraints[c1.name]
            for a in ((0, 0), (0, 1), (1, 0), (1, 1)):
                assert c1(*a) == c2(*a)
                checked += 1
        assert checked > 0

    def test_unary_range_bounded(self):
        dcop, *_ = generate_ising(3, 3, un_range=0.05, seed=3)
        for c in dcop.constraints.values():
            if c.arity == 1:
                assert abs(c(0)) <= 0.05

    def test_intentional_form_matches_extensive(self):
        ext, *_ = generate_ising(2, 2, seed=5, extensive=True)
        intn, *_ = generate_ising(2, 2, seed=5, extensive=False)
        checked_unary = checked_binary = 0
        for name, c_ext in ext.constraints.items():
            c_int = intn.constraints[name]
            if c_ext.arity == 1:
                for v in (0, 1):
                    assert c_ext(v) == pytest.approx(c_int(v))
                checked_unary += 1
            else:
                for a in ((0, 0), (0, 1), (1, 0), (1, 1)):
                    assert c_ext(*a) == pytest.approx(c_int(*a))
                checked_binary += 1
        assert checked_unary and checked_binary


class TestMeetingsGenerator:
    def test_deterministic_by_seed(self):
        d1 = generate_meetings(4, 3, 3, 2, seed=9)
        d2 = generate_meetings(4, 3, 3, 2, seed=9)
        assert sorted(d1.variables) == sorted(d2.variables)
        assert sorted(d1.constraints) == sorted(d2.constraints)
        # Values too, not just names: unary value tables must match.
        checked = 0
        for name, c1 in d1.constraints.items():
            if c1.arity != 1:
                continue
            c2 = d2.constraints[name]
            for v in c1.dimensions[0].domain:
                assert c1(v) == c2(v)
                checked += 1
        assert checked > 0

    def test_solvable_by_dpop(self):
        from pydcop_tpu.api import solve

        dcop = generate_meetings(3, 2, 2, 1, seed=4)
        res = solve(dcop, "dpop")
        assert res["status"] == "FINISHED"
        assert set(res["assignment"]) == set(dcop.variables)

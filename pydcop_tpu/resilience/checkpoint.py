"""Engine checkpoint/resume: NPZ snapshots of device-resident state.

A long on-device solve on a preemptible slice dies with zero recovery
when the whole solve is one uninterruptible XLA program.  The engine
side (``MaxSumEngine.run_checkpointed``) chunks the jitted loop into
K-cycle segments and calls a :class:`CheckpointManager` between
segments; this module owns the on-disk format and the resume entry
point.  Because the superstep is deterministic and segment boundaries
re-enter ``run_maxsum_from`` with the exact device state, a resumed
solve reproduces the uninterrupted trajectory bit-for-bit (asserted in
tests/unit/test_resilience_battery.py).

Format: one ``ckpt_<cycle>.npz`` per snapshot — flattened state leaves
(``leaf_<i>``) + a JSON metadata blob (version, cycle, leaf count,
content checksum, engine tag).  Writes are atomic (tmp +
``os.replace``) so a crash mid-write never corrupts the latest good
snapshot.  Integrity is verified on READ, not trusted from the write
path: the meta blob carries a sha256 over every leaf's bytes (+ shape
and dtype), so a torn async write, a truncated file or silent disk
corruption is detected by :func:`verify_checkpoint` /
:func:`load_state` (:class:`CheckpointCorruptError`) and ``latest()``
falls back to the newest snapshot that fully verifies —
``resume_from_checkpoint`` can therefore NEVER resume from garbage.
:class:`AsyncCheckpointWriter` moves the device→host fetch and the
write onto a background thread (bounded queue, flush-on-exit, same
atomic format) so snapshotting overlaps device compute — the engine's
default checkpoint path.  The state's pytree *structure* is not stored:
restore goes through a template state built from the same compiled
graph, which also re-applies the template's device/sharding placement
(checkpoints taken on a mesh restore onto a mesh).
"""

import atexit
import hashlib
import json
import logging
import os
import queue
import re
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("pydcop.resilience.checkpoint")

CHECKPOINT_VERSION = 1
_CKPT_RE = re.compile(r"^ckpt_(\d+)\.npz$")


class CheckpointCorruptError(RuntimeError):
    """A snapshot failed integrity verification (unreadable container,
    missing leaves, or checksum mismatch)."""


def _content_checksum(arrays: Dict[str, np.ndarray]) -> str:
    """sha256 over every leaf's bytes, shape and dtype, in leaf order.
    Shape/dtype are hashed too: a corruption that re-interprets bytes
    under a different dtype must not collide."""
    h = hashlib.sha256()
    for name in sorted(arrays, key=lambda n: int(n.split("_")[1])):
        arr = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def save_state(path: str, state: Any, *, cycle: int,
               extra: Optional[Dict[str, Any]] = None) -> str:
    """Atomically write a state pytree to ``path`` (.npz)."""
    from pydcop_tpu.observability.trace import tracer

    if tracer.enabled:
        with tracer.span("checkpoint_write", "resilience",
                         path=path, cycle=int(cycle)):
            return _save_state(path, state, cycle=cycle, extra=extra)
    return _save_state(path, state, cycle=cycle, extra=extra)


def _save_state(path: str, state: Any, *, cycle: int,
                extra: Optional[Dict[str, Any]] = None) -> str:
    import jax

    from pydcop_tpu.observability.metrics import registry

    t0 = time.perf_counter()
    leaves = jax.tree_util.tree_leaves(state)
    arrays = {
        f"leaf_{i}": np.asarray(jax.device_get(leaf))
        for i, leaf in enumerate(leaves)
    }
    meta = {
        "version": CHECKPOINT_VERSION,
        "cycle": int(cycle),
        "n_leaves": len(leaves),
        "checksum": _content_checksum(arrays),
        "extra": extra or {},
    }
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=".ckpt_tmp_", suffix=".npz"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta), **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    registry.counter(
        "pydcop_checkpoints_total", "Checkpoint snapshots written"
    ).inc()
    if registry.active:
        registry.histogram(
            "pydcop_checkpoint_write_seconds",
            "Wall seconds per checkpoint write",
        ).observe(time.perf_counter() - t0)
    return path


def read_meta(path: str) -> Dict[str, Any]:
    with np.load(path, allow_pickle=False) as data:
        return json.loads(str(data["__meta__"]))


def _verify_arrays(path: str, meta: Dict[str, Any],
                   arrays: Dict[str, np.ndarray]):
    """Checksum the loaded leaves against the meta blob.  Pre-checksum
    snapshots (no ``checksum`` key) pass — their atomic rename is the
    only integrity story they have."""
    expected = meta.get("checksum")
    if expected is None:
        return
    actual = _content_checksum(arrays)
    if actual != expected:
        raise CheckpointCorruptError(
            f"Checkpoint {path} failed content verification: "
            f"checksum {actual[:12]}… != recorded {expected[:12]}… "
            "(torn write or disk corruption)"
        )


def verify_checkpoint(path: str) -> Dict[str, Any]:
    """Fully verify one snapshot: readable container, every declared
    leaf present, content checksum matching.  Returns the meta blob;
    raises :class:`CheckpointCorruptError` on any failure (including
    an unreadable/truncated NPZ)."""
    try:
        with np.load(path, allow_pickle=False) as data:
            meta = json.loads(str(data["__meta__"]))
            arrays = {
                f"leaf_{i}": data[f"leaf_{i}"]
                for i in range(meta["n_leaves"])
            }
    except CheckpointCorruptError:
        raise
    except Exception as e:
        raise CheckpointCorruptError(
            f"Checkpoint {path} unreadable: {e}") from e
    _verify_arrays(path, meta, arrays)
    return meta


def load_state(path: str, template: Any) -> Tuple[Any, Dict[str, Any]]:
    """Load a snapshot back into ``template``'s pytree structure and
    device placement, verifying its content checksum.  Returns
    ``(state, meta)``; raises :class:`CheckpointCorruptError` when the
    snapshot fails verification."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(template)
    # An unreadable container / missing leaf is CORRUPTION
    # (CheckpointCorruptError — resume falls back past it); a version
    # or structure mismatch is a CALLER error (ValueError — never
    # silently skipped).
    try:
        data = np.load(path, allow_pickle=False)
    except Exception as e:
        raise CheckpointCorruptError(
            f"Checkpoint {path} unreadable: {e}") from e
    with data:
        try:
            meta = json.loads(str(data["__meta__"]))
        except Exception as e:
            raise CheckpointCorruptError(
                f"Checkpoint {path} unreadable: {e}") from e
        if meta.get("version") != CHECKPOINT_VERSION:
            raise ValueError(
                f"Checkpoint {path} has version {meta.get('version')}; "
                f"this build reads version {CHECKPOINT_VERSION}"
            )
        if meta["n_leaves"] != len(leaves):
            raise ValueError(
                f"Checkpoint {path} has {meta['n_leaves']} leaves but "
                f"the engine state has {len(leaves)}: wrong problem or "
                "engine configuration"
            )
        try:
            loaded = [data[f"leaf_{i}"] for i in range(len(leaves))]
        except Exception as e:
            raise CheckpointCorruptError(
                f"Checkpoint {path} unreadable: {e}") from e
    _verify_arrays(
        path, meta,
        {f"leaf_{i}": arr for i, arr in enumerate(loaded)},
    )
    placed = []
    for arr, ref in zip(loaded, leaves):
        if arr.shape != ref.shape:
            raise ValueError(
                f"Checkpoint {path} leaf shape {arr.shape} != engine "
                f"state shape {ref.shape}: wrong problem"
            )
        sharding = getattr(ref, "sharding", None)
        placed.append(
            jax.device_put(arr.astype(ref.dtype), sharding)
            if sharding is not None else jax.device_put(arr)
        )
    return jax.tree_util.tree_unflatten(treedef, placed), meta


class CheckpointManager:
    """Cadence + retention over a checkpoint directory.

    ``every`` is the segment length in cycles (the engine snapshots at
    each segment boundary); ``keep`` bounds how many snapshots stay on
    disk (oldest pruned first — the latest good one is never pruned).
    """

    def __init__(self, directory: str, every: int = 100, keep: int = 2):
        if every <= 0:
            raise ValueError(f"checkpoint cadence must be > 0: {every}")
        if keep < 1:
            raise ValueError(f"must keep at least 1 checkpoint: {keep}")
        self.directory = directory
        self.every = every
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def path_for(self, cycle: int) -> str:
        return os.path.join(self.directory, f"ckpt_{int(cycle)}.npz")

    def checkpoints(self) -> List[Tuple[int, str]]:
        """(cycle, path) pairs present on disk, oldest first."""
        found = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                found.append(
                    (int(m.group(1)),
                     os.path.join(self.directory, name))
                )
        return sorted(found)

    def latest(self) -> Optional[str]:
        """Path of the newest VALID checkpoint.  Candidates are fully
        verified (container readable, every leaf present, content
        checksum matching — :func:`verify_checkpoint`), newest first;
        a corrupt or truncated snapshot (torn async write, disk rot)
        is skipped with a warning and the next older one is tried, so
        a resume can never start from garbage."""
        for cycle, path in reversed(self.checkpoints()):
            try:
                verify_checkpoint(path)
                return path
            except Exception as e:
                logger.warning(
                    "Skipping corrupt checkpoint %s, falling back to "
                    "an older snapshot: %s", path, e
                )
        return None

    def save(self, state: Any, cycle: int,
             extra: Optional[Dict[str, Any]] = None) -> str:
        path = save_state(
            self.path_for(cycle), state, cycle=cycle, extra=extra
        )
        self._prune()
        return path

    def _prune(self):
        existing = self.checkpoints()
        for _, path in existing[:-self.keep]:
            try:
                os.unlink(path)
            except OSError:
                pass


class AsyncCheckpointWriter:
    """Background device→host fetch + atomic NPZ write.

    The synchronous ``CheckpointManager.save`` puts a full host sync
    and a file write on the solve's critical path every segment; this
    writer moves BOTH off it.  ``submit`` enqueues the state pytree
    and returns immediately; a single daemon thread fetches the leaves
    (``jax.device_get`` blocks there, overlapping the next segment's
    device compute) and reuses the crash-safe temp-then-rename write
    (:func:`_save_state`), then applies the manager's retention
    pruning.  Each write runs inside the tracer's ``checkpoint_write``
    span ON THE WRITER THREAD, so a trace of an async-checkpointed run
    shows those spans concurrent with ``engine_segment`` — the
    overlap proof the tier-1 battery asserts.

    Contract:

    - the submitted state must stay valid until written: callers that
      donate their state buffers hand a device-side copy instead
      (``MaxSumEngine.run_checkpointed`` does);
    - the queue is bounded (``maxsize``): if writes fall behind, the
      engine loop blocks on ``submit`` rather than buying unbounded
      host memory — backpressure, not a crash;
    - ``close`` drains the queue and joins the thread (also registered
      ``atexit`` so an abandoned writer still flushes — but the atexit
      drain LOGS a failure instead of raising it: an exception thrown
      into interpreter shutdown cannot be handled by anyone and only
      garbles the exit.  Explicit ``flush``/``close`` calls keep
      raising);
    - a write failure is re-raised on the NEXT ``submit``/``flush``/
      ``close`` — never swallowed, never crashing the writer thread.
    """

    def __init__(self, manager: "CheckpointManager", maxsize: int = 2):
        self._manager = manager
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="pydcop-ckpt-writer", daemon=True
        )
        self._thread.start()
        atexit.register(self._close_at_exit)

    def _run(self):
        import jax

        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            state, cycle, extra = item
            try:
                cycle = int(np.asarray(jax.device_get(cycle)))
                save_state(
                    self._manager.path_for(cycle), state,
                    cycle=cycle, extra=extra,
                )
                self._manager._prune()
            except BaseException as exc:  # noqa: BLE001 - reraised
                self._error = exc
            finally:
                self._q.task_done()

    def _raise_pending(self):
        if self._error is not None:
            exc, self._error = self._error, None
            raise RuntimeError(
                "async checkpoint write failed"
            ) from exc

    def submit(self, state: Any, cycle,
               extra: Optional[Dict[str, Any]] = None) -> None:
        """Enqueue one snapshot.  ``cycle`` may be a device scalar —
        even that fetch happens on the writer thread."""
        if self._closed:
            raise RuntimeError("writer is closed")
        self._raise_pending()
        self._q.put((state, cycle, extra))

    def flush(self) -> None:
        """Block until every submitted snapshot is on disk."""
        self._q.join()
        self._raise_pending()

    def close(self) -> None:
        """Flush, stop the thread and surface any pending error."""
        if self._closed:
            return
        self._closed = True
        try:
            self._q.join()
            self._q.put(None)
            self._thread.join()
        finally:
            try:
                atexit.unregister(self._close_at_exit)
            except Exception:  # pragma: no cover - interpreter exit
                pass
        self._raise_pending()

    def _close_at_exit(self) -> None:
        """Atexit drain: flush like :meth:`close`, but log-and-swallow
        a failure — re-raising into interpreter shutdown turns one
        failed background write into an unhandleable error splat at
        exit.  Every explicit ``submit``/``flush``/``close`` still
        raises."""
        try:
            self.close()
        except Exception:
            logger.exception(
                "Async checkpoint flush failed during interpreter "
                "shutdown; the last snapshot may be missing"
            )


def resume_from_checkpoint(engine, manager, max_cycles: int = 1000,
                           **run_kwargs):
    """Continue an interrupted checkpointed solve.

    ``manager`` is a :class:`CheckpointManager` or a directory path.
    Loads the newest readable snapshot, restores it into the engine's
    state structure (and device placement) and re-enters the segmented
    loop; with no snapshot on disk the solve simply starts from cycle
    0 — so preemptible deployments can always launch through this one
    entry point.  Returns the engine's ``DeviceRunResult``; determinism
    with the uninterrupted run is covered by the tier-1 battery.
    """
    if isinstance(manager, str):
        manager = CheckpointManager(manager)
    initial_state = None
    resumed_cycle = 0
    template = engine.init_state()
    if run_kwargs.get("decimation") is not None:
        # Decimated snapshots bundle the clamp set with the solver
        # state (engine/runner.DecimationState) — restore into the
        # matching structure so resume-mid-decimation continues the
        # exact clamped problem, not the original one.
        from pydcop_tpu.engine.runner import decimation_template

        template = decimation_template(engine, template)
    # Newest-first over every snapshot on disk: load_state re-verifies
    # the checksum, so a snapshot that rots between listing and load
    # falls back to the next older one instead of resuming from
    # garbage.  ONLY corruption falls back: a structural mismatch
    # (wrong problem / engine configuration — ValueError) is a caller
    # error and still aborts loudly, as it always has; silently
    # restarting such a run from cycle 0 would also let retention GC
    # the other problem's snapshots.
    for cycle, path in reversed(manager.checkpoints()):
        try:
            initial_state, meta = load_state(path, template)
            resumed_cycle = meta["cycle"]
            logger.info(
                "Resuming from %s (cycle %d)", path, resumed_cycle
            )
            break
        except (CheckpointCorruptError, OSError) as e:
            logger.warning(
                "Checkpoint %s failed verification (%s); falling back "
                "to an older snapshot", path, e,
            )
    result = engine.run_checkpointed(
        max_cycles=max_cycles, manager=manager,
        initial_state=initial_state, **run_kwargs,
    )
    result.metrics["resumed_from_cycle"] = resumed_cycle
    return result

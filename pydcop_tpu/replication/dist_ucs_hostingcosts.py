"""Distributed replica placement: uniform-cost search over agents with
hosting costs.

Reference parity: pydcop/replication/dist_ucs_hostingcosts.py
(build_replication_computation :89, UCSReplicateMessage :118,
ReplicationTracker :231, UCSReplication :265) — the AAMAS-18 algorithm
placing k replicas of each of an agent's computations on other agents,
exploring candidate hosts in increasing (route + hosting) cost order.

Redesign notes (not a translation).  The reference's fully decentralised
token-walk is replaced by an *owner-driven* uniform-cost search with the
same cost model and the same message-passing constraints:

- the search graph is agents + one virtual ``__hosting__`` node per
  agent whose edge cost is that agent's hosting cost for the
  computation (reference's virtual-node trick, dist_ucs_hostingcosts.py
  module docstring);
- route and hosting costs are *private* to each agent: the owner only
  learns them through probe answers, so cost discovery stays
  distributed — only the frontier bookkeeping is centralised on the
  computation's owner, which removes the reference's budget-based
  iterative deepening while preserving visit order (cheapest first);
- capacity admission is decided by the remote agent at placement time
  (two-phase: probe, then place), so concurrent searches from several
  owners cannot oversubscribe an agent.

Each agent runs one ``UCSReplication`` computation
(``_replication_<agent>``).  The orchestrator triggers replication with
a ``replicate`` message; when every hosted computation has k replicas
(or candidates are exhausted), the owner reports a
``replication_done`` message with the replica hosts.
"""

import heapq
import logging
from typing import Dict, List, Optional, Set, Tuple

from pydcop_tpu.infrastructure.communication import MSG_MGT
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
    build_computation,
    message_type,
    register,
)
from pydcop_tpu.replication.path_utils import Path, before_last, last

logger = logging.getLogger("pydcop.replication")

# Virtual terminal node: a path ending here means "host on the node
# before it" (hosting-cost edge).
HOSTING = "__hosting__"

# Replication runs between algorithm phases; give it management-level
# priority so it finishes before the next event (reference :116).
MSG_REPLICATION = MSG_MGT

ReplicateRequestMessage = message_type(
    "replicate", ["k", "agents"])
UCSProbeMessage = message_type(
    "ucs_probe", ["computation", "path", "footprint"])
UCSProbeAnswerMessage = message_type(
    "ucs_probe_answer",
    ["computation", "path", "can_host", "hosting_cost", "routes"])
PlaceReplicaMessage = message_type(
    "place_replica", ["computation", "comp_def", "footprint", "path"])
PlaceReplicaAnswerMessage = message_type(
    "place_replica_answer", ["computation", "accepted", "path"])
ActivateReplicaMessage = message_type(
    "activate_replica", ["computation", "surviving_hosts"])
ReplicationDoneMessage = message_type(
    "replication_done", ["agent", "replica_hosts"])
RepairDoneMessage = message_type(
    "repair_done", ["agent", "computations"])
RepairFailedMessage = message_type(
    "repair_failed", ["agent", "computations"])


def replication_computation_name(agent_name: str) -> str:
    return f"_replication_{agent_name}"


def build_replication_computation(agent, discovery) -> "UCSReplication":
    """Factory mirroring reference :89."""
    return UCSReplication(agent, discovery)


class _Search:
    """Owner-side UCS state for one computation being replicated."""

    def __init__(self, comp_name: str, comp_def, footprint: float,
                 k: int, origin: str):
        self.comp_name = comp_name
        self.comp_def = comp_def
        self.footprint = footprint
        self.k_remaining = k
        self.origin = origin
        self.frontier: List[Tuple[float, int, Path]] = []
        self._tie = 0
        self.visited: Set[str] = {origin}
        self.hosts: List[str] = []
        self.rejected: Set[str] = set()
        # (kind, path) of the in-flight request, or None.
        self.awaiting: Optional[Tuple[str, Path]] = None
        self.done = False

    def push(self, cost: float, path: Path):
        self._tie += 1
        heapq.heappush(self.frontier, (cost, self._tie, path))

    def pop(self) -> Tuple[float, Path]:
        cost, _, path = heapq.heappop(self.frontier)
        return cost, path


class UCSReplication(MessagePassingComputation):
    """Replica-placement computation, one per resilient agent.

    Owner role: runs the UCS for each computation its agent hosts.
    Host role: answers probes with private route/hosting costs and
    admits replicas under its remaining capacity.
    """

    def __init__(self, agent, discovery):
        super().__init__(replication_computation_name(agent.name))
        self.agent = agent
        self.discovery = discovery
        # Replicas hosted here: comp -> (comp_def, footprint, origin).
        self.replicas: Dict[str, Tuple] = {}
        # Computations this agent has already promoted from replica to
        # live: duplicate activate requests (HTTP at-least-once
        # delivery) are re-acked instead of nacked.
        self._activated: Set[str] = set()
        # Outcome of our own searches: comp -> hosts.
        self.replica_hosts: Dict[str, List[str]] = {}
        self._searches: Dict[str, _Search] = {}
        self._known_agents: List[str] = []

    # -- cost model ---------------------------------------------------- #

    @property
    def agent_def(self):
        return self.agent.agent_def

    def route(self, other: str) -> float:
        if self.agent_def is None:
            return 1.0
        return self.agent_def.route(other)

    def hosting_cost(self, computation: str) -> float:
        if self.agent_def is None:
            return 0.0
        return self.agent_def.hosting_cost(computation)

    def _remaining_capacity(self) -> float:
        """Capacity minus active computations and hosted replicas
        (reference _remaining_capacity :1226)."""
        capacity = None
        if self.agent_def is not None:
            capacity = self.agent_def.capacity
        if capacity is None:
            return float("inf")
        used = 0.0
        for comp in self._own_computations():
            used += _footprint(comp)
        for _, footprint, _ in self.replicas.values():
            used += footprint
        return capacity - used

    def _own_computations(self):
        return [
            c for c in self.agent.computations
            if not c.name.startswith("_")
            and getattr(c, "computation_def", None) is not None
        ]

    def _routes_to_known(self) -> Dict[str, float]:
        """Private route costs to the other *resilient* agents.

        Restricted to the resilient set announced by the trigger so the
        search graph stays closed over agents that can actually answer
        probes."""
        return {
            other: self.route(other) for other in self._known_agents
            if other != self.agent.name
        }

    # -- owner side: running the searches ------------------------------ #

    @register("replicate")
    def _on_replicate(self, sender, msg, t):
        """Trigger: place msg.k replicas of each hosted computation."""
        self._known_agents = [
            a for a in msg.agents if a != self.agent.name
        ]
        self._searches = {}
        own = self._own_computations()
        if not own:
            self._report_done()
            return
        known = set(self._known_agents)
        for comp in own:
            search = _Search(
                comp.name, comp.computation_def, _footprint(comp),
                msg.k, self.agent.name,
            )
            # Idempotent re-replication: replicas already placed on
            # still-live agents count toward k, so a re-trigger after
            # a membership change only fills the gap.
            for host in self.replica_hosts.get(comp.name, []):
                if host in known and search.k_remaining > 0:
                    search.hosts.append(host)
                    search.k_remaining -= 1
            for other in self._known_agents:
                search.push(
                    self.route(other), (self.agent.name, other)
                )
            self._searches[comp.name] = search
        for name in list(self._searches):
            self._continue_search(name)

    def _continue_search(self, comp_name: str):
        search = self._searches[comp_name]
        while search.awaiting is None and not search.done:
            if search.k_remaining == 0 or not search.frontier:
                search.done = True
                break
            cost, path = search.pop()
            if last(path) == HOSTING:
                target = before_last(path)
                if target in search.hosts or target in search.rejected:
                    continue
                search.awaiting = ("place", path, cost)
                self.post_msg(
                    replication_computation_name(target),
                    PlaceReplicaMessage(
                        comp_name, search.comp_def, search.footprint,
                        path,
                    ),
                    MSG_REPLICATION,
                )
            else:
                target = last(path)
                if target in search.visited:
                    continue
                search.visited.add(target)
                search.awaiting = ("probe", path, cost)
                self.post_msg(
                    replication_computation_name(target),
                    UCSProbeMessage(comp_name, path, search.footprint),
                    MSG_REPLICATION,
                )
        if all(s.done for s in self._searches.values()):
            self._report_done()

    @register("ucs_probe_answer")
    def _on_probe_answer(self, sender, msg, t):
        search = self._searches.get(msg.computation)
        if search is None or search.awaiting is None:
            return
        kind, path, cost = search.awaiting
        if kind != "probe" or tuple(msg.path) != tuple(path):
            return  # stale or duplicate answer
        search.awaiting = None
        path = tuple(msg.path)
        if msg.can_host:
            search.push(cost + msg.hosting_cost, path + (HOSTING,))
        for other, route_cost in msg.routes.items():
            if other not in search.visited and other != search.origin:
                search.push(cost + route_cost, path + (other,))
        self._continue_search(msg.computation)

    @register("place_replica_answer")
    def _on_place_answer(self, sender, msg, t):
        search = self._searches.get(msg.computation)
        if search is None or search.awaiting is None:
            return
        kind, path, _ = search.awaiting
        if kind != "place" or tuple(msg.path) != tuple(path):
            # Stale answer from a previous replication round or a
            # duplicate delivery (HTTP retry): accepting it would clear
            # the wrong in-flight request and corrupt k_remaining.
            return
        search.awaiting = None
        target = before_last(tuple(msg.path))
        if msg.accepted:
            if target not in search.hosts:
                search.hosts.append(target)
                search.k_remaining -= 1
        else:
            # Capacity changed between probe and placement.
            search.rejected.add(target)
        self._continue_search(msg.computation)

    def _report_done(self):
        self.replica_hosts = {
            name: list(s.hosts) for name, s in self._searches.items()
        }
        for name, s in self._searches.items():
            if s.k_remaining > 0:
                logger.warning(
                    "Replication of %s incomplete: %d replicas placed, "
                    "%d requested", name, len(s.hosts),
                    len(s.hosts) + s.k_remaining,
                )
        from pydcop_tpu.infrastructure.orchestratedagents import (
            ORCHESTRATOR_MGT,
        )

        self.post_msg(
            ORCHESTRATOR_MGT,
            ReplicationDoneMessage(self.agent.name, self.replica_hosts),
            MSG_REPLICATION,
        )

    # -- host side: admitting replicas --------------------------------- #

    @register("ucs_probe")
    def _on_probe(self, sender, msg, t):
        can_host = (
            msg.footprint <= self._remaining_capacity()
            and msg.computation not in self.replicas
            and not any(
                c.name == msg.computation
                for c in self._own_computations()
            )
        )
        self.post_msg(
            sender,
            UCSProbeAnswerMessage(
                msg.computation, msg.path, can_host,
                self.hosting_cost(msg.computation),
                self._routes_to_known(),
            ),
            MSG_REPLICATION,
        )

    @register("place_replica")
    def _on_place(self, sender, msg, t):
        accepted = (
            msg.footprint <= self._remaining_capacity()
            and msg.computation not in self.replicas
        )
        if accepted:
            self.replicas[msg.computation] = (
                msg.comp_def, msg.footprint, sender,
            )
            self.discovery.register_replica(
                msg.computation, self.agent.name
            )
        self.post_msg(
            sender,
            PlaceReplicaAnswerMessage(msg.computation, accepted, msg.path),
            MSG_REPLICATION,
        )

    @register("activate_replica")
    def _on_activate(self, sender, msg, t):
        """Repair: promote a hosted replica to a live computation
        (reference repair flow, orchestrator.py:440-534 /
        agents.py:1384)."""
        from pydcop_tpu.infrastructure.orchestratedagents import (
            ORCHESTRATOR_MGT,
        )

        if msg.computation in self._activated:
            # Duplicate delivery of a processed request: re-ack, never
            # nack — a nack here could race ahead of the original ack
            # and trigger activation on a second agent.
            self.post_msg(
                ORCHESTRATOR_MGT,
                RepairDoneMessage(self.agent.name, [msg.computation]),
                MSG_REPLICATION,
            )
            return
        entry = self.replicas.pop(msg.computation, None)
        if entry is None:
            logger.error(
                "Cannot activate %s on %s: no replica here",
                msg.computation, self.agent.name,
            )
            # Explicit nack so the orchestrator can retry another
            # candidate instead of waiting out the repair timeout.
            self.post_msg(
                ORCHESTRATOR_MGT,
                RepairFailedMessage(self.agent.name, [msg.computation]),
                MSG_REPLICATION,
            )
            return
        comp_def, _, _ = entry
        computation = build_computation(comp_def)
        self.agent.add_computation(computation)
        computation.start()
        self._activated.add(msg.computation)
        self.discovery.unregister_replica(
            msg.computation, self.agent.name
        )
        # As the computation's new owner, seed our search bookkeeping
        # with the replicas that survive elsewhere so the next
        # replication heal only fills the gap instead of re-placing k
        # fresh replicas (and leaking the survivors' capacity).
        self.replica_hosts[msg.computation] = [
            h for h in (msg.surviving_hosts or [])
            if h != self.agent.name
        ]
        self.post_msg(
            ORCHESTRATOR_MGT,
            RepairDoneMessage(self.agent.name, [msg.computation]),
            MSG_REPLICATION,
        )

    def hosted_replicas(self) -> Dict[str, Tuple[str, float]]:
        """comp -> (origin agent, footprint), reference :332."""
        return {
            c: (origin, footprint)
            for c, (_, footprint, origin) in self.replicas.items()
        }


def _footprint(comp) -> float:
    try:
        return float(comp.footprint())
    except Exception:
        return 1.0

"""ilp_compref_fg: ilp_compref applied to factor graphs.

Reference parity proof: in the reference, ilp_compref_fg.py is a
byte-level duplicate of ilp_compref.py — ``diff`` of the two files
(comments stripped) shows a single blank line as the only difference;
both build the same AAMAS-18 weighted comm+hosting LP over whatever
computation graph they are given.  The faithful port is therefore a
re-export of our ilp_compref, which already handles factor graphs
(its MILP model is graph-agnostic: nodes + links).
"""

from pydcop_tpu.distribution.ilp_compref import (  # noqa: F401
    distribute,
    distribution_cost,
)

"""Domains, variables and agent definitions.

Reference parity: pydcop/dcop/objects.py (Domain :46, Variable :175,
create_variables :258, BinaryVariable :335, VariableWithCostDict :410,
VariableWithCostFunc :464, VariableNoisyCostFunc :547, ExternalVariable
:618, AgentDef :669).

Design notes (TPU-first): a Domain is an ordered, finite list of values;
every value is addressed by its *index* throughout the device engine —
host-side objects keep the human-readable values, the compiled arrays only
ever see indices.  Noise for ``VariableNoisyCostFunc`` is drawn from a
PRNG seeded from the variable name so runs are reproducible across hosts
and backends (the reference uses an unseeded ``random.random()``, which
makes cost parity between runs impossible; we fix that deliberately).
"""

import hashlib
import itertools
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np

from pydcop_tpu.utils.simple_repr import SimpleRepr, simple_repr, from_repr


class Domain(SimpleRepr):
    """An ordered, named, finite set of values.

    >>> d = Domain('colors', 'color', ['R', 'G', 'B'])
    >>> len(d)
    3
    >>> d.index('G')
    1
    >>> Domain('d', 'd', [1, 2, 3]).to_domain_value('2')
    (1, 2)
    """

    def __init__(self, name: str, domain_type: str, values: Iterable):
        self._name = name
        self._domain_type = domain_type
        self._values = tuple(values)

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._domain_type

    @property
    def domain_type(self) -> str:
        return self._domain_type

    @property
    def values(self) -> Tuple:
        return self._values

    def index(self, val) -> int:
        return self._values.index(val)

    def to_domain_value(self, val: str):
        """Map a string to the (index, value) pair it denotes in the domain.

        Accepts either the exact value or its string form (needed when
        values come back from JSON/CLI where ints become strings).
        """
        for i, v in enumerate(self._values):
            if v == val or str(v) == str(val):
                return i, v
        raise ValueError(f"{val!r} is not in domain {self._name}")

    def __iter__(self):
        return iter(self._values)

    def __len__(self):
        return len(self._values)

    def __getitem__(self, i):
        return self._values[i]

    def __contains__(self, v):
        return v in self._values

    def __eq__(self, other):
        return (
            isinstance(other, Domain)
            and self._name == other._name
            and self._values == other._values
            and self._domain_type == other._domain_type
        )

    def __hash__(self):
        return hash((self._name, self._domain_type, self._values))

    def __repr__(self):
        return f"Domain({self._name!r}, {self._domain_type!r}, {list(self._values)})"

    def __str__(self):
        return f"Domain({self._name})"


# Backward-compatible alias used throughout the reference's API.
VariableDomain = Domain

binary_domain = Domain("binary", "binary", [0, 1])


class Variable(SimpleRepr):
    """A decision variable with a finite domain.

    >>> v = Variable('v1', Domain('d', 'd', [0, 1, 2]), initial_value=1)
    >>> v.initial_value
    1
    """

    has_cost = False

    def __init__(self, name: str, domain: Union[Domain, Iterable],
                 initial_value=None):
        self._name = name
        if not isinstance(domain, Domain):
            domain = Domain(f"d_{name}", "unnamed", list(domain))
        self._domain = domain
        if initial_value is not None and initial_value not in domain:
            raise ValueError(
                f"Initial value {initial_value!r} not in domain of {name}"
            )
        self._initial_value = initial_value

    @property
    def name(self) -> str:
        return self._name

    @property
    def domain(self) -> Domain:
        return self._domain

    @property
    def initial_value(self):
        return self._initial_value

    def cost_for_val(self, val) -> float:
        return 0.0

    def cost_vector(self) -> np.ndarray:
        """Dense per-value costs, aligned with domain order (device form)."""
        return np.array(
            [float(self.cost_for_val(v)) for v in self._domain],
            dtype=np.float64,
        )

    def clone(self):
        return Variable(self._name, self._domain, self._initial_value)

    def __eq__(self, other):
        return (
            type(other) is type(self)
            and self._name == other.name
            and self._domain == other.domain
        )

    def __hash__(self):
        return hash((type(self).__name__, self._name, self._domain))

    def __repr__(self):
        return f"Variable({self._name!r}, {self._domain})"

    def __str__(self):
        return f"Variable({self._name})"


class BinaryVariable(Variable):
    """A 0/1 variable (used by the repair-as-DCOP machinery)."""

    def __init__(self, name: str, initial_value=0):
        super().__init__(name, binary_domain, initial_value)

    def clone(self):
        return BinaryVariable(self._name, initial_value=self._initial_value)

    def __repr__(self):
        return f"BinaryVariable({self._name!r})"


class VariableWithCostDict(Variable):
    """Variable with an explicit value→cost table."""

    has_cost = True

    def __init__(self, name, domain, costs: Dict, initial_value=None):
        super().__init__(name, domain, initial_value)
        self._costs = dict(costs)

    @property
    def costs(self):
        return dict(self._costs)

    def cost_for_val(self, val) -> float:
        return self._costs.get(val, 0.0)

    def clone(self):
        return VariableWithCostDict(
            self._name, self._domain, self._costs, self._initial_value
        )


class VariableWithCostFunc(Variable):
    """Variable whose per-value cost comes from a function of its value."""

    has_cost = True

    def __init__(self, name, domain, cost_func: Union[Callable, "str"],
                 initial_value=None):
        super().__init__(name, domain, initial_value)
        from pydcop_tpu.utils.expressionfunction import ExpressionFunction

        if isinstance(cost_func, str):
            cost_func = ExpressionFunction(cost_func)
        if hasattr(cost_func, "variable_names"):
            names = list(cost_func.variable_names)
            if len(names) != 1 or names[0] != name:
                raise ValueError(
                    f"Cost function for variable {name} must depend exactly "
                    f"on it, got {names}"
                )
        self._cost_func = cost_func

    @property
    def cost_func(self):
        return self._cost_func

    def cost_for_val(self, val) -> float:
        if hasattr(self._cost_func, "variable_names"):
            return self._cost_func(**{self._name: val})
        return self._cost_func(val)

    def clone(self):
        return VariableWithCostFunc(
            self._name, self._domain, self._cost_func, self._initial_value
        )

    def _simple_repr(self):
        r = super()._simple_repr()
        r["cost_func"] = simple_repr(self._cost_func)
        return r

    @classmethod
    def _from_repr(cls, r):
        return cls(
            r["name"],
            from_repr(r["domain"]),
            from_repr(r["cost_func"]),
            initial_value=r.get("initial_value"),
        )


def _stable_noise(name: str, n: int, noise_level: float,
                  seed: Optional[int]) -> np.ndarray:
    """Per-value noise in [0, noise_level), deterministic in (name, seed).

    The reference draws unseeded random noise at construction
    (pydcop/dcop/objects.py:547); we derive the stream from the variable
    name + an optional global seed so CPU and TPU runs agree bit-for-bit.
    """
    h = hashlib.sha256(f"{name}:{seed}".encode()).digest()
    rng = np.random.default_rng(int.from_bytes(h[:8], "little"))
    return rng.random(n) * noise_level


class VariableNoisyCostFunc(VariableWithCostFunc):
    """Cost-function variable with small per-value noise added (tie-breaker).

    Used by maxsum's ``noise`` parameter (reference: maxsum.py:477-487).
    """

    has_cost = True

    def __init__(self, name, domain, cost_func, initial_value=None,
                 noise_level: float = 0.02, seed: Optional[int] = None):
        super().__init__(name, domain, cost_func, initial_value)
        self._noise_level = noise_level
        self._seed = seed
        self._noise = _stable_noise(name, len(self.domain), noise_level, seed)

    @property
    def noise_level(self) -> float:
        return self._noise_level

    def cost_for_val(self, val) -> float:
        base = super().cost_for_val(val)
        return base + float(self._noise[self.domain.index(val)])

    def clone(self):
        return VariableNoisyCostFunc(
            self._name, self._domain, self._cost_func, self._initial_value,
            self._noise_level, self._seed,
        )

    def _simple_repr(self):
        r = super()._simple_repr()
        r["noise_level"] = self._noise_level
        r["seed"] = self._seed
        return r

    @classmethod
    def _from_repr(cls, r):
        return cls(
            r["name"],
            from_repr(r["domain"]),
            from_repr(r["cost_func"]),
            initial_value=r.get("initial_value"),
            noise_level=r.get("noise_level", 0.02),
            seed=r.get("seed"),
        )


class ExternalVariable(Variable):
    """A sensor-style variable set from outside the optimization.

    Value changes fire subscribed callbacks (reference:
    pydcop/dcop/objects.py:618, ``_fire`` :655-663); used by dynamic DCOPs.
    """

    def __init__(self, name, domain, value=None):
        super().__init__(name, domain)
        self._cb = []
        self._value = None
        self.value = value if value is not None else domain[0]

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, val):
        if val == self._value:
            return
        if val not in self._domain:
            raise ValueError(
                f"Value {val!r} not in domain of external variable {self._name}"
            )
        self._value = val
        for cb in self._cb:
            cb(val)

    def subscribe(self, callback):
        self._cb.append(callback)

    def unsubscribe(self, callback):
        self._cb.remove(callback)

    def clone(self):
        return ExternalVariable(self._name, self._domain, self._value)

    def _simple_repr(self):
        r = super()._simple_repr()
        r.pop("initial_value", None)
        r["value"] = simple_repr(self._value)
        return r

    @classmethod
    def _from_repr(cls, r):
        return cls(r["name"], from_repr(r["domain"]), r.get("value"))


def _expand_indices(indexes) -> List[Tuple]:
    """Expand index ranges into the cartesian list of index tuples."""
    if isinstance(indexes, range):
        return [(i,) for i in indexes]
    dims = []
    for dim in indexes:
        if isinstance(dim, range):
            dims.append(list(dim))
        elif isinstance(dim, (list, tuple)):
            dims.append(list(dim))
        else:
            return [(i,) for i in indexes]
    return list(itertools.product(*dims))


def create_variables(name_prefix: str, indexes, domain: Domain,
                     separator: str = "_") -> Dict:
    """Mass-create variables from a prefix and index ranges.

    The prefix carries its own separator (reference objects.py:258:
    ``create_variables('x_', ...)`` names variables ``x_a_0``):

    >>> d = Domain('d', 'd', [0, 1])
    >>> vs = create_variables('x_', [['a', 'b'], range(2)], d)
    >>> sorted(vs)[0]
    ('a', 0)
    >>> vs[('a', 0)].name
    'x_a_0'
    """
    variables = {}
    if isinstance(indexes, range):
        indexes = [str(i) for i in indexes]
    if all(isinstance(i, str) for i in indexes):
        for i in indexes:
            name = name_prefix + i
            variables[name] = Variable(name, domain)
        return variables
    for combo in _expand_indices(indexes):
        name = name_prefix + separator.join(str(i) for i in combo)
        variables[tuple(combo)] = Variable(name, domain)
    return variables


def create_binary_variables(name_prefix: str, indexes,
                            separator: str = "_") -> Dict:
    """Mass-create BinaryVariables (used to build repair DCOPs)."""
    variables = {}
    if all(isinstance(i, str) for i in indexes):
        for i in indexes:
            name = name_prefix + i
            variables[name] = BinaryVariable(name)
        return variables
    for combo in _expand_indices(indexes):
        name = name_prefix + separator.join(str(i) for i in combo)
        variables[tuple(combo)] = BinaryVariable(name)
    return variables


DEFAULT_CAPACITY = 100
DEFAULT_HOSTING_COST = 0
DEFAULT_ROUTE = 1


class AgentDef(SimpleRepr):
    """Definition of an agent: capacity, hosting costs, routes, extras.

    >>> a = AgentDef('a1', capacity=100, foo='bar')
    >>> a.capacity
    100
    >>> a.foo
    'bar'
    >>> a.route('a2')
    1
    """

    def __init__(self, name: str,
                 default_hosting_cost: float = DEFAULT_HOSTING_COST,
                 hosting_costs: Optional[Dict[str, float]] = None,
                 default_route: float = DEFAULT_ROUTE,
                 routes: Optional[Dict[str, float]] = None,
                 **extra_attr):
        self._name = name
        self._default_hosting_cost = default_hosting_cost
        self._hosting_costs = dict(hosting_costs) if hosting_costs else {}
        self._default_route = default_route
        self._routes = dict(routes) if routes else {}
        self._extra_attr = dict(extra_attr)

    @property
    def name(self) -> str:
        return self._name

    @property
    def extra_attr(self) -> Dict:
        return dict(self._extra_attr)

    @property
    def capacity(self):
        return self._extra_attr.get("capacity", DEFAULT_CAPACITY)

    @property
    def default_hosting_cost(self) -> float:
        return self._default_hosting_cost

    @property
    def hosting_costs(self) -> Dict[str, float]:
        return dict(self._hosting_costs)

    @property
    def default_route(self) -> float:
        return self._default_route

    @property
    def routes(self) -> Dict[str, float]:
        return dict(self._routes)

    def hosting_cost(self, computation: str) -> float:
        return self._hosting_costs.get(computation, self._default_hosting_cost)

    def route(self, other_agent: str) -> float:
        if other_agent == self._name:
            return 0
        return self._routes.get(other_agent, self._default_route)

    def __getattr__(self, item):
        extra = object.__getattribute__(self, "_extra_attr")
        if item in extra:
            return extra[item]
        raise AttributeError(f"AgentDef has no attribute {item!r}")

    def __eq__(self, other):
        return (
            isinstance(other, AgentDef)
            and self._name == other._name
            and self._extra_attr == other._extra_attr
            and self._hosting_costs == other._hosting_costs
            and self._routes == other._routes
            and self._default_route == other._default_route
            and self._default_hosting_cost == other._default_hosting_cost
        )

    def __hash__(self):
        return hash(self._name)

    def __repr__(self):
        return f"AgentDef({self._name!r})"

    def __str__(self):
        return f"AgentDef({self._name})"

    def _simple_repr(self):
        r = {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "name": self._name,
            "default_hosting_cost": self._default_hosting_cost,
            "hosting_costs": dict(self._hosting_costs),
            "default_route": self._default_route,
            "routes": dict(self._routes),
        }
        r.update(simple_repr(self._extra_attr))
        return r

    @classmethod
    def _from_repr(cls, r):
        extras = {
            k: v for k, v in r.items()
            if k not in ("name", "default_hosting_cost", "hosting_costs",
                         "default_route", "routes")
        }
        return cls(
            r["name"],
            default_hosting_cost=r.get("default_hosting_cost", 0),
            hosting_costs=r.get("hosting_costs"),
            default_route=r.get("default_route", 1),
            routes=r.get("routes"),
            **extras,
        )


def create_agents(name_prefix: str, indexes,
                  default_hosting_cost: float = 0,
                  hosting_costs: Optional[Dict] = None,
                  default_route: float = 1,
                  routes: Optional[Dict] = None,
                  separator: str = "_",
                  **extra_attr) -> Dict:
    """Mass-create AgentDefs from a prefix and index ranges."""
    agents = {}
    if isinstance(indexes, range):
        for i in indexes:
            name = f"{name_prefix}{i}"
            agents[name] = AgentDef(
                name, default_hosting_cost, hosting_costs,
                default_route, routes, **extra_attr)
        return agents
    for combo in _expand_indices(indexes):
        name = name_prefix + separator.join(str(i) for i in combo)
        agents[tuple(combo)] = AgentDef(
            name, default_hosting_cost, hosting_costs,
            default_route, routes, **extra_attr)
    return agents

"""Tensorized DPOP: level-batched UTIL/VALUE sweeps under jit.

Reference semantics: pydcop/algorithms/dpop.py:313-439 — every node
joins its assigned constraints with its children's UTIL tables and
projects its own variable out (min/max-eliminate), leaves→root; then
assignments flow root→leaves with first-optimum tie-breaking
(relations.py:1554 find_arg_optimal).

TPU-first redesign (not a translation): the reference runs one python
computation per node, enumerating assignments in dict loops.  Here the
pseudo-tree is *level-scheduled*: all nodes at the same depth are
independent, so their UTIL tables are computed in one batched XLA call
per *signature bucket*.  A node's signature is the static shape of its
join:

    (joined-shape, (axes of component 0, axes of component 1, ...))

where each component is a dense cost table over a subset of the node's
joined dims — its own unary cost vector, the constraints assigned to
it, and its children's UTIL tables.  Nodes sharing a signature (the
common case: e.g. every leaf with one binary constraint to its parent)
are stacked on a new leading batch axis and processed by ONE jitted
kernel: broadcast-add every component into the joined hypercube, then
min/max-reduce the node's own axis.  Kernels are cached per signature,
so a 10k-node tree typically compiles a handful of programs.

The VALUE sweep is host-side: it is O(separator) gathers per node with
no batchable math (each node's slice depends on its ancestors' chosen
values), so device round-trips would dominate.

Raggedness guards (SURVEY §7 hard parts): a single node whose UTIL
table exceeds ``MAX_NODE_ELEMENTS`` raises ``UtilTooLargeError``
(mirrors the reference's footprint accounting, dpop.py:80-85 /
pseudotree computation_memory); callers fall back to the host-numpy
path when the *total* work is too small to amortize device dispatch or
too large for device memory (see algorithms/dpop.py).
"""

from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# Per-node UTIL element cap: beyond this the separator is so wide that
# the problem needs a different algorithm (or more devices), and one
# table would dominate device memory anyway.
MAX_NODE_ELEMENTS = 2 ** 26


class UtilTooLargeError(MemoryError):
    """A UTIL table exceeds the per-node element cap."""


# -- host-side compilation: tree -> level-bucketed dense components ---- #


class _NodePlan:
    """Static plan for one pseudo-tree node's UTIL computation."""

    __slots__ = (
        "name", "dims", "shape", "components", "parent", "depth",
    )

    def __init__(self, name, dims, shape, parent, depth):
        self.name = name
        self.dims = dims          # (own, sep...) variable names
        self.shape = shape        # domain sizes, same order
        self.parent = parent
        self.depth = depth
        # axes-tuple -> summed dense array (axes ascending in dims).
        self.components: Dict[Tuple[int, ...], np.ndarray] = {}

    def add_component(self, axes: Tuple[int, ...], array: np.ndarray):
        if axes in self.components:
            self.components[axes] = self.components[axes] + array
        else:
            self.components[axes] = array


def _transpose_to_axes(array: np.ndarray, positions: List[int]
                       ) -> Tuple[Tuple[int, ...], np.ndarray]:
    """Reorder ``array`` (one axis per entry of ``positions``, positions
    being indices into the node's dims) into ascending-position order."""
    order = sorted(range(len(positions)), key=lambda i: positions[i])
    axes = tuple(positions[i] for i in order)
    return axes, np.ascontiguousarray(np.transpose(array, order))


def compile_tree(graph, mode: str) -> Dict[str, _NodePlan]:
    """Build per-node static plans: dims, shapes, local components.

    ``graph`` is a ComputationPseudoTree; child-UTIL components are
    added level by level during the sweep (their arrays are produced by
    the previous level's kernels).
    """
    from pydcop_tpu.computations_graph.pseudotree import node_depths
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    nodes = {n.name: n for n in graph.nodes}
    depth = node_depths(graph)

    # Separator sets, bottom-up: sep(n) = (U sep(children) U scopes) - n.
    sep: Dict[str, set] = {}
    for name in sorted(nodes, key=lambda n: -depth[n]):
        node = nodes[name]
        s = set()
        for c in node.constraints:
            s.update(v.name for v in c.dimensions)
        for child in node.children:
            s.update(sep[child])
        s.discard(name)
        sep[name] = s

    plans: Dict[str, _NodePlan] = {}
    for name, node in nodes.items():
        var = node.variable
        # Deterministic dim order: own variable first, then separator
        # variables shallowest-first (ties by name) — ancestors of the
        # node by the pseudo-tree property.
        sep_sorted = sorted(sep[name], key=lambda v: (depth[v], v))
        dims = (name,) + tuple(sep_sorted)
        domain_of = {name: len(var.domain)}
        for c in node.constraints:
            for v in c.dimensions:
                domain_of[v.name] = len(v.domain)
        # Children contribute dims too; domain sizes resolved from the
        # child variables themselves below (graph nodes know them).
        for child in node.children:
            domain_of[nodes[child].variable.name] = \
                len(nodes[child].variable.domain)
        shape = tuple(
            domain_of.get(d) or len(nodes[d].variable.domain)
            for d in dims
        )
        n_elements = int(np.prod(shape, dtype=np.int64))
        if n_elements > MAX_NODE_ELEMENTS:
            raise UtilTooLargeError(
                f"UTIL table for {name} has {n_elements} elements "
                f"(> {MAX_NODE_ELEMENTS}); separator too wide"
            )
        plan = _NodePlan(name, dims, shape, node.parent, depth[name])
        pos = {d: i for i, d in enumerate(dims)}
        plan.add_component(
            (0,), np.asarray(var.cost_vector(), dtype=np.float32)
        )
        for c in node.constraints:
            dense = NAryMatrixRelation.from_func_relation(c)
            positions = [pos[v.name] for v in dense.dimensions]
            axes, arr = _transpose_to_axes(
                np.asarray(dense.matrix, dtype=np.float32), positions
            )
            plan.add_component(axes, arr)
        plans[name] = plan
    return plans


# -- device kernels: one per signature, cached -------------------------- #

_KERNEL_CACHE: Dict[Tuple, Any] = {}


def _kernel_for(signature: Tuple) -> Any:
    """signature = (shape, axes_tuples, mode, want_util)."""
    if signature in _KERNEL_CACHE:
        return _KERNEL_CACHE[signature]
    if len(_KERNEL_CACHE) >= 512:
        # Long-lived processes solving many differently-shaped DCOPs
        # must not accumulate compiled executables without bound.
        _KERNEL_CACHE.clear()
    import jax
    import jax.numpy as jnp

    shape, axes_tuples, mode, want_util = signature
    k = len(shape)

    def kernel(*comps):
        n = comps[0].shape[0]
        acc = jnp.zeros((n,) + shape, dtype=jnp.float32)
        for comp, axes in zip(comps, axes_tuples):
            newshape = (n,) + tuple(
                shape[i] if i in axes else 1 for i in range(k)
            )
            acc = acc + comp.reshape(newshape)
        if not want_util:
            return acc, None
        util = (
            jnp.min(acc, axis=1) if mode == "min"
            else jnp.max(acc, axis=1)
        )
        return acc, util

    _KERNEL_CACHE[signature] = jax.jit(kernel)
    return _KERNEL_CACHE[signature]


def solve_sweep(graph, mode: str = "min"
                ) -> Tuple[Dict[str, Any], Dict[str, int]]:
    """Run the full DPOP solve with level-batched jitted kernels.

    Returns (assignment, stats).
    """
    plans = compile_tree(graph, mode)
    nodes = {n.name: n for n in graph.nodes}
    by_level: Dict[int, List[str]] = defaultdict(list)
    for name, plan in plans.items():
        by_level[plan.depth].append(name)
    max_depth = max(by_level) if by_level else 0

    joined: Dict[str, np.ndarray] = {}
    n_kernel_calls = 0
    msg_count = 0
    msg_size = 0

    # UTIL sweep, deepest level first; each level is one batched kernel
    # call per signature bucket.
    for level in range(max_depth, -1, -1):
        buckets: Dict[Tuple, List[str]] = defaultdict(list)
        for name in by_level[level]:
            plan = plans[name]
            axes_tuples = tuple(sorted(plan.components))
            want_util = plan.parent is not None
            key = (plan.shape, axes_tuples, mode, want_util)
            buckets[key].append(name)
        for key, names in sorted(buckets.items()):
            shape, axes_tuples, _, want_util = key
            stacked = [
                np.stack(
                    [plans[n].components[axes] for n in names]
                )
                for axes in axes_tuples
            ]
            acc, util = _kernel_for(key)(*stacked)
            n_kernel_calls += 1
            acc_np = np.asarray(acc)
            util_np = None if util is None else np.asarray(util)
            for i, name in enumerate(names):
                plan = plans[name]
                joined[name] = acc_np[i]
                if want_util:
                    parent_plan = plans[plan.parent]
                    ppos = {
                        d: j for j, d in enumerate(parent_plan.dims)
                    }
                    positions = [ppos[d] for d in plan.dims[1:]]
                    axes, arr = _transpose_to_axes(
                        util_np[i], positions
                    )
                    parent_plan.add_component(axes, arr)
                    msg_count += 1
                    msg_size += arr.size

    # VALUE sweep, root level down: slice on ancestors' values, pick
    # the first optimum (reference find_arg_optimal order).
    assignment: Dict[str, Any] = {}
    argopt = np.argmin if mode == "min" else np.argmax
    for level in range(0, max_depth + 1):
        for name in sorted(by_level[level]):
            plan = plans[name]
            var = nodes[name].variable
            idx = tuple(
                var_index(nodes[d].variable, assignment[d])
                for d in plan.dims[1:]
            )
            vec = joined[name][(slice(None),) + idx]
            assignment[name] = var.domain[int(argopt(vec))]
            msg_count += len(nodes[name].children)
    stats = {
        "msg_count": msg_count,
        "msg_size": msg_size,
        "kernel_calls": n_kernel_calls,
        "levels": max_depth + 1,
    }
    return assignment, stats


def var_index(variable, value) -> int:
    return variable.domain.index(value)

"""Scrubbed-environment helper for JAX backend selection.

This image's sitecustomize registers the axon TPU PJRT plugin in every
python interpreter (gated on ``PALLAS_AXON_POOL_IPS``); once registered,
a wedged tunnel hangs backend init and no in-process ``jax.config``
update can recover. Every entry point that needs a guaranteed-live CPU
backend (tests, bench fallback, multichip dryrun) builds its child env
through this one helper so the scrub recipe cannot drift between copies.

No jax import here — this module must be importable before any backend
is initialized.
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def scrubbed_cpu_env(n_devices=None, base=None):
    """Return an env dict that forces a clean CPU JAX backend.

    - drops ``PALLAS_AXON_POOL_IPS`` so sitecustomize skips plugin
      registration entirely in the child interpreter;
    - sets ``JAX_PLATFORMS=cpu``;
    - when ``n_devices`` is given, forces exactly that virtual host
      device count in ``XLA_FLAGS`` (replacing any inherited value —
      an inherited smaller count would make sharded code fail even
      though it is healthy).
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = re.sub(
            _COUNT_FLAG + r"=\d+", "", env.get("XLA_FLAGS", "")
        ).strip()
        env["XLA_FLAGS"] = (
            flags + f" {_COUNT_FLAG}={n_devices}"
        ).strip()
    return env

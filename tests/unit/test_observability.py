"""Tests for the observability subsystems: event bus, step tracing,
websocket UI server.

Reference parity targets: Events.py (event bus), stats.py (trace CSV),
ui.py (per-agent websocket server).
"""

import base64
import hashlib
import json
import socket
import struct
import time

import pytest

from pydcop_tpu.infrastructure import stats
from pydcop_tpu.infrastructure.events import EventDispatcher, event_bus
from pydcop_tpu.infrastructure.ui import (
    WS_GUID,
    decode_frame,
    encode_text_frame,
)


class TestEventBus:
    def test_exact_topic(self):
        bus = EventDispatcher()
        seen = []
        bus.subscribe("a.b", lambda t, d: seen.append((t, d)))
        bus.emit("a.b", 1)
        bus.emit("a.c", 2)
        assert seen == [("a.b", 1)]

    def test_wildcard(self):
        bus = EventDispatcher()
        seen = []
        bus.subscribe("computations.value.*",
                      lambda t, d: seen.append(t))
        bus.emit("computations.value.v1", 0)
        bus.emit("computations.cycle.v1", 0)
        assert seen == ["computations.value.v1"]

    def test_disabled_when_no_subscribers(self):
        bus = EventDispatcher()
        assert not bus.enabled
        cb = bus.subscribe("x", lambda t, d: None)
        assert bus.enabled
        bus.unsubscribe(cb)
        assert not bus.enabled

    def test_value_selection_emits(self):
        from pydcop_tpu.infrastructure.computations import (
            VariableComputation,
        )
        from pydcop_tpu.dcop.objects import Domain, Variable

        seen = []
        cb = event_bus.subscribe(
            "computations.value.*", lambda t, d: seen.append((t, d))
        )
        try:
            v = Variable("vx", Domain("d", "", [0, 1]))
            comp = VariableComputation(v, None)
            comp.value_selection(1, 0.5)
        finally:
            event_bus.unsubscribe(cb)
        assert seen == [("computations.value.vx", (1, 0.5))]


class TestStats:
    def test_trace_rows(self, tmp_path):
        path = tmp_path / "trace.csv"
        stats.set_stats_file(str(path))
        try:
            assert stats.tracing_enabled()
            stats.trace_computation("v1", 0.01, 1, 3, 2, 4, value="R")
        finally:
            stats.set_stats_file(None)
        assert not stats.tracing_enabled()
        lines = path.read_text().strip().splitlines()
        assert lines[0].split(",")[:3] == ["time", "computation",
                                           "duration"]
        row = lines[1].split(",")
        assert row[1] == "v1"
        assert row[3:8] == ["1", "3", "2", "4", "R"]

    def test_noop_without_file(self):
        stats.trace_computation("v1", 0.01)  # must not raise


class _WsClient:
    """Minimal RFC6455 client for tests."""

    def __init__(self, port: int):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=5)
        key = base64.b64encode(b"0123456789abcdef").decode()
        self.sock.sendall(
            (f"GET / HTTP/1.1\r\nHost: localhost:{port}\r\n"
             "Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\n"
             "Sec-WebSocket-Version: 13\r\n\r\n").encode()
        )
        response = self.sock.recv(4096).decode("latin-1")
        assert "101" in response.split("\r\n")[0]
        expected = base64.b64encode(
            hashlib.sha1((key + WS_GUID).encode()).digest()
        ).decode()
        assert expected in response

    def send_json(self, obj):
        payload = json.dumps(obj).encode()
        mask = b"\x01\x02\x03\x04"
        masked = bytes(
            b ^ mask[i % 4] for i, b in enumerate(payload)
        )
        header = b"\x81"
        assert len(payload) < 126
        header += struct.pack("!B", 0x80 | len(payload))
        self.sock.sendall(header + mask + masked)

    def recv_json(self):
        frame = decode_frame(self.sock)
        assert frame is not None
        opcode, payload = frame
        assert opcode == 0x1
        return json.loads(payload.decode())

    def close(self):
        self.sock.close()


class TestUiServer:
    def test_frame_roundtrip(self):
        frame = encode_text_frame("hello")
        assert frame[0] == 0x81
        assert frame[2:] == b"hello"

    def test_server_commands_and_push(self):
        from pydcop_tpu.infrastructure.communication import (
            InProcessCommunicationLayer,
        )
        from pydcop_tpu.infrastructure.agents import Agent
        from pydcop_tpu.infrastructure.computations import (
            VariableComputation,
        )
        from pydcop_tpu.dcop.objects import Domain, Variable

        agent = Agent("ui_agent", InProcessCommunicationLayer(),
                      ui_port=18765)
        try:
            v = Variable("v1", Domain("d", "", ["R", "G"]))
            comp = VariableComputation(v, None)
            agent.add_computation(comp)
            client = _WsClient(18765)
            try:
                client.send_json({"cmd": "agent"})
                reply = client.recv_json()
                assert reply["reply"] == "agent"
                assert reply["agent"] == "ui_agent"
                assert "v1" in reply["computations"]

                # Event push: a value selection lands on the socket.
                comp.value_selection("R", 0.0)
                deadline = time.time() + 5
                pushed = client.recv_json()
                assert pushed["topic"] == "computations.value.v1"
                assert pushed["data"] == ["R", 0.0]
                assert time.time() < deadline

                client.send_json(
                    {"cmd": "value", "computation": "v1"})
                reply = client.recv_json()
                assert reply["value"] == "R"
            finally:
                client.close()
        finally:
            agent.ui_server.stop()

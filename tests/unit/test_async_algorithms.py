"""True-async amaxsum / adsa agent-mode semantics.

VERDICT round-1 item 5: amaxsum must fire per message (no synchronous
mixin, no cycle barrier) and adsa must be clock-driven via periodic
actions.  These tests observe value updates and outgoing messages after
a SINGLE incoming message — no full-cycle message set anywhere.
"""

from unittest.mock import MagicMock

from pydcop_tpu.algorithms import AlgorithmDef, ComputationDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.computations_graph import factor_graph as fg
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.infrastructure.agent_algorithms import (
    ADsaComputation,
    AdsaValueMessage,
    AMaxSumFactorComputation,
    AMaxSumVariableComputation,
    MaxSumMessage,
)

d3 = Domain("d3", "", [0, 1, 2])


def _amaxsum_defs(noise=0):
    v1, v2, v3 = (Variable(n, d3) for n in ("v1", "v2", "v3"))
    c1 = constraint_from_str("c1", "abs(v1 - v2)", [v1, v2])
    c2 = constraint_from_str("c2", "abs(v1 - v3)", [v1, v3])
    graph = fg.build_computation_graph(
        variables=[v1, v2, v3], constraints=[c1, c2]
    )
    algo = AlgorithmDef.build_with_default_param(
        "amaxsum", {"noise": noise}, "min"
    )
    return {n.name: ComputationDef(n, algo) for n in graph.nodes}


class TestAsyncMaxSumVariable:
    def test_no_sync_mixin(self):
        from pydcop_tpu.infrastructure.computations import (
            SynchronousComputationMixin,
        )

        assert not issubclass(
            AMaxSumVariableComputation, SynchronousComputationMixin
        )
        assert not issubclass(
            AMaxSumFactorComputation, SynchronousComputationMixin
        )

    def test_start_sends_plain_messages(self):
        vc = AMaxSumVariableComputation(_amaxsum_defs()["v1"])
        vc._msg_sender = MagicMock()
        vc.start()
        sent = [c[0][2] for c in vc._msg_sender.call_args_list]
        assert sent, "no start messages"
        # Plain max_sum messages — NOT cycle-stamped fillers.
        assert all(m.type == "max_sum" for m in sent)

    def test_single_message_fires_update(self):
        """One factor message (of two neighbors) triggers an immediate
        value re-selection and a send to the OTHER factor — no waiting
        for the full message set."""
        vc = AMaxSumVariableComputation(_amaxsum_defs()["v1"])
        vc._msg_sender = MagicMock()
        vc.start()
        vc._msg_sender.reset_mock()
        # Strong preference for value 2 from factor c1 only.
        vc.on_message(
            "c1", MaxSumMessage({0: 100.0, 1: 100.0, 2: 0.0}), 0
        )
        assert vc.current_value == 2
        targets = [c[0][1] for c in vc._msg_sender.call_args_list]
        assert "c2" in targets


class TestAsyncMaxSumFactor:
    def test_single_message_fires_other_side(self):
        fc = AMaxSumFactorComputation(_amaxsum_defs()["c1"])
        fc._msg_sender = MagicMock()
        fc.start()
        fc._msg_sender.reset_mock()
        fc.on_message(
            "v1", MaxSumMessage({0: 0.0, 1: 50.0, 2: 50.0}), 0
        )
        targets = [c[0][1] for c in fc._msg_sender.call_args_list]
        assert "v2" in targets
        msg = next(
            c[0][2] for c in fc._msg_sender.call_args_list
            if c[0][1] == "v2"
        )
        assert msg.type == "max_sum"
        # min over v1 of |v1 - v2| + recv[v1]: for v2=0 -> 0 (v1=0).
        assert min(msg.costs.values()) == msg.costs[0]


def _adsa_comp(probability=1.0, variant="A", period=0.05):
    v1, v2, v3 = (Variable(n, d3) for n in ("v1", "v2", "v3"))
    c1 = constraint_from_str("c1", "abs(v1 - v2)", [v1, v2])
    c2 = constraint_from_str("c2", "abs(v1 - v3)", [v1, v3])
    graph = chg.build_computation_graph(
        variables=[v1, v2, v3], constraints=[c1, c2]
    )
    algo = AlgorithmDef.build_with_default_param(
        "adsa",
        {"probability": probability, "variant": variant,
         "period": period},
        "min",
    )
    node = next(n for n in graph.nodes if n.name == "v1")
    comp = ADsaComputation(ComputationDef(node, algo))
    comp._msg_sender = MagicMock()
    return comp


class TestAdsa:
    def test_clock_driven_periodic_action(self):
        comp = _adsa_comp()
        comp.start()
        assert comp._periodic_actions, "no periodic action registered"
        period, action, _guard = comp._periodic_actions[0]
        assert period == 0.05
        assert action == comp.tick

    def test_value_messages_carry_no_cycle(self):
        comp = _adsa_comp()
        comp.start()
        msg = comp._msg_sender.call_args[0][2]
        assert msg.type == "adsa_value"

    def test_tick_with_partial_knowledge_bootstraps(self):
        comp = _adsa_comp()
        comp.start()
        comp.on_message("v2", AdsaValueMessage(0), 0)
        comp._msg_sender.reset_mock()
        comp.tick()  # only one of two neighbors known: re-broadcast
        sent = [c[0][2] for c in comp._msg_sender.call_args_list]
        assert all(m.type == "adsa_value" for m in sent)
        assert comp.cycle_count == 0

    def test_tick_evaluates_with_latest_values(self):
        comp = _adsa_comp(probability=1.0, variant="A")
        comp.start()
        comp.on_message("v2", AdsaValueMessage(2), 0)
        comp.on_message("v3", AdsaValueMessage(2), 0)
        comp._msg_sender.reset_mock()
        comp.tick()
        # probability=1 and both neighbors at 2: best response is 2.
        assert comp.current_value == 2
        assert comp.cycle_count == 1
        # The move was announced without any cycle barrier.
        targets = [c[0][1] for c in comp._msg_sender.call_args_list]
        assert set(targets) <= {"v2", "v3"}

    def test_updated_value_overwrites_not_queues(self):
        """Latest neighbor value wins — no per-cycle maps."""
        comp = _adsa_comp(probability=1.0, variant="A")
        comp.start()
        comp.on_message("v2", AdsaValueMessage(0), 0)
        comp.on_message("v2", AdsaValueMessage(1), 0)
        assert comp._neighbor_values["v2"] == 1


class TestAsyncEndToEnd:
    def test_amaxsum_thread_quality(self):
        from pydcop_tpu.api import solve
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

        from fixtures_paths import local

        dcop = load_dcop_from_file(local("coloring_chain.yaml"))
        res = solve(dcop, "amaxsum", backend="thread", timeout=3)
        assert res["violations"] == 0
        # async maxsum must land on a proper coloring of the chain
        # (costs span [-0.6, 0.6] over preference ties).
        assert res["cost"] <= 0.6 + 1e-6
        assert res["msg_count"] > 0

    def test_adsa_thread_quality(self):
        from pydcop_tpu.api import solve
        from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

        from fixtures_paths import local

        dcop = load_dcop_from_file(local("coloring_chain.yaml"))
        res = solve(
            dcop, "adsa", backend="thread", timeout=10,
            algo_params={"stop_cycle": 20, "period": 0.05},
        )
        assert res["status"] == "FINISHED"
        assert res["violations"] == 0


class TestPeriodicActionSemantics:
    """Reference periodic-action semantics
    (computations.py:546-568, tests at
    test_infra_computations.py:122-278): pause suppression and
    removal-after-deployment."""

    def _agent_with(self, comp):
        from pydcop_tpu.infrastructure.agents import Agent
        from pydcop_tpu.infrastructure.communication import (
            InProcessCommunicationLayer,
        )

        agent = Agent("a", InProcessCommunicationLayer())
        agent.add_computation(comp)
        agent.start()
        agent.run()
        return agent

    def test_periodic_action_fires_on_agent_thread(self):
        import time

        from pydcop_tpu.infrastructure.computations import (
            MessagePassingComputation,
        )

        comp = MessagePassingComputation("t")
        calls = []
        comp.add_periodic_action(0.05, lambda: calls.append(1))
        agent = self._agent_with(comp)
        try:
            time.sleep(0.4)
            assert len(calls) >= 2
        finally:
            agent.stop()

    def test_periodic_action_not_called_when_paused(self):
        import time

        from pydcop_tpu.infrastructure.computations import (
            MessagePassingComputation,
        )

        comp = MessagePassingComputation("t")
        calls = []
        comp.add_periodic_action(0.05, lambda: calls.append(1))
        agent = self._agent_with(comp)
        try:
            time.sleep(0.3)
            assert calls, "action never fired while running"
            comp.pause(True)
            time.sleep(0.1)      # drain an in-flight tick
            n = len(calls)
            time.sleep(0.3)
            assert len(calls) == n, "action fired while paused"
            comp.pause(False)
            time.sleep(0.3)
            assert len(calls) > n, "action did not resume"
        finally:
            agent.stop()

    def test_remove_periodic_action_after_deployment(self):
        import time

        from pydcop_tpu.infrastructure.computations import (
            MessagePassingComputation,
        )

        comp = MessagePassingComputation("t")
        calls = []

        def action():
            calls.append(1)

        comp.add_periodic_action(0.05, action)
        agent = self._agent_with(comp)
        try:
            time.sleep(0.3)
            assert calls
            comp.remove_periodic_action(action)
            time.sleep(0.1)
            n = len(calls)
            time.sleep(0.3)
            assert len(calls) == n, "action fired after removal"
        finally:
            agent.stop()

    def test_remove_computation_stops_periodic_actions(self):
        import time

        from pydcop_tpu.infrastructure.computations import (
            MessagePassingComputation,
        )

        comp = MessagePassingComputation("t")
        calls = []
        comp.add_periodic_action(0.05, lambda: calls.append(1))
        agent = self._agent_with(comp)
        try:
            time.sleep(0.3)
            assert calls
            agent.remove_computation("t")
            time.sleep(0.1)
            n = len(calls)
            time.sleep(0.3)
            assert len(calls) == n, \
                "periodic action fired after remove_computation"
        finally:
            agent.stop()

    def test_bound_method_action_removable(self):
        """Bound methods compare equal but are not identical across
        accesses: removal must use equality."""
        from pydcop_tpu.infrastructure.computations import (
            MessagePassingComputation,
        )

        class C(MessagePassingComputation):
            def __init__(self):
                super().__init__("t")
                self.ticks = 0

            def tick(self):
                self.ticks += 1

        comp = C()
        comp.add_periodic_action(0.05, comp.tick)
        assert comp.tick is not comp._periodic_actions[0][1]
        comp.remove_periodic_action(comp.tick)
        assert not comp._periodic_actions

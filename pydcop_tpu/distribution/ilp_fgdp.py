"""ilp_fgdp: optimal ILP for factor-graph distribution, communication
cost only.

Reference parity: pydcop/distribution/ilp_fgdp.py (distribute :68,
OPTMAS-17; PuLP replaced by scipy.optimize.milp — same model).
"""

from pydcop_tpu.distribution._base import (
    distribution_cost_impl,
    ilp_place,
)


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None,
               timeout=None, **_):
    return ilp_place(
        computation_graph, agentsdef, hints,
        computation_memory, communication_load,
        timeout=timeout,
        comm_weight=1.0, hosting_weight=0.0,
    )


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    return distribution_cost_impl(
        distribution, computation_graph, agentsdef,
        computation_memory, communication_load, ratio=1.0)

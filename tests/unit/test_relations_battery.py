"""Deep battery over the constraint algebra (dcop/relations.py) —
every class and free function, including the edge cases the reference
exercises heavily (its test_dcop_relations.py has ~140 tests; this
file brings our coverage of the numeric core to comparable depth).
"""

import numpy as np
import pytest

from pydcop_tpu.dcop.objects import Domain, Variable, VariableWithCostDict
from pydcop_tpu.dcop.relations import (
    AsNAryFunctionRelation,
    ConditionalRelation,
    Constraint,
    MAX_MATERIALIZED_ELEMENTS,
    NAryFunctionRelation,
    NAryMatrixRelation,
    NeutralRelation,
    RelationProtocol,
    UnaryBooleanRelation,
    UnaryFunctionRelation,
    ZeroAryRelation,
    add_var_to_rel,
    assignment_cost,
    assignment_matrix,
    constraint_from_str,
    count_var_match,
    find_arg_optimal,
    find_optimal,
    find_optimum,
    generate_assignment,
    generate_assignment_as_dict,
    join,
    optimal_cost_value,
    projection,
)
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

d2 = Domain("d2", "", ["a", "b"])
d3 = Domain("d3", "", [0, 1, 2])
x = Variable("x", d2)
y = Variable("y", d2)
z = Variable("z", d3)


# --- ZeroAryRelation ------------------------------------------------- #

class TestZeroAry:
    def test_value(self):
        r = ZeroAryRelation("k", 7.5)
        assert r() == 7.5

    def test_arity_and_dims(self):
        r = ZeroAryRelation("k", 1)
        assert r.arity == 0
        assert r.dimensions == []
        assert r.scope_names == []

    def test_to_array_scalar(self):
        arr = ZeroAryRelation("k", 3).to_array()
        assert arr.shape == ()
        assert float(arr) == 3

    def test_shape_empty(self):
        assert ZeroAryRelation("k", 1).shape == ()


# --- UnaryFunctionRelation ------------------------------------------- #

class TestUnaryFunction:
    def test_callable(self):
        r = UnaryFunctionRelation("u", z, lambda v: v * 10)
        assert r(2) == 20

    def test_kwargs_call(self):
        r = UnaryFunctionRelation("u", z, lambda v: v + 1)
        assert r(z=1) == 2

    def test_expression_string(self):
        r = UnaryFunctionRelation("u", z, "z ** 2")
        assert r(2) == 4
        assert r.expression == "z ** 2"

    def test_expression_none_for_callable(self):
        r = UnaryFunctionRelation("u", z, lambda v: v)
        assert r.expression is None

    def test_variable_property(self):
        r = UnaryFunctionRelation("u", z, lambda v: v)
        assert r.variable is z

    def test_to_array(self):
        r = UnaryFunctionRelation("u", z, lambda v: v * 2)
        np.testing.assert_array_equal(r.to_array(), [0, 2, 4])

    def test_get_value_for_assignment_dict_and_list(self):
        r = UnaryFunctionRelation("u", z, lambda v: v + 5)
        assert r.get_value_for_assignment({"z": 1}) == 6
        assert r.get_value_for_assignment([2]) == 7


class TestUnaryBoolean:
    def test_truthy(self):
        r = UnaryBooleanRelation("b", z)
        assert r(0) == 0
        assert r(1) == 1
        assert r(2) == 1

    def test_kwargs(self):
        r = UnaryBooleanRelation("b", z)
        assert r(z=0) == 0


# --- NAryFunctionRelation -------------------------------------------- #

class TestNAryFunction:
    def test_positional(self):
        r = NAryFunctionRelation(lambda a, b: a + b, [z, z2()], "s")
        assert r(1, 2) == 3

    def test_keyword(self):
        r = NAryFunctionRelation(
            lambda a, b: a - b, [Variable("a", d3), Variable("b", d3)],
            "s")
        assert r(a=2, b=1) == 1

    def test_expression(self):
        r = NAryFunctionRelation("x1 + 2 * x2",
                                 [Variable("x1", d3), Variable("x2", d3)])
        assert r(1, 2) == 5

    def test_expression_dims_order_from_ctor(self):
        v1, v2 = Variable("x1", d3), Variable("x2", d3)
        r = NAryFunctionRelation("x2 - x1", [v1, v2])
        # positional args follow the ctor's variable order
        assert r(2, 0) == -2

    def test_slice_expression(self):
        v1, v2 = Variable("x1", d3), Variable("x2", d3)
        r = NAryFunctionRelation("x1 * 10 + x2", [v1, v2], name="e")
        s = r.slice({"x1": 2})
        assert s.arity == 1
        assert s.scope_names == ["x2"]
        assert s(1) == 21

    def test_slice_callable(self):
        v1, v2 = Variable("x1", d3), Variable("x2", d3)
        r = NAryFunctionRelation(lambda x1, x2: x1 * 10 + x2, [v1, v2],
                                 name="c")
        s = r.slice({"x2": 1})
        assert s.scope_names == ["x1"]
        assert s(2) == 21

    def test_function_property(self):
        f = lambda a: a  # noqa: E731
        r = NAryFunctionRelation(f, [z], "n")
        assert r.function is f

    def test_wire_roundtrip_expression(self):
        v1, v2 = Variable("x1", d3), Variable("x2", d3)
        r = NAryFunctionRelation("x1 + x2", [v1, v2], name="w")
        r2 = from_repr(simple_repr(r))
        assert r2(1, 2) == 3
        assert r2.name == "w"
        assert r2.scope_names == ["x1", "x2"]

    def test_decorator(self):
        @AsNAryFunctionRelation(z)
        def my_rel(zv):
            return zv * 3

        assert my_rel.name == "my_rel"
        assert my_rel(2) == 6
        assert my_rel.arity == 1


def z2():
    return Variable("z2", d3)


# --- NAryMatrixRelation ---------------------------------------------- #

class TestMatrixRelation:
    def test_default_zero_matrix(self):
        r = NAryMatrixRelation([x, y])
        assert r("a", "b") == 0.0
        assert r.matrix.shape == (2, 2)

    def test_lookup_order(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        assert r(x="a", y="b") == 2.0
        assert r(x="b", y="a") == 3.0

    def test_positional_call(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        assert r("b", "b") == 4.0

    def test_shape_mismatch_raises(self):
        with pytest.raises(ValueError, match="does not match"):
            NAryMatrixRelation([x, y], np.zeros((2, 3)), "bad")

    def test_get_value_for_assignment_list(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        assert r.get_value_for_assignment(["a", "b"]) == 2.0

    def test_set_value_immutable(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        r2 = r.set_value_for_assignment({"x": "a", "y": "a"}, 9)
        assert r2("a", "a") == 9.0
        assert r("a", "a") == 1.0  # original untouched
        assert r2.name == r.name

    def test_slice_to_unary(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        s = r.slice({"x": "b"})
        assert s.arity == 1
        assert s.scope_names == ["y"]
        np.testing.assert_array_equal(s.matrix, [3, 4])

    def test_slice_empty_partial_is_identity(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        s = r.slice({})
        assert s.scope_names == ["x", "y"]
        np.testing.assert_array_equal(s.matrix, r.matrix)

    def test_slice_all_gives_zero_ary_matrix(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        s = r.slice({"x": "a", "y": "b"})
        assert s.arity == 0
        assert float(s.matrix) == 2.0

    def test_from_func_relation(self):
        f = NAryFunctionRelation("x1 + x2",
                                 [Variable("x1", d3), Variable("x2", d3)],
                                 name="f")
        m = NAryMatrixRelation.from_func_relation(f)
        assert isinstance(m, NAryMatrixRelation)
        assert m.name == "f"
        assert m(2, 2) == 4.0

    def test_equality_includes_matrix(self):
        a = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        b = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        c = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 5]]), "m")
        assert a == b
        assert a != c

    def test_wire_roundtrip(self):
        r = NAryMatrixRelation([x, z], np.arange(6).reshape(2, 3), "w")
        r2 = from_repr(simple_repr(r))
        assert r2 == r
        assert r2(x="b", z=2) == 5.0

    def test_3d_matrix(self):
        w = Variable("w", d2)
        m = np.arange(8).reshape(2, 2, 2)
        r = NAryMatrixRelation([x, y, w], m, "cube")
        assert r("b", "a", "b") == 5.0
        assert r.shape == (2, 2, 2)


# --- Neutral / Conditional ------------------------------------------- #

class TestNeutralConditional:
    def test_neutral_zero_everywhere(self):
        r = NeutralRelation([x, y])
        for a in generate_assignment_as_dict([x, y]):
            assert r(**a) == 0

    def test_neutral_is_join_identity(self):
        m = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        j = join(m, NeutralRelation([x, y]))
        np.testing.assert_array_equal(j.matrix, m.matrix)

    def test_conditional_applies_when_true(self):
        cond = UnaryBooleanRelation("c", z)
        rel = UnaryFunctionRelation("u", z, lambda v: v * 10)
        r = ConditionalRelation(cond, rel)
        assert r(z=2) == 20
        assert r(z=0) == 0   # condition falsy -> default

    def test_conditional_custom_default(self):
        cond = UnaryBooleanRelation("c", z)
        rel = UnaryFunctionRelation("u", z, lambda v: v)
        r = ConditionalRelation(cond, rel, return_default=99)
        assert r(z=0) == 99

    def test_conditional_dims_union(self):
        cond = UnaryBooleanRelation("c", z)
        rel = NAryFunctionRelation(
            "x1 + z", [Variable("x1", d3), z])
        r = ConditionalRelation(cond, rel)
        assert set(r.scope_names) == {"z", "x1"}
        # z appears once even though it is in both scopes
        assert len(r.scope_names) == 2

    def test_condition_and_relation_properties(self):
        cond = UnaryBooleanRelation("c", z)
        rel = UnaryFunctionRelation("u", z, lambda v: v)
        r = ConditionalRelation(cond, rel)
        assert r.condition is cond
        assert r.relation is rel


# --- constraint_from_str / base class -------------------------------- #

class TestFromStr:
    def test_dims_are_free_names(self):
        r = constraint_from_str("c", "x1 + x2", [
            Variable("x1", d3), Variable("x2", d3), Variable("x3", d3)])
        assert set(r.scope_names) == {"x1", "x2"}

    def test_unknown_variable_raises(self):
        with pytest.raises(ValueError, match="Unknown variable"):
            constraint_from_str("c", "x1 + nope", [Variable("x1", d3)])

    def test_builtins_allowed(self):
        r = constraint_from_str("c", "abs(x1 - 2)", [Variable("x1", d3)])
        assert r(0) == 2

    def test_constant_expression_zero_arity(self):
        r = constraint_from_str("c", "42", [Variable("x1", d3)])
        assert r.arity == 0
        assert r() == 42

    def test_relation_protocol_alias(self):
        assert RelationProtocol is Constraint

    def test_materialization_cap(self):
        big = Domain("big", "", list(range(300)))
        vs = [Variable(f"v{i}", big) for i in range(4)]
        r = NAryFunctionRelation(lambda **kw: 0, vs, "huge",
                                 f_kwargs=True)
        assert int(np.prod(r.shape)) > MAX_MATERIALIZED_ELEMENTS
        with pytest.raises(MemoryError, match="Refusing"):
            r.to_array()

    def test_base_slice_freezes_values(self):
        r = constraint_from_str("c", "x1 * 10 + x2", [
            Variable("x1", d3), Variable("x2", d3)])
        s = r.slice({"x1": 1})
        assert s(2) == 12


# --- free functions -------------------------------------------------- #

class TestAssignments:
    def test_assignment_matrix_default(self):
        m = assignment_matrix([x, z], 5)
        assert m.shape == (2, 3)
        assert (m == 5).all()

    def test_generate_assignment_order(self):
        combos = list(generate_assignment([x, z]))
        # last variable iterates fastest
        assert combos[0] == ["a", 0]
        assert combos[1] == ["a", 1]
        assert combos[3] == ["b", 0]
        assert len(combos) == 6

    def test_generate_assignment_as_dict(self):
        first = next(generate_assignment_as_dict([x, y]))
        assert first == {"x": "a", "y": "a"}

    def test_count_var_match(self):
        r = NAryMatrixRelation([x, y], name="m")
        assert count_var_match(["x", "z", "y"], r) == 2
        assert count_var_match(["nope"], r) == 0

    def test_assignment_cost_sums(self):
        r1 = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "a")
        r2 = UnaryFunctionRelation("b", z, lambda v: v)
        cost = assignment_cost({"x": "b", "y": "a", "z": 2}, [r1, r2])
        assert cost == 5

    def test_assignment_cost_hard_violation_raises(self):
        r = UnaryFunctionRelation("h", z, lambda v: float("inf"))
        with pytest.raises(ValueError, match="Hard constraint"):
            assignment_cost({"z": 0}, [r])


class TestOptima:
    def test_find_optimum_min_max(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m")
        assert find_optimum(r, "min") == 1.0
        assert find_optimum(r, "max") == 4.0

    def test_find_arg_optimal_single(self):
        r = UnaryFunctionRelation("u", z, lambda v: (v - 1) ** 2)
        vals, cost = find_arg_optimal(z, r, "min")
        assert vals == [1]
        assert cost == 0.0

    def test_find_arg_optimal_ties_in_domain_order(self):
        r = UnaryFunctionRelation("u", z, lambda v: 0 if v != 1 else 9)
        vals, cost = find_arg_optimal(z, r, "min")
        assert vals == [0, 2]   # domain order preserved
        assert cost == 0.0

    def test_find_arg_optimal_max(self):
        r = UnaryFunctionRelation("u", z, lambda v: v)
        vals, cost = find_arg_optimal(z, r, "max")
        assert vals == [2] and cost == 2.0

    def test_find_arg_optimal_rejects_binary(self):
        r = NAryMatrixRelation([x, y], name="m")
        with pytest.raises(ValueError, match="unary"):
            find_arg_optimal(x, r, "min")

    def test_find_optimal_with_context(self):
        r = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 0]]), "m")
        vals, cost = find_optimal(y, {"x": "b"}, [r], "min")
        assert vals == ["b"] and cost == 0

    def test_find_optimal_ties(self):
        r = NAryMatrixRelation([x, y], np.array([[5, 5], [1, 2]]), "m")
        vals, cost = find_optimal(y, {"x": "a"}, [r], "min")
        assert vals == ["a", "b"] and cost == 5

    def test_optimal_cost_value(self):
        v = VariableWithCostDict(
            "v", d3, {0: 3.0, 1: 0.5, 2: 2.0})
        assert optimal_cost_value(v, "min") == (1, 0.5)
        assert optimal_cost_value(v, "max") == (0, 3.0)


class TestJoinProjection:
    def test_join_disjoint_dims(self):
        r1 = UnaryFunctionRelation("a", x, lambda v: 1 if v == "a" else 2)
        r2 = UnaryFunctionRelation("b", z, lambda v: v)
        j = join(r1, r2)
        assert j.scope_names == ["x", "z"]
        assert j(x="b", z=2) == 4.0

    def test_join_shared_dim(self):
        m1 = NAryMatrixRelation([x, y], np.array([[1, 2], [3, 4]]), "m1")
        m2 = NAryMatrixRelation([y], np.array([10, 20]), "m2")
        j = join(m1, m2)
        assert j.scope_names == ["x", "y"]
        assert j(x="a", y="b") == 22.0

    def test_join_identical_scope(self):
        m1 = NAryMatrixRelation([x, y], np.ones((2, 2)), "m1")
        m2 = NAryMatrixRelation([x, y], 2 * np.ones((2, 2)), "m2")
        j = join(m1, m2)
        assert (j.matrix == 3).all()

    def test_join_respects_axis_order(self):
        # m2's dims are reversed relative to m1: values must still line
        # up per-assignment, not per-axis-position.
        a = np.array([[1, 2], [3, 4]])
        m1 = NAryMatrixRelation([x, y], a, "m1")
        m2 = NAryMatrixRelation([y, x], a.T, "m2")
        j = join(m1, m2)
        for asst in generate_assignment_as_dict([x, y]):
            assert j(**asst) == 2 * m1(**asst)

    def test_join_with_zero_ary(self):
        m = NAryMatrixRelation([x], np.array([1, 2]), "m")
        k = ZeroAryRelation("k", 10)
        j = join(m, k)
        np.testing.assert_array_equal(j.matrix, [11, 12])

    def test_projection_min_eliminates_axis(self):
        m = NAryMatrixRelation([x, y], np.array([[1, 5], [4, 2]]), "m")
        p = projection(m, y, "min")
        assert p.scope_names == ["x"]
        np.testing.assert_array_equal(p.matrix, [1, 2])

    def test_projection_max(self):
        m = NAryMatrixRelation([x, y], np.array([[1, 5], [4, 2]]), "m")
        p = projection(m, x, "max")
        np.testing.assert_array_equal(p.matrix, [4, 5])

    def test_projection_missing_variable_raises(self):
        m = NAryMatrixRelation([x], np.array([1, 2]), "m")
        with pytest.raises(ValueError, match="not in dimensions"):
            projection(m, z)

    def test_projection_to_zero_ary(self):
        m = NAryMatrixRelation([x], np.array([3, 1]), "m")
        p = projection(m, x, "min")
        assert p.arity == 0
        assert float(p.matrix) == 1.0

    def test_dpop_identity_join_then_project(self):
        # min_y (m1 + m2) computed via the algebra equals the direct
        # enumeration — the invariant DPOP's UTIL messages rely on.
        m1 = NAryMatrixRelation([x, y], np.array([[1, 5], [4, 2]]), "m1")
        m2 = NAryMatrixRelation([y, z],
                                np.arange(6).reshape(2, 3), "m2")
        p = projection(join(m1, m2), y, "min")
        for asst in generate_assignment_as_dict([x, z]):
            direct = min(
                m1(x=asst["x"], y=vy) + m2(y=vy, z=asst["z"])
                for vy in y.domain
            )
            assert p(**asst) == direct

    def test_add_var_to_rel(self):
        m = NAryMatrixRelation([x], np.array([1, 2]), "m")
        r = add_var_to_rel("ext", m, z, lambda rel_cost, vz: rel_cost + vz)
        assert r.scope_names == ["x", "z"]
        assert r(x="b", z=2) == 4

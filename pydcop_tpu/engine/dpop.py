"""Exact-inference engine tier: DPOP through the runner seam.

``ops/dpop.solve_sweep`` owns the algorithm (level-batched UTIL kernels,
host VALUE sweep); this module owns the *accounting*: every device
dispatch is routed through :func:`engine.runner.timed_jit_call` so the
tracer, the metrics registry, the efficiency tracker and the AOT disk
cache see exact solves through the same chokepoint as every iterative
engine, and the result comes back as a :class:`DeviceRunResult` with the
overlapping compile/run timing convention the serving ledgers expect.

Width policy lives here too: :func:`dpop_feasibility` answers "is exact
inference affordable on this pseudo-tree" (optionally after CEC
shrinkage) without materializing a single table — the portfolio racer,
the serve-plane admission check and the session oracle all gate on it.
"""

import time
from typing import Any, Dict, Optional

from pydcop_tpu.engine.runner import DeviceRunResult, timed_jit_call
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.ops import dpop as dpop_ops


def dpop_feasibility(graph, mode: str = "min", cec: bool = True,
                     max_elements: Optional[int] = None
                     ) -> Dict[str, Any]:
    """Width feasibility verdict for exact inference on ``graph``.

    Returns the raw :func:`ops.dpop.tree_stats` counters plus
    ``{"feasible", "max_elements_cap", "cec_max_elements"}``.  When the
    raw hypercubes bust the cap and ``cec`` is allowed, the CEC-shrunk
    sizes are tried before giving up — pruning is exactly how the width
    ceiling rises.  Never raises: infeasible is a verdict, not an error.
    """
    cap = dpop_ops.MAX_NODE_ELEMENTS if max_elements is None \
        else int(max_elements)
    stats = dpop_ops.tree_stats(graph)
    out: Dict[str, Any] = dict(stats)
    out["max_elements_cap"] = cap
    out["cec_max_elements"] = None
    if stats["max_elements"] <= cap:
        out["feasible"] = True
        return out
    if cec:
        try:
            survivors, _ = dpop_ops.cec_survivors(graph, mode)
            shrunk = dpop_ops.tree_stats(graph, survivors)
            out["cec_max_elements"] = shrunk["max_elements"]
            out["feasible"] = shrunk["max_elements"] <= cap
            return out
        except Exception:  # noqa: BLE001 — verdict, not error
            pass
    out["feasible"] = False
    return out


class DpopEngine:
    """One exact solve of a compiled pseudo-tree, fully accounted.

    Unlike the iterative engines there is no cycle budget to resume —
    ``run`` ignores ``max_cycles`` and always sweeps to the optimum (or
    raises :class:`ops.dpop.UtilTooLargeError` when a UTIL hypercube,
    even CEC-shrunk, busts ``MAX_NODE_ELEMENTS``).  The warm-key set
    persists across ``run`` calls, so repeat solves of same-signature
    structures (serving bins, the session oracle re-certifying after
    each quiescence) hit compiled kernels.
    """

    def __init__(self, graph, mode: str = "min", cec: bool = True,
                 warm: Optional[set] = None):
        self.graph = graph
        self.mode = mode
        self.cec = cec
        self.efficiency_class = "dpop"
        # Callers that solve many same-shaped problems (the serving
        # dispatch plane) pass a shared warm-key set so signature-bucket
        # kernels compiled for one request are warm for the next.
        self._warm: set = warm if warm is not None else set()
        self._survivors = None  # cached cec_survivors result
        self.last_stats: Dict[str, Any] = {}

    def _call(self, key, fn, *args):
        out, compile_s, run_s = timed_jit_call(self._warm, key, fn, *args)
        self._compile_s += compile_s
        self._run_s += run_s
        return out

    def run(self, max_cycles: Optional[int] = None) -> DeviceRunResult:
        del max_cycles  # exact: no budget, sweeps to the optimum
        t0 = time.perf_counter()
        self._compile_s = 0.0
        self._run_s = 0.0
        if self.cec and self._survivors is None:
            # The dominance pass only depends on the (static) problem;
            # repeat solves — the portfolio race's timed leg, serving
            # bins, the session oracle — reuse it.
            self._survivors = dpop_ops.cec_survivors(
                self.graph, self.mode)
        kwargs = dict(
            mode=self.mode, cec=self.cec, call=self._call,
            precomputed_survivors=self._survivors,
        )
        if tracer.enabled:
            with tracer.span("dpop_sweep", "engine", mode=self.mode,
                             cec=self.cec):
                assignment, stats = dpop_ops.solve_sweep(
                    self.graph, **kwargs)
        else:
            assignment, stats = dpop_ops.solve_sweep(
                self.graph, **kwargs)
        elapsed = time.perf_counter() - t0
        self.last_stats = dict(stats)
        metrics = dict(stats)
        metrics["engine"] = "dpop"
        metrics["optimal"] = True
        metrics["cold_start"] = self._compile_s > 0.0
        return DeviceRunResult(
            assignment=assignment,
            cycles=stats["levels"],
            converged=True,
            time_s=elapsed,
            compile_time_s=min(self._compile_s, elapsed),
            metrics=metrics,
        )

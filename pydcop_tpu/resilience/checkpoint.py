"""Engine checkpoint/resume: NPZ snapshots of device-resident state.

A long on-device solve on a preemptible slice dies with zero recovery
when the whole solve is one uninterruptible XLA program.  The engine
side (``MaxSumEngine.run_checkpointed``) chunks the jitted loop into
K-cycle segments and calls a :class:`CheckpointManager` between
segments; this module owns the on-disk format and the resume entry
point.  Because the superstep is deterministic and segment boundaries
re-enter ``run_maxsum_from`` with the exact device state, a resumed
solve reproduces the uninterrupted trajectory bit-for-bit (asserted in
tests/unit/test_resilience_battery.py).

Format: one ``ckpt_<cycle>.npz`` per snapshot — flattened state leaves
(``leaf_<i>``) + a JSON metadata blob (version, cycle, leaf count,
engine tag).  Writes are atomic (tmp + ``os.replace``) so a crash
mid-write never corrupts the latest good snapshot, and ``latest()``
skips unreadable files.  :class:`AsyncCheckpointWriter` moves the
device→host fetch and the write onto a background thread (bounded
queue, flush-on-exit, same atomic format) so snapshotting overlaps
device compute — the engine's default checkpoint path.  The state's pytree *structure* is not stored:
restore goes through a template state built from the same compiled
graph, which also re-applies the template's device/sharding placement
(checkpoints taken on a mesh restore onto a mesh).
"""

import atexit
import json
import logging
import os
import queue
import re
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("pydcop.resilience.checkpoint")

CHECKPOINT_VERSION = 1
_CKPT_RE = re.compile(r"^ckpt_(\d+)\.npz$")


def save_state(path: str, state: Any, *, cycle: int,
               extra: Optional[Dict[str, Any]] = None) -> str:
    """Atomically write a state pytree to ``path`` (.npz)."""
    from pydcop_tpu.observability.trace import tracer

    if tracer.enabled:
        with tracer.span("checkpoint_write", "resilience",
                         path=path, cycle=int(cycle)):
            return _save_state(path, state, cycle=cycle, extra=extra)
    return _save_state(path, state, cycle=cycle, extra=extra)


def _save_state(path: str, state: Any, *, cycle: int,
                extra: Optional[Dict[str, Any]] = None) -> str:
    import jax

    from pydcop_tpu.observability.metrics import registry

    t0 = time.perf_counter()
    leaves = jax.tree_util.tree_leaves(state)
    arrays = {
        f"leaf_{i}": np.asarray(jax.device_get(leaf))
        for i, leaf in enumerate(leaves)
    }
    meta = {
        "version": CHECKPOINT_VERSION,
        "cycle": int(cycle),
        "n_leaves": len(leaves),
        "extra": extra or {},
    }
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=".ckpt_tmp_", suffix=".npz"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta), **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    registry.counter(
        "pydcop_checkpoints_total", "Checkpoint snapshots written"
    ).inc()
    if registry.active:
        registry.histogram(
            "pydcop_checkpoint_write_seconds",
            "Wall seconds per checkpoint write",
        ).observe(time.perf_counter() - t0)
    return path


def read_meta(path: str) -> Dict[str, Any]:
    with np.load(path, allow_pickle=False) as data:
        return json.loads(str(data["__meta__"]))


def load_state(path: str, template: Any) -> Tuple[Any, Dict[str, Any]]:
    """Load a snapshot back into ``template``'s pytree structure and
    device placement.  Returns ``(state, meta)``."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
        if meta.get("version") != CHECKPOINT_VERSION:
            raise ValueError(
                f"Checkpoint {path} has version {meta.get('version')}; "
                f"this build reads version {CHECKPOINT_VERSION}"
            )
        if meta["n_leaves"] != len(leaves):
            raise ValueError(
                f"Checkpoint {path} has {meta['n_leaves']} leaves but "
                f"the engine state has {len(leaves)}: wrong problem or "
                "engine configuration"
            )
        loaded = [data[f"leaf_{i}"] for i in range(len(leaves))]
    placed = []
    for arr, ref in zip(loaded, leaves):
        if arr.shape != ref.shape:
            raise ValueError(
                f"Checkpoint {path} leaf shape {arr.shape} != engine "
                f"state shape {ref.shape}: wrong problem"
            )
        sharding = getattr(ref, "sharding", None)
        placed.append(
            jax.device_put(arr.astype(ref.dtype), sharding)
            if sharding is not None else jax.device_put(arr)
        )
    return jax.tree_util.tree_unflatten(treedef, placed), meta


class CheckpointManager:
    """Cadence + retention over a checkpoint directory.

    ``every`` is the segment length in cycles (the engine snapshots at
    each segment boundary); ``keep`` bounds how many snapshots stay on
    disk (oldest pruned first — the latest good one is never pruned).
    """

    def __init__(self, directory: str, every: int = 100, keep: int = 2):
        if every <= 0:
            raise ValueError(f"checkpoint cadence must be > 0: {every}")
        if keep < 1:
            raise ValueError(f"must keep at least 1 checkpoint: {keep}")
        self.directory = directory
        self.every = every
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def path_for(self, cycle: int) -> str:
        return os.path.join(self.directory, f"ckpt_{int(cycle)}.npz")

    def checkpoints(self) -> List[Tuple[int, str]]:
        """(cycle, path) pairs present on disk, oldest first."""
        found = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                found.append(
                    (int(m.group(1)),
                     os.path.join(self.directory, name))
                )
        return sorted(found)

    def latest(self) -> Optional[str]:
        """Path of the newest READABLE checkpoint (corrupt/partial
        files — e.g. from a crash predating the atomic rename — are
        skipped with a warning)."""
        for cycle, path in reversed(self.checkpoints()):
            try:
                read_meta(path)
                return path
            except Exception as e:
                logger.warning(
                    "Skipping unreadable checkpoint %s: %s", path, e
                )
        return None

    def save(self, state: Any, cycle: int,
             extra: Optional[Dict[str, Any]] = None) -> str:
        path = save_state(
            self.path_for(cycle), state, cycle=cycle, extra=extra
        )
        self._prune()
        return path

    def _prune(self):
        existing = self.checkpoints()
        for _, path in existing[:-self.keep]:
            try:
                os.unlink(path)
            except OSError:
                pass


class AsyncCheckpointWriter:
    """Background device→host fetch + atomic NPZ write.

    The synchronous ``CheckpointManager.save`` puts a full host sync
    and a file write on the solve's critical path every segment; this
    writer moves BOTH off it.  ``submit`` enqueues the state pytree
    and returns immediately; a single daemon thread fetches the leaves
    (``jax.device_get`` blocks there, overlapping the next segment's
    device compute) and reuses the crash-safe temp-then-rename write
    (:func:`_save_state`), then applies the manager's retention
    pruning.  Each write runs inside the tracer's ``checkpoint_write``
    span ON THE WRITER THREAD, so a trace of an async-checkpointed run
    shows those spans concurrent with ``engine_segment`` — the
    overlap proof the tier-1 battery asserts.

    Contract:

    - the submitted state must stay valid until written: callers that
      donate their state buffers hand a device-side copy instead
      (``MaxSumEngine.run_checkpointed`` does);
    - the queue is bounded (``maxsize``): if writes fall behind, the
      engine loop blocks on ``submit`` rather than buying unbounded
      host memory — backpressure, not a crash;
    - ``close`` drains the queue and joins the thread (also registered
      ``atexit`` so an abandoned writer still flushes);
    - a write failure is re-raised on the NEXT ``submit``/``flush``/
      ``close`` — never swallowed, never crashing the writer thread.
    """

    def __init__(self, manager: "CheckpointManager", maxsize: int = 2):
        self._manager = manager
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="pydcop-ckpt-writer", daemon=True
        )
        self._thread.start()
        atexit.register(self.close)

    def _run(self):
        import jax

        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            state, cycle, extra = item
            try:
                cycle = int(np.asarray(jax.device_get(cycle)))
                save_state(
                    self._manager.path_for(cycle), state,
                    cycle=cycle, extra=extra,
                )
                self._manager._prune()
            except BaseException as exc:  # noqa: BLE001 - reraised
                self._error = exc
            finally:
                self._q.task_done()

    def _raise_pending(self):
        if self._error is not None:
            exc, self._error = self._error, None
            raise RuntimeError(
                "async checkpoint write failed"
            ) from exc

    def submit(self, state: Any, cycle,
               extra: Optional[Dict[str, Any]] = None) -> None:
        """Enqueue one snapshot.  ``cycle`` may be a device scalar —
        even that fetch happens on the writer thread."""
        if self._closed:
            raise RuntimeError("writer is closed")
        self._raise_pending()
        self._q.put((state, cycle, extra))

    def flush(self) -> None:
        """Block until every submitted snapshot is on disk."""
        self._q.join()
        self._raise_pending()

    def close(self) -> None:
        """Flush, stop the thread and surface any pending error."""
        if self._closed:
            return
        self._closed = True
        try:
            self._q.join()
            self._q.put(None)
            self._thread.join()
        finally:
            try:
                atexit.unregister(self.close)
            except Exception:  # pragma: no cover - interpreter exit
                pass
        self._raise_pending()


def resume_from_checkpoint(engine, manager, max_cycles: int = 1000,
                           **run_kwargs):
    """Continue an interrupted checkpointed solve.

    ``manager`` is a :class:`CheckpointManager` or a directory path.
    Loads the newest readable snapshot, restores it into the engine's
    state structure (and device placement) and re-enters the segmented
    loop; with no snapshot on disk the solve simply starts from cycle
    0 — so preemptible deployments can always launch through this one
    entry point.  Returns the engine's ``DeviceRunResult``; determinism
    with the uninterrupted run is covered by the tier-1 battery.
    """
    if isinstance(manager, str):
        manager = CheckpointManager(manager)
    path = manager.latest()
    initial_state = None
    resumed_cycle = 0
    if path is not None:
        initial_state, meta = load_state(path, engine.init_state())
        resumed_cycle = meta["cycle"]
        logger.info(
            "Resuming from %s (cycle %d)", path, resumed_cycle
        )
    result = engine.run_checkpointed(
        max_cycles=max_cycles, manager=manager,
        initial_state=initial_state, **run_kwargs,
    )
    result.metrics["resumed_from_cycle"] = resumed_cycle
    return result

"""MaxSum message-update kernels: one BSP superstep as pure JAX.

Semantics mirror the reference algorithm exactly (factor update:
pydcop/algorithms/maxsum.py:382 factor_costs_for_var; variable update:
:623 costs_for_factor with mean-normalization :670-674; damping :679;
convergence test :688 approx_match), but batched:

- factor→variable: per arity-bucket, ``total = costs + Σ_q bcast(m_q)``
  then for each position p ``min`` over all axes except p minus ``m_p``
  (m_p is constant along the reduced axes, so subtracting it after the
  reduction equals excluding it before) — one batched reduction instead
  of a python loop over d^arity assignments;
- variable→factor: segment-sum of incoming messages over the bucket var
  indices, per-slot "subtract own contribution", mean-normalized over
  valid domain slots, damped;
- value selection: argmin of (own costs + message sums) masked to valid
  slots; argmin's lowest-index tie-break reproduces the reference's
  first-optimum ordering (maxsum.py:584 select_value iterates the domain
  in order).

Messages live in bucket space ([F, arity, D] per bucket): factor updates
touch only local rows, and the single segment-sum is the only op that
crosses shards when buckets are sharded over a mesh (one all-reduce of
the [V+1, D] totals per superstep).

All kernels minimize; `objective=max` problems are negated at compile
time (see engine.compile).

Pallas note: a hand-written Pallas kernel for the binary-factor update
(blocking F onto lanes, one fused min-reduce pass) was prototyped and
measured on a v5e chip at parity with XLA's fusion of this code
(~0.26-0.34 ms/superstep on the 15k-factor benchmark, both ways) —
the op mix here is gather/scatter + tiny-minor-dim elementwise, which
Mosaic cannot schedule better than XLA does.  The XLA path is kept;
revisit Pallas if a future problem shape makes the factor update
reduction-bound (large arity/domains) rather than dispatch-bound.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import BIG, CompiledFactorGraph

Msgs = Tuple[jnp.ndarray, ...]  # one [F, arity, D] array per bucket


class MaxSumState(NamedTuple):
    v2f: Msgs            # variable -> factor messages
    f2v: Msgs            # factor -> variable messages
    stable: jnp.ndarray  # scalar bool: all messages approx-matched
    cycle: jnp.ndarray   # scalar int32


def init_state(graph: CompiledFactorGraph) -> MaxSumState:
    d = graph.var_costs.shape[1]
    dtype = graph.var_costs.dtype
    zeros = tuple(
        jnp.zeros(b.var_ids.shape + (d,), dtype=dtype)
        for b in graph.buckets
    )
    return MaxSumState(
        v2f=zeros,
        f2v=zeros,
        stable=jnp.asarray(False),
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def _all_match(new: Msgs, old: Msgs, stability: float,
               valids: Msgs) -> jnp.ndarray:
    """Reference approx_match (maxsum.py:688): relative change
    2|Δ|/|a+b| below `stability` (exact equality always matches).
    Slots outside `valids` (domain padding, sentinel padding rows) are
    ignored so device padding cannot delay convergence."""
    oks = []
    for n, o, valid in zip(new, old, valids):
        delta = jnp.abs(n - o)
        s = jnp.abs(n + o)
        ok = (delta == 0) | ((s != 0) & (2 * delta < stability * s))
        oks.append(jnp.all(ok | ~valid))
    if not oks:
        return jnp.asarray(True)
    out = oks[0]
    for ok in oks[1:]:
        out = out & ok
    return out


def factor_to_var(graph: CompiledFactorGraph, v2f: Msgs) -> Msgs:
    """All factor→variable messages for one superstep."""
    out = []
    for bucket, msgs in zip(graph.buckets, v2f):
        f, arity, d = msgs.shape
        total = bucket.costs  # [F, D, ..., D]
        for q in range(arity):
            shape = [f] + [1] * arity
            shape[q + 1] = d
            total = total + msgs[:, q].reshape(shape)
        outs_p = []
        for p in range(arity):
            axes = tuple(i + 1 for i in range(arity) if i != p)
            reduced = jnp.min(total, axis=axes) if axes else total
            outs_p.append(reduced - msgs[:, p])
        out.append(jnp.stack(outs_p, axis=1))  # [F, arity, D]
    return tuple(out)


def aggregate_beliefs(graph: CompiledFactorGraph, f2v: Msgs
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sum incoming factor messages per variable.

    Returns (beliefs [V+1, D] = own costs + sums, sums [V+1, D]).
    This segment-sum is the single cross-shard op per superstep.
    """
    n_segments = graph.var_costs.shape[0]
    d = graph.var_costs.shape[1]
    sums = jnp.zeros_like(graph.var_costs)
    for bucket, msgs in zip(graph.buckets, f2v):
        flat = msgs.reshape(-1, d)
        seg = bucket.var_ids.reshape(-1)
        sums = sums + jax.ops.segment_sum(
            flat, seg, num_segments=n_segments
        )
    return graph.var_costs + sums, sums


def var_to_factor(graph: CompiledFactorGraph, f2v: Msgs,
                  beliefs: jnp.ndarray, sums: jnp.ndarray) -> Msgs:
    """All variable→factor messages: belief minus own contribution,
    mean-normalized over valid slots (reference maxsum.py:670-674)."""
    out = []
    for bucket, msgs in zip(graph.buckets, f2v):
        valid = graph.var_valid[bucket.var_ids]        # [F, a, D]
        raw = beliefs[bucket.var_ids] - msgs           # own cost + others
        factor_sum = sums[bucket.var_ids] - msgs       # others only
        n_valid = jnp.maximum(
            jnp.sum(valid, axis=-1, keepdims=True), 1
        )
        avg = (
            jnp.sum(jnp.where(valid, factor_sum, 0.0), axis=-1,
                    keepdims=True)
            / n_valid
        )
        out.append(jnp.where(valid, raw - avg, BIG))
    return tuple(out)


def select_values(graph: CompiledFactorGraph,
                  beliefs: jnp.ndarray) -> jnp.ndarray:
    """Per-variable argmin of belief over valid slots ([V] int32)."""
    masked = jnp.where(graph.var_valid, beliefs, jnp.inf)
    return jnp.argmin(masked[:-1], axis=1).astype(jnp.int32)


def _damp(new: Msgs, old: Msgs, damping: float,
          first: jnp.ndarray) -> Msgs:
    """damped = damping * prev + (1-damping) * new; no damping on the
    first cycle (reference apply_damping with prev=None, maxsum.py:679)."""
    return tuple(
        jnp.where(first, n, damping * o + (1.0 - damping) * n)
        for n, o in zip(new, old)
    )


def superstep(state: MaxSumState, graph: CompiledFactorGraph, *,
              damping: float, damp_vars: bool, damp_factors: bool,
              stability: float) -> MaxSumState:
    """One synchronous MaxSum cycle: factors fire, then variables."""
    first = state.cycle == 0
    valids = tuple(
        graph.var_valid[b.var_ids] for b in graph.buckets
    )

    f2v_new = factor_to_var(graph, state.v2f)
    if damp_factors and damping > 0:
        f2v_new = _damp(f2v_new, state.f2v, damping, first)

    beliefs, sums = aggregate_beliefs(graph, f2v_new)
    v2f_new = var_to_factor(graph, f2v_new, beliefs, sums)
    if damp_vars and damping > 0:
        v2f_new = _damp(v2f_new, state.v2f, damping, first)

    stable = (
        _all_match(f2v_new, state.f2v, stability, valids)
        & _all_match(v2f_new, state.v2f, stability, valids)
        & ~first
    )
    return MaxSumState(
        v2f=v2f_new,
        f2v=f2v_new,
        stable=stable,
        cycle=state.cycle + 1,
    )


def run_maxsum(graph: CompiledFactorGraph, max_cycles: int, *,
               damping: float = 0.5, damp_vars: bool = True,
               damp_factors: bool = True, stability: float = 0.1,
               stop_on_convergence: bool = True,
               ) -> Tuple[MaxSumState, jnp.ndarray]:
    """Full MaxSum run in one XLA program (no host sync per cycle).

    Returns (final state, selected value indices [V]).
    """

    def step(state):
        return superstep(
            state, graph, damping=damping, damp_vars=damp_vars,
            damp_factors=damp_factors, stability=stability,
        )

    state = init_state(graph)
    if stop_on_convergence:
        state = jax.lax.while_loop(
            lambda s: (s.cycle < max_cycles) & ~s.stable,
            step,
            state,
        )
    else:
        state = jax.lax.fori_loop(
            0, max_cycles, lambda i, s: step(s), state
        )
    beliefs, _ = aggregate_beliefs(graph, state.f2v)
    values = select_values(graph, beliefs)
    return state, values

"""Deterministic, seed-driven fault injection for the agent runtime.

Two independent instruments:

- :class:`FaultyCommunicationLayer` decorates any
  ``CommunicationLayer`` with per-message drop / duplicate / delay
  faults and network partitions.  Decisions are a pure function of
  ``(seed, src_agent, dest_agent, per-edge message index)`` — the same
  seed replays the same fault pattern regardless of thread
  interleaving, which is what makes chaos tests assertable.
- :class:`CrashSchedule` + :class:`FaultMonitor` murder agents
  mid-solve ("kill agent X at cycle N"): the monitor watches the
  orchestrator's cycle reports, hard-stops the victim's thread (no
  clean shutdown, no stop report — a crash, not a stop) and reports
  the failure so the reparation path migrates the orphaned
  computations (see Orchestrator.report_agent_failure).

Management and discovery traffic is protected by default
(``protect_management=True``): dropping a deploy or a directory
publication does not test the *algorithms'* fault tolerance, it only
wedges the harness.  Set it False to chaos-test the control plane too.
"""

import hashlib
import logging
import random
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from pydcop_tpu.infrastructure.communication import (
    CommunicationLayer,
    MSG_VALUE,
)
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer

logger = logging.getLogger("pydcop.resilience.faults")


def _note_fault(kind: str, src: str, dest: str, msg_type: str):
    """One injected fault -> one trace instant + one counter bump, so a
    chaos run is reconstructable from its trace file alone."""
    metrics_registry.counter(
        "pydcop_fault_injections_total",
        "Faults injected by the chaos layer",
    ).inc(kind=kind)
    if tracer.enabled:
        tracer.instant(f"fault_{kind}", "fault", src=src, dest=dest,
                       type=msg_type)


@dataclass(frozen=True)
class CrashEvent:
    """Kill ``agent`` once the global cycle count reaches ``cycle``."""

    agent: str
    cycle: int

    @classmethod
    def parse(cls, spec: str) -> "CrashEvent":
        """Parse an ``agent:cycle`` CLI spec (e.g. ``a1:30``)."""
        agent, _, cycle = spec.rpartition(":")
        if not agent:
            raise ValueError(
                f"crash spec must be agent:cycle, got {spec!r}")
        return cls(agent, int(cycle))


@dataclass(frozen=True)
class FaultPlan:
    """Everything a chaos run injects, in one seedable value.

    Probabilities are per message: ``drop`` (never delivered),
    ``duplicate`` (delivered twice), ``delay`` (delivered after
    ``delay_time`` seconds, off the sender thread).  ``partitions`` is
    a set of agent groups; messages crossing group boundaries are
    dropped (agents absent from every group communicate freely).
    ``partition_heal_index`` HEALS the partition deterministically:
    once a cross-group edge's per-edge message index reaches it,
    traffic flows again — the transport-level analogue of an end-cycle
    (for cycle-synchronous algorithms the per-edge index advances one
    per cycle), chosen over wall-clock so decisions stay a pure
    function of (seed, edge, index) and soak scenarios can assert
    post-heal reconvergence under replay.  ``crashes`` is the kill
    schedule; ``replicas`` the replication factor a harness should
    place before letting the crashes fire.
    """

    seed: int = 0
    drop: float = 0.0
    duplicate: float = 0.0
    delay: float = 0.0
    delay_time: float = 0.05
    partitions: Tuple[frozenset, ...] = ()
    partition_heal_index: Optional[int] = None
    crashes: Tuple[CrashEvent, ...] = ()
    replicas: int = 2
    protect_management: bool = True

    def is_partitioned(self, src: str, dest: str,
                       index: int = 0) -> bool:
        """True when the partition blocks ``src -> dest``'s
        ``index``-th message — a pure function of the plan and the
        per-edge message index (no clocks, no shared state)."""
        if not self.partitions:
            return False
        if self.partition_heal_index is not None \
                and index >= self.partition_heal_index:
            return False  # healed: cross-group traffic flows again
        src_groups = {
            i for i, g in enumerate(self.partitions) if src in g
        }
        dest_groups = {
            i for i, g in enumerate(self.partitions) if dest in g
        }
        if not src_groups or not dest_groups:
            return False
        return not (src_groups & dest_groups)

    def wrapper(self, stats: Optional["FaultStats"] = None
                ) -> Callable[[CommunicationLayer, str],
                              "FaultyCommunicationLayer"]:
        """A ``comm_wrapper(layer, agent_name)`` factory for
        ``run_local_thread_dcop`` — all wrapped layers share ``stats``."""
        shared = stats if stats is not None else FaultStats()

        def wrap(inner: CommunicationLayer, agent_name: str
                 ) -> "FaultyCommunicationLayer":
            return FaultyCommunicationLayer(inner, self, stats=shared)

        return wrap


class FaultStats:
    """Thread-safe counters shared by every wrapped layer of a run."""

    def __init__(self):
        self._lock = threading.Lock()
        self.sent = 0
        self.dropped = 0
        self.duplicated = 0
        self.delayed = 0
        self.partitioned = 0

    def bump(self, name: str, n: int = 1):
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return {
                "sent": self.sent,
                "dropped": self.dropped,
                "duplicated": self.duplicated,
                "delayed": self.delayed,
                "partitioned": self.partitioned,
            }

    def __repr__(self):
        return f"FaultStats({self.as_dict()})"


def _edge_rng(seed: int, src: str, dest: str, index: int
              ) -> random.Random:
    """A Random seeded purely by (plan seed, edge, message index) —
    stable across processes and thread schedules (``hash()`` is salted
    per process, so blake2 instead)."""
    key = f"{seed}:{src}>{dest}:{index}".encode()
    digest = hashlib.blake2b(key, digest_size=8).digest()
    return random.Random(int.from_bytes(digest, "big"))


class FaultyCommunicationLayer(CommunicationLayer):
    """Decorator over any transport, injecting the plan's faults on the
    SEND side (the receive path is untouched: for the in-process layer
    other agents deliver straight into the inner layer's address).

    ``messaging`` / ``discovery`` are forwarded to the inner layer so
    agent wiring (``Messaging.__init__``, ``Agent.__init__``) works
    unchanged on the wrapped object.
    """

    def __init__(self, inner: CommunicationLayer, plan: FaultPlan,
                 stats: Optional[FaultStats] = None):
        self._inner = inner
        self._plan = plan
        self.stats = stats if stats is not None else FaultStats()
        self._counts: Dict[Tuple[str, str], int] = {}
        self._lock = threading.Lock()
        # Intentionally no super().__init__(): messaging/discovery are
        # forwarding properties over the inner layer's attributes.

    # -- forwarded wiring ---------------------------------------------- #

    @property
    def messaging(self):
        return self._inner.messaging

    @messaging.setter
    def messaging(self, value):
        self._inner.messaging = value

    @property
    def discovery(self):
        return self._inner.discovery

    @discovery.setter
    def discovery(self, value):
        self._inner.discovery = value

    @property
    def address(self):
        return self._inner.address

    def on_agent_change(self, event: str, agent_name: str):
        self._inner.on_agent_change(event, agent_name)

    def receive_msg(self, src_agent: str, dest_agent: str, msg):
        self._inner.receive_msg(src_agent, dest_agent, msg)

    def shutdown(self):
        self._inner.shutdown()

    # -- fault injection ------------------------------------------------ #

    def _next_index(self, src: str, dest: str) -> int:
        with self._lock:
            n = self._counts.get((src, dest), 0)
            self._counts[(src, dest)] = n + 1
            return n

    def send_msg(self, src_agent: str, dest_agent: str, msg,
                 on_error=None):
        plan = self._plan
        if plan.protect_management and msg.msg_type < MSG_VALUE:
            self._inner.send_msg(src_agent, dest_agent, msg,
                                 on_error=on_error)
            return
        # One index per faultable message, consumed BEFORE the
        # partition verdict: partition healing is keyed on this index
        # (a pure function of the edge's send count), so partitioned
        # messages must advance it too.
        index = self._next_index(src_agent, dest_agent)
        if plan.is_partitioned(src_agent, dest_agent, index):
            self.stats.bump("partitioned")
            _note_fault("partition", src_agent, dest_agent,
                        msg.msg.type)
            logger.debug(
                "PARTITION %s -> %s: %s dropped",
                src_agent, dest_agent, msg.msg.type,
            )
            return
        rng = _edge_rng(plan.seed, src_agent, dest_agent, index)
        if rng.random() < plan.drop:
            self.stats.bump("dropped")
            _note_fault("drop", src_agent, dest_agent, msg.msg.type)
            logger.debug(
                "DROP %s -> %s: %s", src_agent, dest_agent, msg.msg.type
            )
            return
        copies = 1
        if plan.duplicate and rng.random() < plan.duplicate:
            copies = 2
            self.stats.bump("duplicated")
            _note_fault("duplicate", src_agent, dest_agent,
                        msg.msg.type)
        if plan.delay and rng.random() < plan.delay:
            self.stats.bump("delayed")
            _note_fault("delay", src_agent, dest_agent, msg.msg.type)
            timer = threading.Timer(
                plan.delay_time,
                self._deliver, (src_agent, dest_agent, msg, copies,
                                on_error),
            )
            timer.daemon = True
            timer.start()
            return
        self._deliver(src_agent, dest_agent, msg, copies, on_error)

    def _deliver(self, src_agent: str, dest_agent: str, msg,
                 copies: int, on_error):
        for _ in range(copies):
            self.stats.bump("sent")
            try:
                self._inner.send_msg(src_agent, dest_agent, msg,
                                     on_error=on_error)
            except Exception:
                # Delayed deliveries run on a timer thread: an
                # unreachable destination must not kill the timer with
                # an unhandled exception (the inner layer's own retry /
                # dead-marking already handled or logged it).
                logger.debug(
                    "Fault-delayed delivery to %s failed", dest_agent,
                    exc_info=True,
                )

    def __repr__(self):
        return f"FaultyCommunicationLayer({self._inner!r})"


class CrashSchedule:
    """An ordered kill list; parses the CLI's ``agent:cycle`` specs."""

    def __init__(self, events: Sequence[CrashEvent]):
        self.events: List[CrashEvent] = sorted(
            events, key=lambda e: e.cycle
        )

    @classmethod
    def from_specs(cls, specs: Sequence[str]) -> "CrashSchedule":
        return cls([CrashEvent.parse(s) for s in specs])

    def __bool__(self):
        return bool(self.events)

    def __iter__(self):
        return iter(self.events)


def kill_agent(orchestrator, agent_name: str,
               report: bool = True) -> None:
    """Crash ``agent_name``: hard-stop its thread when it is reachable
    in this process (thread-mode runs expose ``local_agents``), then
    report the failure so the orchestrator's reparation path migrates
    the orphaned computations.  Process/remote agents cannot be stopped
    from here — for them this is purely the failure report (the real
    process keeps running until its transport is cut externally).

    ``report=False`` makes the crash SILENT: the thread dies but no
    failure report is filed — the mode chaos runs use to prove that a
    death is *detected* (heartbeat monitor, transport retry window)
    rather than merely announced by its own injector."""
    agents = getattr(orchestrator, "local_agents", {}) or {}
    agent = agents.get(agent_name)
    if agent is not None:
        agent.stop()
        logger.warning("CRASH injected: agent %s thread stopped",
                       agent_name)
    metrics_registry.counter(
        "pydcop_fault_injections_total",
        "Faults injected by the chaos layer",
    ).inc(kind="kill")
    if tracer.enabled:
        tracer.instant("fault_kill", "fault", agent=agent_name)
    if report:
        orchestrator.report_agent_failure(agent_name)


class FaultMonitor:
    """Daemon thread firing a :class:`CrashSchedule` against a running
    orchestrator.  Triggers on the orchestrator's *global* cycle view
    (max over all computations' reported cycles) so a kill lands
    mid-solve regardless of which agent reports first."""

    def __init__(self, orchestrator, schedule: CrashSchedule,
                 poll: float = 0.02,
                 kill: Callable = kill_agent):
        self.orchestrator = orchestrator
        self.schedule = schedule
        self.poll = poll
        self.kill = kill
        self.killed: List[str] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="fault_monitor", daemon=True
        )

    def start(self) -> "FaultMonitor":
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(2.0)

    def _global_cycle(self) -> int:
        try:
            return max(self.orchestrator.mgt.cycles.values(), default=0)
        except RuntimeError:
            # The mgt thread mutated the dict mid-iteration; this poll
            # is best-effort — read again next tick.
            return 0

    def _run(self):
        pending = list(self.schedule)
        fired: Set[str] = set()
        while pending and not self._stop.is_set():
            cycle = self._global_cycle()
            due = [e for e in pending if cycle >= e.cycle]
            for event in due:
                pending.remove(event)
                if event.agent in fired:
                    continue
                fired.add(event.agent)
                try:
                    self.kill(self.orchestrator, event.agent)
                    self.killed.append(event.agent)
                except Exception:
                    logger.exception(
                        "Crash injection of %s failed", event.agent
                    )
            self._stop.wait(self.poll)

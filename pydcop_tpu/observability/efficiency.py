"""Device-efficiency accounting plane: utilization attainment, request
time ledgers, and the where-the-time-went rollup (ISSUE 14).

The system claims device-efficiency wins (batching, pruning, envelope
packing) but until now had no surface that could *verify* them: of
every second of wall clock, how much was useful device work vs.
padding, compile, queue wait and host glue — and on which backend?
This module is that surface, three interlocking parts:

- **Utilization attainment.**  Every timed dispatch already carries an
  XLA ``cost_analysis`` keyed by its jit cache key
  (observability/profiler.py, captured on the cold dispatch) and a
  measured wall time (``engine.runner.timed_jit_call``).  Dividing
  them gives an MFU-style achieved-vs-peak number per dispatch: XLA
  counts a while-loop body ONCE (trip-count-independent, pinned in
  tests/unit/test_perf_intel_battery.py), so a loop program's flops
  entry is per-superstep — achieved flops/s is
  ``flops * cycles / execute_s``.  Attainment is roofline-style: the
  MAX of flop attainment and bandwidth attainment (a memory-bound
  program at 80% of peak bandwidth is an efficiently used machine even
  at 1% of peak flops); both components are reported.  Peaks come
  from a per-backend table (:data:`BACKEND_PEAKS`, deliberately
  coarse) overridable with ``PYDCOP_PEAK_FLOPS`` /
  ``PYDCOP_PEAK_BYTES_PER_S`` — the rollup says which source it used,
  so a number computed against a default peak can never masquerade as
  calibrated.

- **Useful-work fraction.**  Attainment says how hard the device
  worked; the honest waste accounting the dispatch paths already emit
  (``pad_fraction`` — duplicated batch lanes; ``envelope_waste`` —
  mask-padded cells of heterogeneous packing) says how much of that
  work answered nobody's question.  ``useful_work_fraction =
  attainment * (1 - pad_fraction) * (1 - envelope_waste)`` folds both
  into the single number the ROADMAP's "as fast as the hardware
  allows" north star needs, rolled up per structure, per backend and
  per request class (solo / batched / envelope / lane / session).

- **Request time ledgers.**  Every served request carries a component
  breakdown of its end-to-end latency — ``submit`` (admission +
  compile + journal on the submitting thread), ``queue`` (bounded
  queue + coalescing window), ``plan`` (flush planning / packing
  decision), ``prep`` (host-side stack/pad assembly and dispatch
  bookkeeping), ``compile`` (cold XLA compile), ``execute`` (device
  run) and ``decode`` (host post-processing) — built from contiguous
  timestamps so the components SUM to the measured total (the
  invariant tests/unit/test_efficiency_battery.py asserts within 5%
  across solo, binned, envelope-packed, lane-packed and session
  paths).  Component totals aggregate here into the
  where-the-time-went breakdown ``/profile``, ``/stats`` and
  ``pydcop profile report`` serve.

**Backend honesty**: every rollup and exported metric is labeled with
the RESOLVED backend (:func:`resolved_backend` — ``jax``'s actual
default backend plus the accelerator-probe outcome from
``utils.cleanenv.diag_events``), so a CPU-fallback number can never
masquerade as a TPU number — the same discipline bench.py's
``leg_backends`` applies per leg and ``tools/bench_sentinel.py``
enforces across rounds.

Overhead: recording is a dict update under one lock per DISPATCH
(milliseconds of device work), never per cycle; ``make perf-smoke``
gates the plane at ≤ 5% with the pairwise-interleaved on/off
methodology.  ``PYDCOP_EFFICIENCY=0`` disables recording entirely.
"""

import os
import threading
from typing import Any, Dict, List, Optional

from pydcop_tpu.observability.metrics import registry as metrics_registry

# Ledger components, in wall-clock order.  ``make_ledger`` accepts any
# subset; the invariant is components-sum-to-total, not all-present
# (an expired request has no execute component to report).
LEDGER_COMPONENTS = ("submit", "queue", "plan", "prep", "compile",
                     "execute", "decode")

# Per-backend peak (flops/s, bytes/s) used for attainment when no env
# override is given.  Deliberately coarse, order-of-magnitude honest:
# tpu = v5e bf16 peak (197 TFLOP/s, 819 GB/s HBM); gpu = a mid-range
# datacenter part; cpu = a few vector cores' worth.  The rollup
# reports ``peak_source`` so consumers know whether the denominator
# was calibrated (env) or a default — calibrate with
# PYDCOP_PEAK_FLOPS / PYDCOP_PEAK_BYTES_PER_S for real MFU numbers.
BACKEND_PEAKS: Dict[str, Any] = {
    "tpu": (1.97e14, 8.19e11),
    "gpu": (1.0e13, 9.0e11),
    "cpu": (1.0e11, 5.0e10),
}
DEFAULT_PEAK = (1.0e11, 5.0e10)

PEAK_FLOPS_ENV = "PYDCOP_PEAK_FLOPS"
PEAK_BYTES_ENV = "PYDCOP_PEAK_BYTES_PER_S"
ENABLE_ENV = "PYDCOP_EFFICIENCY"


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if raw is None:
        return None
    try:
        value = float(raw)
    except ValueError:
        return None
    return value if value > 0 else None


def backend_peaks(backend: str) -> Dict[str, Any]:
    """``{flops_per_s, bytes_per_s, source}`` for one backend —
    env-calibrated when ``PYDCOP_PEAK_FLOPS``/``PYDCOP_PEAK_BYTES_PER_S``
    are set, the coarse :data:`BACKEND_PEAKS` default otherwise.
    ``source`` is ``env`` only when BOTH peaks are calibrated;
    calibrating one resource reports ``mixed`` — an attainment whose
    binding resource was judged against a default peak must never
    read as calibrated."""
    flops, bw = BACKEND_PEAKS.get(backend, DEFAULT_PEAK)
    env_flops = _env_float(PEAK_FLOPS_ENV)
    env_bw = _env_float(PEAK_BYTES_ENV)
    if env_flops is not None:
        flops = env_flops
    if env_bw is not None:
        bw = env_bw
    calibrated = sum(1 for v in (env_flops, env_bw) if v is not None)
    source = ("env" if calibrated == 2
              else "mixed" if calibrated == 1 else "default")
    return {"flops_per_s": flops, "bytes_per_s": bw, "source": source}


_backend_cache: Dict[str, Any] = {}
_backend_lock = threading.Lock()


def resolved_backend(refresh: bool = False) -> Dict[str, Any]:
    """The backend this process ACTUALLY runs on, plus the
    accelerator-probe outcome at resolution time — the label every
    efficiency metric carries (backend honesty: a CPU fallback must
    say so).  The jax resolution is memoized (the default backend
    cannot change once initialized); the probe summary is re-read per
    call — failures can accumulate while a process runs."""
    with _backend_lock:
        base = dict(_backend_cache)
    if refresh or not base:
        try:
            import jax

            base = {
                "backend": jax.default_backend(),
                "n_devices": len(jax.devices()),
            }
        except Exception as exc:  # noqa: BLE001 — the accounting
            # plane must answer even before/without a live backend.
            base = {"backend": "unknown", "n_devices": 0,
                    "error": f"{type(exc).__name__}: {exc}"[:120]}
        with _backend_lock:
            _backend_cache.clear()
            _backend_cache.update(base)
    out = dict(base)
    try:
        from pydcop_tpu.utils.cleanenv import (
            diag_events,
            is_probe_failure,
        )

        failures = [e for e in diag_events() if is_probe_failure(e)]
        out["probe_failures"] = len(failures)
        out["probe_ok"] = not failures
        if failures:
            out["last_probe_error"] = failures[-1].get("error")
    except Exception:  # noqa: BLE001
        out["probe_failures"] = 0
        out["probe_ok"] = None
    return out


def backend_name() -> str:
    """The memoized resolved-backend STRING — the per-dispatch hot
    form.  :func:`resolved_backend` additionally re-reads the
    accelerator-probe diagnostics (a JSON env parse) on every call;
    dispatch recording only needs the label, so it must not pay that
    per dispatch."""
    with _backend_lock:
        cached = _backend_cache.get("backend")
    if cached is not None:
        return cached
    return resolved_backend()["backend"]


def structure_label(graph) -> str:
    """Low-cardinality structure label for the rollup's cell key
    (duck-typed over a CompiledFactorGraph: ``var_costs`` +
    ``buckets``).  ONE definition — the batched, lane and dynamic
    dispatch paths must never drift into splitting the same structure
    across two rollup cells."""
    rows = "_".join(
        f"a{b.arity}x{b.costs.shape[0]}" for b in graph.buckets)
    return (f"v{graph.var_costs.shape[0] - 1}"
            f"d{graph.var_costs.shape[1]}_{rows or 'nofactors'}")


def split_device_time(time_s: float, compile_s: float
                      ) -> Dict[str, float]:
    """Disjoint ``{compile, execute}`` from the DeviceRunResult
    overlapping-fields convention (cold: ``compile_time_s == time_s``
    — trace+compile+first run are one unseparable interval, charged
    to ``compile``; warm: compile is 0 and the whole wall is
    execute).  The two always sum to ``time_s``, which is what keeps
    the request ledger's sum invariant exact."""
    compile_part = min(max(compile_s, 0.0), max(time_s, 0.0))
    return {"compile": compile_part,
            "execute": max(time_s - compile_part, 0.0)}


def make_ledger(total_s: float, **components: float) -> Dict[str, Any]:
    """Assemble one time ledger: non-negative components (unknown keys
    rejected — the taxonomy is the contract), the measured total, and
    ``unaccounted_s`` (total minus component sum — honest residual,
    near zero when the breakpoints are contiguous; NEVER silently
    absorbed into a component)."""
    ledger: Dict[str, Any] = {}
    acc = 0.0
    for name in LEDGER_COMPONENTS:
        if name not in components:
            continue
        value = max(float(components.pop(name)), 0.0)
        ledger[f"{name}_s"] = round(value, 6)
        acc += value
    if components:
        raise ValueError(
            f"unknown ledger component(s) {sorted(components)}; "
            f"valid: {', '.join(LEDGER_COMPONENTS)}")
    total_s = max(float(total_s), 0.0)
    ledger["total_s"] = round(total_s, 6)
    ledger["unaccounted_s"] = round(total_s - acc, 6)
    return ledger


def ledger_component_sum(ledger: Dict[str, Any]) -> float:
    """Sum of the ledger's components (excluding total/unaccounted) —
    the left side of the sums-to-total invariant."""
    return sum(
        float(ledger.get(f"{name}_s", 0.0))
        for name in LEDGER_COMPONENTS
    )


def attainment_from_cost(cost_entry: Optional[Dict[str, Any]],
                         cycles: int, execute_s: float,
                         backend: str) -> Optional[Dict[str, Any]]:
    """MFU-style attainment of one dispatch from its XLA cost entry.

    ``cost_entry`` is a profiler entry (``flops`` / ``bytes_accessed``
    per loop iteration — XLA counts the while body once); ``cycles``
    scales it to the whole dispatch; ``execute_s`` is the measured
    device-execute wall.  Returns None when the entry is missing /
    unavailable or nothing was measured — "not profiled" must stay
    distinguishable from "0% attained"."""
    if not cost_entry or not cost_entry.get("available"):
        return None
    if execute_s <= 0 or cycles <= 0:
        return None
    peaks = backend_peaks(backend)
    out: Dict[str, Any] = {"peak_source": peaks["source"]}
    flop_att = bw_att = None
    flops = cost_entry.get("flops")
    if flops:
        achieved = float(flops) * cycles / execute_s
        flop_att = achieved / peaks["flops_per_s"]
        out["achieved_flops_per_s"] = achieved
        out["flop_attainment"] = flop_att
    bytes_accessed = cost_entry.get("bytes_accessed")
    if bytes_accessed:
        achieved_b = float(bytes_accessed) * cycles / execute_s
        bw_att = achieved_b / peaks["bytes_per_s"]
        out["achieved_bytes_per_s"] = achieved_b
        out["bandwidth_attainment"] = bw_att
    candidates = [a for a in (flop_att, bw_att) if a is not None]
    if not candidates:
        return None
    # Roofline verdict: the better-attained resource is the one the
    # program is bound by — a memory-bound kernel near peak bandwidth
    # is using the machine well regardless of its flop fraction.
    out["attainment"] = max(candidates)
    return out


class _StructureAgg:
    """Running aggregate of one (backend, structure) cell."""

    __slots__ = ("dispatches", "requests", "device_s", "execute_s",
                 "compile_s", "flops", "bytes", "pad_waste_s",
                 "envelope_waste_s", "by_class")

    def __init__(self):
        self.dispatches = 0
        self.requests = 0
        self.device_s = 0.0
        self.execute_s = 0.0
        self.compile_s = 0.0
        self.flops = 0.0
        self.bytes = 0.0
        self.pad_waste_s = 0.0
        self.envelope_waste_s = 0.0
        self.by_class: Dict[str, int] = {}


class EfficiencyTracker:
    """Process-wide efficiency aggregates: per-dispatch attainment
    records, request-ledger component totals, and jit compile/dispatch
    accounting — the single source behind ``/profile``, the ``/stats``
    efficiency block, the backend-labeled gauges and ``pydcop profile
    report``'s live mode.

    All recorders are cheap (one lock + dict arithmetic, per dispatch
    or per request, never per cycle), never raise, and no-op when
    :attr:`enabled` is off (``PYDCOP_EFFICIENCY=0``)."""

    def __init__(self):
        env = os.environ.get(ENABLE_ENV, "1").strip().lower()
        self.enabled = env not in ("0", "off", "false", "no")
        self._lock = threading.Lock()
        self._structures: Dict[Any, _StructureAgg] = {}
        self._ledger_totals: Dict[str, float] = {}
        self._ledger_counts: Dict[str, int] = {}
        self._ledger_unaccounted = 0.0
        self._jit_cold_s = 0.0
        self._jit_cold = 0
        self._jit_warm = 0
        self._overlap_s = 0.0
        self._overlap_execute_s = 0.0
        self._overlap_dispatches = 0
        self._last_attainment: Optional[float] = None
        self._last_useful: Optional[float] = None

    # -- recorders ------------------------------------------------------ #

    def record_overlap(self, overlap_s: float,
                       execute_s: float) -> None:
        """One pipelined dispatch's device/host overlap: the wall the
        host spent elsewhere (decoding the previous dispatch,
        launching the next) while this dispatch's device work was in
        flight, clamped by the caller to the dispatch's own execute
        wall.  ``pipeline_overlap_fraction = overlap_s / execute_s``
        over all pipelined dispatches — 0 on the synchronous path, →1
        when the device never waits for host-side decode."""
        if not self.enabled:
            return
        with self._lock:
            self._overlap_s += max(float(overlap_s), 0.0)
            self._overlap_execute_s += max(float(execute_s), 0.0)
            self._overlap_dispatches += 1

    def record_dispatch(self, key: str, structure: str, backend: str,
                        time_s: float, compile_s: float, cycles: int,
                        n_real: int, batch_size: int,
                        pad_fraction: float = 0.0,
                        envelope_waste: float = 0.0,
                        packing: str = "structure",
                        cost_entry: Optional[Dict[str, Any]] = None,
                        ) -> Optional[Dict[str, Any]]:
        """Account one device dispatch.  Returns the per-dispatch
        efficiency record (attainment + useful_work_fraction) for the
        caller to fold into its own metrics, or None when disabled.
        Waste seconds are charged out of the EXECUTE wall: padded
        lanes and masked envelope cells burn device time whether or
        not anyone wanted their answers."""
        if not self.enabled:
            return None
        try:
            return self._record_dispatch(
                key, structure, backend, time_s, compile_s, cycles,
                n_real, batch_size, pad_fraction, envelope_waste,
                packing, cost_entry)
        except Exception:  # noqa: BLE001 — accounting must never
            # fail a dispatch.
            return None

    def _record_dispatch(self, key, structure, backend, time_s,
                         compile_s, cycles, n_real, batch_size,
                         pad_fraction, envelope_waste, packing,
                         cost_entry) -> Dict[str, Any]:
        split = split_device_time(time_s, compile_s)
        execute_s = split["execute"]
        pad_fraction = min(max(float(pad_fraction or 0.0), 0.0), 1.0)
        envelope_waste = min(max(float(envelope_waste or 0.0), 0.0),
                             1.0)
        att = attainment_from_cost(cost_entry, cycles, execute_s,
                                   backend)
        useful = None
        if att is not None:
            useful = (att["attainment"] * (1.0 - pad_fraction)
                      * (1.0 - envelope_waste))
        record: Dict[str, Any] = {
            "backend": backend,
            "structure": structure,
            "packing": packing,
            "execute_s": round(execute_s, 6),
            "compile_s": round(split["compile"], 6),
            "cycles": int(cycles),
            "pad_fraction": pad_fraction,
            "envelope_waste": envelope_waste,
            "attainment": (round(att["attainment"], 6)
                           if att is not None else None),
            "useful_work_fraction": (round(useful, 6)
                                     if useful is not None else None),
        }
        if att is not None:
            record["attainment_detail"] = att
        cell_key = (backend, structure)
        with self._lock:
            agg = self._structures.get(cell_key)
            if agg is None:
                agg = self._structures[cell_key] = _StructureAgg()
            agg.dispatches += 1
            agg.requests += int(n_real)
            agg.device_s += float(time_s)
            agg.execute_s += execute_s
            agg.compile_s += split["compile"]
            # Flops/bytes only accumulate against measurable execute
            # wall: a cold dispatch's whole interval is charged to
            # compile (execute 0), so counting its work would inflate
            # the weighted attainment with seconds that aren't in the
            # denominator.
            if (execute_s > 0 and cost_entry
                    and cost_entry.get("available")):
                agg.flops += float(cost_entry.get("flops") or 0.0) \
                    * cycles
                agg.bytes += float(
                    cost_entry.get("bytes_accessed") or 0.0) * cycles
            # Waste seconds: duplicated bin lanes + masked envelope
            # cells, both charged against the execute wall.
            agg.pad_waste_s += execute_s * pad_fraction
            agg.envelope_waste_s += (
                execute_s * (1.0 - pad_fraction) * envelope_waste)
            agg.by_class[packing] = agg.by_class.get(packing, 0) + 1
            if att is not None:
                self._last_attainment = att["attainment"]
                self._last_useful = useful
        self._export_dispatch(backend, packing, record)
        return record

    def record_ledger(self, ledger: Dict[str, Any],
                      backend: Optional[str] = None,
                      kind: str = "request") -> None:
        """Fold one request/session ledger into the component totals
        (the where-the-time-went breakdown)."""
        if not self.enabled or not ledger:
            return
        try:
            backend = backend or backend_name()
            with self._lock:
                for name in LEDGER_COMPONENTS:
                    value = float(ledger.get(f"{name}_s", 0.0))
                    if value:
                        self._ledger_totals[name] = \
                            self._ledger_totals.get(name, 0.0) + value
                self._ledger_unaccounted += abs(
                    float(ledger.get("unaccounted_s", 0.0)))
                self._ledger_counts[kind] = \
                    self._ledger_counts.get(kind, 0) + 1
            if metrics_registry.active:
                counter = metrics_registry.counter(
                    "pydcop_request_ledger_seconds_total",
                    "End-to-end request latency by ledger component "
                    "(sums to total request seconds)")
                for name in LEDGER_COMPONENTS:
                    value = float(ledger.get(f"{name}_s", 0.0))
                    if value:
                        counter.inc(value, component=name,
                                    backend=backend)
        except Exception:  # noqa: BLE001
            pass

    def record_jit(self, key: str, first: bool, elapsed: float,
                   compile_s: Optional[float] = None) -> None:
        """timed_jit_call hook: global cold-compile wall + dispatch
        counts (the compile column of waste-by-cause, covering every
        engine — one-shot, segmented, dynamic, batched).
        ``compile_s`` overrides the charged compile wall when the
        caller attributed the cold interval more precisely — a cold
        dispatch whose executables all deserialized from the
        persistent AOT cache charges only the retrieval wall
        (engine/aotcache.split_cold_call), not the whole interval."""
        if not self.enabled:
            return
        with self._lock:
            if first:
                self._jit_cold += 1
                self._jit_cold_s += float(
                    elapsed if compile_s is None else compile_s)
            else:
                self._jit_warm += 1

    def _export_dispatch(self, backend: str, packing: str,
                         record: Dict[str, Any]) -> None:
        if not metrics_registry.active:
            return
        try:
            metrics_registry.counter(
                "pydcop_efficiency_dispatches_total",
                "Efficiency-accounted device dispatches by backend "
                "and packing class",
            ).inc(backend=backend, packing=packing)
            metrics_registry.counter(
                "pydcop_device_execute_seconds_total",
                "Device execute wall seconds by backend and packing "
                "class (compile excluded)",
            ).inc(record["execute_s"], backend=backend,
                  packing=packing)
            if record["compile_s"]:
                metrics_registry.counter(
                    "pydcop_device_compile_seconds_total",
                    "Cold-compile wall seconds by backend",
                ).inc(record["compile_s"], backend=backend)
            if record["attainment"] is not None:
                metrics_registry.gauge(
                    "pydcop_efficiency_attainment",
                    "Roofline attainment of the last accounted "
                    "dispatch (max of flop/bandwidth fraction of the "
                    "configured peak)",
                ).set(record["attainment"], backend=backend)
            if record["useful_work_fraction"] is not None:
                metrics_registry.gauge(
                    "pydcop_useful_work_fraction",
                    "Attainment discounted by padding and envelope "
                    "waste, last accounted dispatch",
                ).set(record["useful_work_fraction"],
                      backend=backend)
        except Exception:  # noqa: BLE001
            pass

    # -- readback ------------------------------------------------------- #

    def _weighted(self, aggs: List[_StructureAgg], backend: str
                  ) -> Dict[str, Any]:
        """Execute-time-weighted attainment + useful fraction over a
        set of structure cells."""
        execute_s = sum(a.execute_s for a in aggs)
        flops = sum(a.flops for a in aggs)
        byts = sum(a.bytes for a in aggs)
        pad_s = sum(a.pad_waste_s for a in aggs)
        env_s = sum(a.envelope_waste_s for a in aggs)
        out: Dict[str, Any] = {
            "execute_s": round(execute_s, 6),
            "compile_s": round(sum(a.compile_s for a in aggs), 6),
            "device_s": round(sum(a.device_s for a in aggs), 6),
            "dispatches": sum(a.dispatches for a in aggs),
            "requests": sum(a.requests for a in aggs),
            "pad_waste_s": round(pad_s, 6),
            "envelope_waste_s": round(env_s, 6),
        }
        if execute_s > 0:
            peaks = backend_peaks(backend)
            flop_att = (flops / execute_s / peaks["flops_per_s"]
                        if flops else None)
            bw_att = (byts / execute_s / peaks["bytes_per_s"]
                      if byts else None)
            candidates = [a for a in (flop_att, bw_att)
                          if a is not None]
            if candidates:
                att = max(candidates)
                useful_frac = 1.0 - (pad_s + env_s) / execute_s
                out["attainment"] = round(att, 6)
                out["flop_attainment"] = (round(flop_att, 6)
                                          if flop_att else None)
                out["bandwidth_attainment"] = (round(bw_att, 6)
                                              if bw_att else None)
                out["useful_work_fraction"] = round(
                    att * useful_frac, 6)
                out["peak_source"] = peaks["source"]
        return out

    def rollup(self, top_n: int = 10) -> Dict[str, Any]:
        """The full efficiency document (``/profile``, ``profile
        report --url``): backend identity, weighted attainment,
        ledger breakdown, waste-by-cause, and the top-N structures by
        device time."""
        backend_info = resolved_backend()
        with self._lock:
            cells = {k: v for k, v in self._structures.items()}
            ledger_totals = dict(self._ledger_totals)
            ledger_counts = dict(self._ledger_counts)
            unaccounted = self._ledger_unaccounted
            jit = {"cold_dispatches": self._jit_cold,
                   "warm_dispatches": self._jit_warm,
                   "cold_compile_s": round(self._jit_cold_s, 6)}
            overlap_s = self._overlap_s
            overlap_execute_s = self._overlap_execute_s
            overlap_n = self._overlap_dispatches
        by_backend: Dict[str, List[_StructureAgg]] = {}
        for (backend, _structure), agg in cells.items():
            by_backend.setdefault(backend, []).append(agg)
        backends = {
            backend: self._weighted(aggs, backend)
            for backend, aggs in sorted(by_backend.items())
        }
        structures = []
        for (backend, structure), agg in cells.items():
            row = self._weighted([agg], backend)
            row.update({"structure": structure, "backend": backend,
                        "by_class": dict(agg.by_class)})
            structures.append(row)
        structures.sort(key=lambda r: -r["device_s"])
        ledger_total = sum(ledger_totals.values())
        waste = {
            "padding_s": round(sum(
                a.pad_waste_s for a in cells.values()), 6),
            "envelope_s": round(sum(
                a.envelope_waste_s for a in cells.values()), 6),
            "compile_s": round(jit["cold_compile_s"], 6),
            "queue_s": round(ledger_totals.get("queue", 0.0), 6),
        }
        return {
            "backend": backend_info,
            "backends": backends,
            "structures": structures[:top_n],
            "structures_total": len(structures),
            "ledger": {
                "components_s": {
                    k: round(v, 6)
                    for k, v in sorted(ledger_totals.items())
                },
                "total_s": round(ledger_total, 6),
                "unaccounted_abs_s": round(unaccounted, 6),
                "counts": ledger_counts,
            },
            "waste_by_cause": waste,
            "jit": jit,
            "pipeline": {
                "overlap_s": round(overlap_s, 6),
                "execute_s": round(overlap_execute_s, 6),
                "dispatches": overlap_n,
            },
            "pipeline_overlap_fraction": (
                round(overlap_s / overlap_execute_s, 6)
                if overlap_execute_s > 0 else 0.0),
        }

    def summary(self) -> Dict[str, Any]:
        """The compact ``/stats`` block: resolved backend, last/
        weighted attainment and useful fraction, ledger component
        sums."""
        roll = self.rollup(top_n=3)
        backend = roll["backend"]["backend"]
        agg = roll["backends"].get(backend, {})
        return {
            "backend": backend,
            "probe_ok": roll["backend"].get("probe_ok"),
            "attainment": agg.get("attainment"),
            "useful_work_fraction": agg.get("useful_work_fraction"),
            "device_execute_s": agg.get("execute_s", 0.0),
            "dispatches": agg.get("dispatches", 0),
            "ledger_components_s": roll["ledger"]["components_s"],
            "waste_by_cause": roll["waste_by_cause"],
            "pipeline_overlap_fraction":
                roll["pipeline_overlap_fraction"],
        }

    def clear(self) -> None:
        """Drop every aggregate (tests); ``enabled`` is untouched."""
        with self._lock:
            self._structures = {}
            self._ledger_totals = {}
            self._ledger_counts = {}
            self._ledger_unaccounted = 0.0
            self._jit_cold_s = 0.0
            self._jit_cold = 0
            self._jit_warm = 0
            self._overlap_s = 0.0
            self._overlap_execute_s = 0.0
            self._overlap_dispatches = 0
            self._last_attainment = None
            self._last_useful = None


tracker = EfficiencyTracker()


def get_tracker() -> EfficiencyTracker:
    return tracker


def pooled_rollup(docs: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Pool per-replica ``rollup()`` documents (keyed by source name)
    into one fleet-level view — the ``GET /fleet/profile`` body.

    Additive pieces (ledger component seconds and counts, waste by
    cause, jit dispatch/compile totals, pipeline overlap) sum;
    attainment pools as a device-time-weighted mean (a busy replica's
    attainment must dominate an idle one's); the per-replica
    documents ride along untouched under ``replicas`` so nothing is
    hidden by the pooling."""
    ledger_components: Dict[str, float] = {}
    ledger_counts: Dict[str, int] = {}
    waste: Dict[str, float] = {}
    jit = {"cold_dispatches": 0, "warm_dispatches": 0,
           "cold_compile_s": 0.0}
    pipeline = {"overlap_s": 0.0, "execute_s": 0.0, "dispatches": 0}
    total_s = 0.0
    unaccounted = 0.0
    att_weight = 0.0
    att_sum = 0.0
    per_replica: Dict[str, Any] = {}
    for source in sorted(docs):
        doc = docs[source] or {}
        per_replica[source] = doc
        ledger = doc.get("ledger") or {}
        for k, v in (ledger.get("components_s") or {}).items():
            ledger_components[k] = (ledger_components.get(k, 0.0)
                                    + float(v or 0.0))
        for k, v in (ledger.get("counts") or {}).items():
            ledger_counts[k] = ledger_counts.get(k, 0) + int(v or 0)
        total_s += float(ledger.get("total_s") or 0.0)
        unaccounted += float(ledger.get("unaccounted_abs_s") or 0.0)
        for k, v in (doc.get("waste_by_cause") or {}).items():
            waste[k] = waste.get(k, 0.0) + float(v or 0.0)
        doc_jit = doc.get("jit") or {}
        jit["cold_dispatches"] += int(
            doc_jit.get("cold_dispatches") or 0)
        jit["warm_dispatches"] += int(
            doc_jit.get("warm_dispatches") or 0)
        jit["cold_compile_s"] += float(
            doc_jit.get("cold_compile_s") or 0.0)
        doc_pipe = doc.get("pipeline") or {}
        pipeline["overlap_s"] += float(
            doc_pipe.get("overlap_s") or 0.0)
        pipeline["execute_s"] += float(
            doc_pipe.get("execute_s") or 0.0)
        pipeline["dispatches"] += int(
            doc_pipe.get("dispatches") or 0)
        for agg in (doc.get("backends") or {}).values():
            att = agg.get("attainment")
            weight = float(agg.get("execute_s") or 0.0)
            if att is not None and weight > 0:
                att_sum += float(att) * weight
                att_weight += weight
    return {
        "replicas": per_replica,
        "n_replicas": len(per_replica),
        "attainment": (round(att_sum / att_weight, 6)
                       if att_weight > 0 else None),
        "ledger": {
            "components_s": {k: round(v, 6) for k, v in
                             sorted(ledger_components.items())},
            "total_s": round(total_s, 6),
            "unaccounted_abs_s": round(unaccounted, 6),
            "counts": ledger_counts,
        },
        "waste_by_cause": {k: round(v, 6)
                           for k, v in sorted(waste.items())},
        "jit": {"cold_dispatches": jit["cold_dispatches"],
                "warm_dispatches": jit["warm_dispatches"],
                "cold_compile_s": round(jit["cold_compile_s"], 6)},
        "pipeline": {
            "overlap_s": round(pipeline["overlap_s"], 6),
            "execute_s": round(pipeline["execute_s"], 6),
            "dispatches": pipeline["dispatches"],
        },
        "pipeline_overlap_fraction": (
            round(pipeline["overlap_s"] / pipeline["execute_s"], 6)
            if pipeline["execute_s"] > 0 else 0.0),
    }

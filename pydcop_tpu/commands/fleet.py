"""``pydcop fleet``: one-command request forensics over the fleet
trace plane (ISSUE 20).

``pydcop fleet forensics REQUEST_ID --url http://ROUTER`` asks a
RUNNING router for ``/fleet/forensics/<id>`` — the request's full
causal tree reconstructed from the router-merged trace: the admission
span, every route pick (replica + affinity/spill reason), injected
faults and NotSent-vs-ambiguous retries, dedupe hits on the winning
replica, and that replica's serve ledger (queue wait, dispatch,
engine segments), printed as one annotated timeline.

``pydcop fleet forensics REQUEST_ID --trace FILE [FILE...]`` answers
the same question offline from a saved ``/fleet/trace`` document (or
any exported trace files): the id is resolved to its ``trace_id`` by
scanning span args, then the tree is rebuilt with the same
per-lane-nesting machinery as ``pydcop trace query``.

Exit codes: 0 printed a tree, 1 unknown request, 2 bad input
(unreachable router / unreadable trace file).
"""

import json
import sys

# Router instants that deserve a callout in the timeline: the name
# maps to the annotation prefix the printer attaches.
_ANNOTATIONS = {
    "router_route_pick": "route-pick",
    "router_repick": "REPICK",
    "router_retry": "RETRY",
    "router_fence_flush": "fence-flush",
    "router_migrate": "MIGRATE",
    "router_session_events": "events-batch",
    "router_session_open": "session-open",
    "serve_dedupe": "DEDUPE-HIT",
    "netfault_injected": "FAULT",
}


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "fleet", help="fleet-wide observability: request forensics")
    fleet_sub = parser.add_subparsers(
        title="fleet commands", dest="fleet_command")

    forensics = fleet_sub.add_parser(
        "forensics",
        help="one request's causal tree across router and replicas")
    forensics.add_argument(
        "request_id",
        help="router-minted request id (the 'request_id' in the "
             "submit ack), or a session id")
    forensics.add_argument(
        "--url", default=None, metavar="URL",
        help="router base url (e.g. http://127.0.0.1:8099); asks "
             "the live /fleet/forensics surface")
    forensics.add_argument(
        "--trace", default=None, nargs="+", metavar="FILE",
        help="offline mode: saved /fleet/trace JSON or exported "
             "trace files (several are clock-anchor aligned)")
    forensics.add_argument(
        "--timeout", type=float, default=10.0,
        help="HTTP timeout for --url (seconds, default 10)")
    forensics.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the reconstructed tree as one JSON document")
    forensics.set_defaults(func=run_forensics)

    parser.set_defaults(func=_no_subcommand(parser))


def _no_subcommand(parser):
    def run(_args) -> int:
        parser.print_help(sys.stderr)
        return 2

    return run


def fetch_forensics(url: str, request_id: str,
                    timeout: float = 10.0):
    """GET the router's live forensics doc.  Returns (doc, None) on
    200, (None, message) otherwise — a 404 message means the id is
    unknown, anything else means the router was unreachable/refused."""
    from urllib.error import HTTPError, URLError
    from urllib.request import urlopen

    endpoint = (url.rstrip("/") + "/fleet/forensics/"
                + request_id.strip("/"))
    try:
        with urlopen(endpoint, timeout=timeout) as resp:  # noqa: S310
            return json.loads(resp.read()), None
    except HTTPError as exc:
        try:
            detail = json.loads(exc.read()).get("error", "")
        except ValueError:
            detail = ""
        return None, f"{exc.code}: {detail or exc.reason}"
    except (URLError, OSError, ValueError) as exc:
        return None, f"router unreachable: {exc}"


def _events_from_files(paths):
    """Load events from saved /fleet/trace docs OR plain trace files
    (mixed is fine): a fleet doc's events are already merged/rebased;
    plain files go through the clock-anchor aligner."""
    from pydcop_tpu.observability.trace import (
        TraceFileError,
        load_events_aligned,
    )

    fleet_docs, plain = [], []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as fh:
                head = json.load(fh)
        except (OSError, ValueError):
            head = None
        if isinstance(head, dict) and "sources" in head \
                and isinstance(head.get("events"), list):
            fleet_docs.append(head)
        else:
            plain.append(path)
    events = []
    for doc in fleet_docs:
        events.extend(doc["events"])
    if plain:
        events.extend(load_events_aligned(plain))
    return events


def resolve_trace_id(events, request_id: str):
    """Find the trace_id a request/session id belongs to by scanning
    span args (the router tags every fleet event with both)."""
    for ev in events:
        args = ev.get("args") or {}
        if request_id in (args.get("request"), args.get("session")):
            tid = args.get("trace_id")
            if tid:
                return tid
    return None


def print_forensics(doc, request_id: str, out=None) -> None:
    """The annotated timeline: ``trace query``'s tree printer plus
    fleet callouts (route picks, retries, dedupe hits, faults).

    ``out`` is resolved at call time (a ``sys.stdout`` default would
    freeze whatever stream was installed at import)."""
    out = out if out is not None else sys.stdout
    nesting = ("well-nested" if doc.get("well_nested")
               else "NOT WELL-NESTED (lossy shipping or clock skew?)")
    dropped = doc.get("dropped_spans")
    loss = (f", {dropped} span(s) dropped fleet-wide"
            if dropped else "")
    print(f"request {request_id} (trace {doc.get('trace_id')}): "
          f"{doc.get('spans', 0)} spans, {doc.get('instants', 0)} "
          f"instants on {doc.get('lanes', 0)} lane(s), "
          f"{nesting}{loss}", file=out)

    def _print(node, depth):
        indent = "  " * depth
        mark = _ANNOTATIONS.get(node["name"])
        if node["ph"] == "X":
            head = f"{node['name']} {node['dur_ms']:.3f} ms"
        else:
            head = f"* {node['name']}"
        if mark:
            head = f"[{mark}] {head}"
        extras = {k: v for k, v in (node.get("args") or {}).items()
                  if k not in ("trace_id", "trace_ids")}
        detail = (" " + " ".join(f"{k}={v}" for k, v
                                 in sorted(extras.items()))
                  if extras else "")
        print(f"{indent}{head} [{node['cat']}] "
              f"@{node['ts_ms']:.3f} ms (lane {node['tid']})"
              f"{detail}", file=out)
        for child in node.get("children", ()):
            _print(child, depth + 1)

    for root in doc.get("tree", ()):
        _print(root, 0)


def run_forensics(args) -> int:
    if bool(args.url) == bool(args.trace):
        print("pydcop fleet forensics: pass exactly one of --url "
              "(live router) or --trace FILE (offline)",
              file=sys.stderr)
        return 2

    if args.url:
        doc, err = fetch_forensics(args.url, args.request_id,
                                   args.timeout)
        if doc is None:
            print(f"pydcop fleet forensics: {err}", file=sys.stderr)
            return 1 if err and err.startswith("404") else 2
    else:
        from pydcop_tpu.observability.trace import (
            TraceFileError,
            query_request,
        )

        try:
            events = _events_from_files(args.trace)
        except TraceFileError as exc:
            print(f"pydcop fleet forensics: {exc}", file=sys.stderr)
            return 2
        trace_id = resolve_trace_id(events, args.request_id)
        if trace_id is None:
            print(f"pydcop fleet forensics: no span mentions request "
                  f"{args.request_id!r} in {len(args.trace)} "
                  "file(s)", file=sys.stderr)
            return 1
        doc = query_request(events, trace_id)
        doc["request_id"] = args.request_id

    if args.as_json:
        print(json.dumps(doc))
        return 0 if doc.get("events") else 1
    if not doc.get("events"):
        print(f"pydcop fleet forensics: trace for "
              f"{args.request_id!r} is empty", file=sys.stderr)
        return 1
    print_forensics(doc, args.request_id)
    return 0

"""CLI tests for ``pydcop graph`` and ``pydcop consolidate`` output
surfaces (reference tests/dcop_cli depth)."""

import json
import os
import subprocess
import sys

from fixtures_paths import LOCAL_INSTANCES as INSTANCES
FIXTURE = os.path.join(INSTANCES, "coloring_chain.yaml")
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def run_raw(args, timeout=120):
    return subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli"] + args,
        timeout=timeout, env=ENV, text=True,
    )


def run_json(args, timeout=120):
    return json.loads(run_raw(args, timeout))


class TestGraph:
    def test_graph_by_model(self):
        res = run_json(["graph", "-g", "factor_graph", FIXTURE])
        # 4 vars + 3 factors (coloring_chain: clash_12/23/34)
        assert res["nodes"] == 7
        assert res["edges"] == 6
        assert res["density"] > 0

    def test_graph_model_from_algo(self):
        res = run_json(["graph", "-a", "dsa", FIXTURE])
        assert res["graph"] == "constraints_hypergraph"
        assert res["nodes"] == 4

    def test_graph_requires_model_or_algo(self):
        proc = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "graph",
             FIXTURE],
            capture_output=True, text=True, env=ENV, timeout=60,
        )
        assert proc.returncode == 2
        assert "one of --graph or --algo" in (
            proc.stdout + proc.stderr)

    def test_graph_degree_and_cycles(self):
        res = run_json(["graph", "-g", "constraints_hypergraph",
                        FIXTURE])
        # w1-w2-w3-w4 chain: no cycles, max degree 2, diameter 3
        assert res["cycles"] == 0
        assert res["max_degree"] == 2
        assert res["min_degree"] == 1
        assert res["component_diameters"] == [3]


class TestConsolidate:
    def _result_file(self, tmp_path, name, cost, time_s):
        payload = {
            "status": "FINISHED", "cost": cost, "time": time_s,
            "cycle": 10, "msg_count": 100, "msg_size": 1000,
        }
        p = tmp_path / name
        p.write_text(json.dumps(payload))
        return str(p)

    def test_solution_rows(self, tmp_path):
        f1 = self._result_file(tmp_path, "r1.json", 5.0, 1.0)
        f2 = self._result_file(tmp_path, "r2.json", 7.0, 2.0)
        out = run_raw(["consolidate", "--solution", f1, f2])
        lines = [ln for ln in out.strip().splitlines() if ln]
        # rows only on stdout (header is written to --output files)
        assert len(lines) == 2
        assert lines[0].split(",")[:2] == ["1.0", "5.0"]

    def test_solution_output_file_gets_header(self, tmp_path):
        f1 = self._result_file(tmp_path, "r1.json", 5.0, 1.0)
        out_file = tmp_path / "out.csv"
        run_raw(["--output", str(out_file),
                 "consolidate", "--solution", f1])
        lines = out_file.read_text().strip().splitlines()
        assert lines[0].startswith("time,cost,cycle")
        assert len(lines) == 2

    def test_average_mode(self, tmp_path):
        f1 = self._result_file(tmp_path, "r1.json", 5.0, 1.0)
        f2 = self._result_file(tmp_path, "r2.json", 7.0, 3.0)
        out = run_raw(["consolidate", "--average", f1, f2])
        row = out.strip().split(",")
        # n_runs, time, cost, cycle, msg_count, msg_size, finished_frac
        assert row[0] == "2"
        assert float(row[1]) == 2.0   # mean time
        assert float(row[2]) == 6.0   # mean cost
        assert float(row[6]) == 1.0   # both FINISHED

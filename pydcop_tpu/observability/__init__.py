"""Unified observability: tracing, metrics registry, engine telemetry.

Four parts (docs/observability.md):

- :mod:`.trace` — process-wide :data:`~pydcop_tpu.observability.trace.
  tracer` producing timestamped, parent-correlated spans with Chrome
  ``trace_event`` and JSONL exporters;
- :mod:`.metrics` — :data:`~pydcop_tpu.observability.metrics.registry`
  of counters/gauges/histograms with Prometheus text export and JSONL
  snapshots;
- :mod:`.engine_probe` — per-chunk honest device timings + cost
  convergence for the jitted solvers;
- the instrumentation wired through infrastructure, engine and
  resilience (all guarded on one flag check, zero overhead when off).

:class:`ObservabilitySession` is the run-scoped front door used by
``api.solve``: it enables the tracer/registry for one solve and
exports trace + Prometheus files on the way out.
"""

from typing import Optional

from pydcop_tpu.observability.metrics import (  # noqa: F401
    MetricsRegistry,
    get_registry,
    registry,
)
from pydcop_tpu.observability.trace import (  # noqa: F401
    Tracer,
    get_tracer,
    tracer,
)


class ObservabilitySession:
    """Enable tracing/metrics for one solve; export on finish.

    ``trace_path`` + ``trace_format`` ('chrome'|'jsonl') control the
    trace export; ``metrics_path`` activates the registry's optional
    instrumentation and, on finish, writes a Prometheus text dump next
    to the JSONL snapshots (``<metrics_path>.prom``).
    """

    def __init__(self, trace_path: Optional[str] = None,
                 trace_format: str = "chrome",
                 metrics_path: Optional[str] = None):
        if trace_format not in ("chrome", "jsonl"):
            raise ValueError(
                f"trace_format must be 'chrome' or 'jsonl', got "
                f"{trace_format!r}"
            )
        self.trace_path = trace_path
        self.trace_format = trace_format
        self.metrics_path = metrics_path
        self._was_active = registry.active

    def start(self) -> "ObservabilitySession":
        if self.trace_path:
            tracer.enable()
        if self.metrics_path:
            registry.active = True
        return self

    def finish(self):
        if self.trace_path:
            tracer.disable()
            tracer.export(self.trace_path, self.trace_format)
        if self.metrics_path:
            registry.active = self._was_active
            with open(f"{self.metrics_path}.prom", "w",
                      encoding="utf-8") as f:
                f.write(registry.to_prometheus())

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.finish()
        return False

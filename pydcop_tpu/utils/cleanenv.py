"""Scrubbed-environment helper for JAX backend selection.

This image's sitecustomize registers the axon TPU PJRT plugin in every
python interpreter (gated on ``PALLAS_AXON_POOL_IPS``); once registered,
a wedged tunnel hangs backend init and no in-process ``jax.config``
update can recover. Every entry point that needs a guaranteed-live CPU
backend (tests, bench fallback, multichip dryrun) builds its child env
through this one helper so the scrub recipe cannot drift between copies.

No jax import here — this module must be importable before any backend
is initialized.
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def scrubbed_cpu_env(n_devices=None, base=None):
    """Return an env dict that forces a clean CPU JAX backend.

    - drops ``PALLAS_AXON_POOL_IPS`` so sitecustomize skips plugin
      registration entirely in the child interpreter;
    - sets ``JAX_PLATFORMS=cpu``;
    - when ``n_devices`` is given, forces exactly that virtual host
      device count in ``XLA_FLAGS`` (replacing any inherited value —
      an inherited smaller count would make sharded code fail even
      though it is healthy).
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = re.sub(
            _COUNT_FLAG + r"=\d+", "", env.get("XLA_FLAGS", "")
        ).strip()
        env["XLA_FLAGS"] = (
            flags + f" {_COUNT_FLAG}={n_devices}"
        ).strip()
    return env


def ensure_live_backend(tag="bench", retries=1, probe_timeout=120):
    """Guard a benchmark entry point against a wedged TPU tunnel.

    Probes jax backend init in a subprocess (a wedged axon tunnel hangs
    `jax.devices()` forever, even under JAX_PLATFORMS=cpu, because the
    plugin blocks at registration).  After ``retries`` failed probes
    (the wedge is frequently transient, so callers may ask for several)
    the current script is re-exec'd into a scrubbed CPU env so it
    always emits its result line.  No-op in the re-exec'd child
    (PYDCOP_BENCH_NO_PROBE marker).
    """
    import subprocess
    import sys
    import time

    if os.environ.get("PYDCOP_BENCH_NO_PROBE"):
        return
    for attempt in range(retries):
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=probe_timeout, check=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            return
        except (subprocess.TimeoutExpired,
                subprocess.CalledProcessError):
            print(
                f"{tag}: accelerator probe {attempt + 1}/{retries} "
                "failed", file=sys.stderr,
            )
            if attempt < retries - 1:
                time.sleep(5)
    print(
        f"{tag}: accelerator backend unresponsive; falling back to "
        "CPU", file=sys.stderr,
    )
    env = scrubbed_cpu_env()
    env["PYDCOP_BENCH_NO_PROBE"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)

"""Process-wide tracer: timestamped spans with parent/child
correlation, exported as Chrome ``trace_event`` JSON or JSONL.

The runtime is threaded (one thread per agent, HTTP server threads,
retry sweepers, fault timers); a single locked event list would
serialize every instrumented site on one mutex.  Instead each thread
appends to its own buffer (``threading.local``) — the only lock is
taken once per thread per session, when the buffer is registered for
export — so recording is a list append plus a dict build.

Disabled (the default) costs ONE attribute check: every instrumented
site guards on ``tracer.enabled``, :meth:`Tracer.span` returns a
shared no-op context manager singleton (no allocation), and
:meth:`Tracer.instant` returns before touching its arguments.  The
zero-overhead contract is asserted in the observability battery.

Span events carry ``id``/``parent`` correlation ids (a per-thread span
stack): a message-handling span opened inside an agent-step span
records the step as its parent, so one trace file reconstructs the
whole causal tree of a chaos run.  Chrome ``trace_event`` output loads
directly in ``chrome://tracing`` / Perfetto (spans are ``ph:"X"``
complete events, instants ``ph:"i"``); JSONL output is one event per
line for ad-hoc ``jq``/pandas processing.
"""

import itertools
import json
import os
import threading
import time
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional

_US = 1e6  # trace_event timestamps are microseconds


class _NoopSpan:
    """Shared do-nothing context manager returned while disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """An open span; records a complete (``ph:"X"``) event on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "span_id",
                 "parent_id", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.span_id = next(tracer._ids)
        self.parent_id = 0
        self._t0 = 0.0

    def __enter__(self):
        stack = self._tracer._stack()
        self.parent_id = stack[-1] if stack else 0
        stack.append(self.span_id)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        stack = self._tracer._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        self._tracer._record({
            "name": self.name,
            "cat": self.cat,
            "ph": "X",
            "ts": self._t0 * _US,
            "dur": (t1 - self._t0) * _US,
            "id": self.span_id,
            "parent": self.parent_id,
            "args": self.args,
        })
        return False


class Tracer:
    """Per-thread-buffered span/instant recorder.

    Lifecycle: :meth:`enable` clears previous events and starts a
    session; :meth:`disable` stops recording (events stay readable for
    export); :meth:`events` / :meth:`export_chrome` /
    :meth:`export_jsonl` read them back.
    """

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._local = threading.local()
        # (tid, thread name, buffer) per registered thread.
        self._buffers: List[tuple] = []
        # Bumping the generation invalidates every thread's cached
        # buffer, so enable() drops stale events without touching
        # other threads' locals.
        self._generation = 0
        self._ids = itertools.count(1)

    # -- recording ----------------------------------------------------- #

    def _buf(self) -> list:
        if getattr(self._local, "gen", None) != self._generation:
            buf: list = []
            thread = threading.current_thread()
            self._local.buf = buf
            self._local.stack = []
            self._local.gen = self._generation
            with self._lock:
                # Synthetic tid, not thread.ident: the OS reuses
                # idents once a thread exits (killed agents, repair
                # threads), which would merge two threads' lanes and
                # break span nesting within one exported lane.
                tid = len(self._buffers) + 1
                self._local.tid = tid
                self._buffers.append((tid, thread.name, buf))
        return self._local.buf

    def _stack(self) -> list:
        self._buf()
        return self._local.stack

    def _record(self, event: Dict[str, Any]):
        if not self.enabled:
            return
        buf = self._buf()
        event["tid"] = self._local.tid
        buf.append(event)

    def span(self, name: str, cat: str = "default", **args) -> Any:
        """Context manager recording a complete span on exit.

        Hot call sites should still guard on ``tracer.enabled`` so the
        kwargs dict is never built while disabled."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "default", **args):
        """Record a point-in-time event."""
        if not self.enabled:
            return
        parent = self._stack()
        self._record({
            "name": name,
            "cat": cat,
            "ph": "i",
            "ts": time.perf_counter() * _US,
            "id": next(self._ids),
            "parent": parent[-1] if parent else 0,
            "args": args,
        })

    # -- lifecycle ----------------------------------------------------- #

    def enable(self):
        """Start a fresh tracing session (previous events dropped)."""
        with self._lock:
            self._generation += 1
            self._buffers = []
            self.enabled = True

    def disable(self):
        """Stop recording; buffered events stay readable for export."""
        self.enabled = False

    def clear(self):
        """Drop all events; recording state unchanged."""
        with self._lock:
            self._generation += 1
            self._buffers = []

    # -- readback / export --------------------------------------------- #

    def events(self) -> List[Dict[str, Any]]:
        """All recorded events, globally sorted by timestamp."""
        with self._lock:
            buffers = [(tid, name, list(buf))
                       for tid, name, buf in self._buffers]
        merged = [ev for _, _, buf in buffers for ev in buf]
        merged.sort(key=lambda e: e["ts"])
        return merged

    def thread_names(self) -> Dict[int, str]:
        with self._lock:
            return {tid: name for tid, name, _ in self._buffers}

    def export_chrome(self, path: str):
        """Write Chrome ``trace_event`` JSON (open in chrome://tracing
        or https://ui.perfetto.dev)."""
        pid = os.getpid()
        trace_events = [
            {
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid, "args": {"name": name},
            }
            for tid, name in sorted(self.thread_names().items())
        ]
        for ev in self.events():
            out = {
                "name": ev["name"],
                "cat": ev["cat"],
                "ph": ev["ph"],
                "ts": ev["ts"],
                "pid": pid,
                "tid": ev["tid"],
                "args": dict(ev.get("args") or {}),
            }
            if ev["ph"] == "X":
                out["dur"] = ev["dur"]
            else:
                out["s"] = "t"  # thread-scoped instant
            # Correlation ids ride in args: the Chrome schema has no
            # parent field for X events, and viewers ignore extras.
            out["args"]["span_id"] = ev.get("id", 0)
            out["args"]["parent_id"] = ev.get("parent", 0)
            trace_events.append(out)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {"traceEvents": trace_events, "displayTimeUnit": "ms"},
                f, default=str,
            )
        os.replace(tmp, path)

    def export_jsonl(self, path: str):
        """One JSON event per line (jq/pandas-friendly)."""
        names = self.thread_names()
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for ev in self.events():
                row = dict(ev)
                row["thread"] = names.get(ev["tid"], str(ev["tid"]))
                f.write(json.dumps(row, default=str) + "\n")
        os.replace(tmp, path)

    def export(self, path: str, fmt: str = "chrome"):
        if fmt == "chrome":
            self.export_chrome(path)
        elif fmt == "jsonl":
            self.export_jsonl(path)
        else:
            raise ValueError(
                f"unknown trace format {fmt!r}: use 'chrome' or 'jsonl'"
            )


tracer = Tracer()


def get_tracer() -> Tracer:
    return tracer


# --------------------------------------------------------------------- #
# trace-file readback + analysis (pydcop trace summary, make trace-demo)


def load_trace_file(path: str) -> List[Dict[str, Any]]:
    """Load events from a Chrome-trace JSON or a JSONL trace file.

    Returns the normalized internal event shape (name/cat/ph/ts/dur/
    tid/args); Chrome metadata events (``ph:"M"``) are dropped.
    """
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        # One JSON document: the Chrome container, a bare list, or a
        # single-line JSONL file (one event object).
        data = json.loads(text)
        if isinstance(data, dict):
            events = data.get("traceEvents")
            if events is None:
                events = [data]
        else:
            events = data
    except json.JSONDecodeError:
        # Multiple documents: JSONL, one event per line.
        events = [json.loads(line) for line in text.splitlines()
                  if line.strip()]
    return [ev for ev in events if ev.get("ph") != "M"]


def summarize_spans(events: Iterable[Dict[str, Any]],
                    by: str = "name", top: Optional[int] = None
                    ) -> List[Dict[str, Any]]:
    """Aggregate complete spans by ``name`` (or ``cat``): count, total
    / mean / max duration in ms, sorted by total descending.  Instant
    events aggregate with zero duration (their counts still matter —
    fault drops and breaker trips are instants)."""
    agg: Dict[str, List[float]] = defaultdict(lambda: [0, 0.0, 0.0])
    for ev in events:
        if ev.get("ph") not in ("X", "i"):
            continue
        key = ev.get(by) or "?"
        dur_ms = float(ev.get("dur", 0.0)) / 1000.0
        entry = agg[key]
        entry[0] += 1
        entry[1] += dur_ms
        entry[2] = max(entry[2], dur_ms)
    rows = [
        {
            by: key, "count": count, "total_ms": total,
            "mean_ms": total / count if count else 0.0, "max_ms": mx,
        }
        for key, (count, total, mx) in agg.items()
    ]
    rows.sort(key=lambda r: (-r["total_ms"], -r["count"], r[by]))
    return rows[:top] if top else rows


def check_well_nested(events: Iterable[Dict[str, Any]]) -> None:
    """Raise ``ValueError`` unless, per thread, complete spans form a
    proper nesting (every pair either disjoint or contained).  Spans
    are recorded via a per-thread stack, so a violation means a
    corrupted trace file — ``make trace-demo`` gates on this."""
    by_tid: Dict[Any, List[tuple]] = defaultdict(list)
    for ev in events:
        if ev.get("ph") != "X":
            continue
        ts = float(ev["ts"])
        by_tid[ev.get("tid")].append((ts, ts + float(ev["dur"]), ev))
    eps = 1.0  # µs of timer slack between adjacent spans
    for tid, spans in by_tid.items():
        spans.sort(key=lambda s: (s[0], -s[1]))
        stack: List[tuple] = []
        for start, end, ev in spans:
            while stack and start >= stack[-1][1] - eps:
                stack.pop()
            if stack and end > stack[-1][1] + eps:
                raise ValueError(
                    f"span {ev.get('name')!r} [{start:.0f}, {end:.0f}] "
                    f"on tid {tid} overlaps enclosing span "
                    f"{stack[-1][2].get('name')!r} "
                    f"[{stack[-1][0]:.0f}, {stack[-1][1]:.0f}] "
                    "without nesting"
                )
            stack.append((start, end, ev))

"""Dynamic-DCOP scenario generator: random agent-removal events.

Reference parity: pydcop/commands/generators/scenario.py — evts_count
events of actions_count remove_agent actions each, separated by fixed
delays; never removes the orchestrator or already-removed agents.
"""

from typing import List, Optional

import numpy as np

from pydcop_tpu.dcop.scenario import DcopEvent, EventAction, Scenario


def generate_scenario(
    evts_count: int,
    actions_count: int,
    delay: float,
    agents: List[str],
    initial_delay: float = 20,
    end_delay: float = 20,
    seed: Optional[int] = None,
) -> Scenario:
    rng = np.random.default_rng(seed)
    available = list(agents)
    events = [DcopEvent("init_delay", delay=initial_delay)]
    for e in range(evts_count):
        if len(available) < actions_count:
            break
        chosen = rng.choice(
            len(available), size=actions_count, replace=False)
        removed = [available[i] for i in sorted(chosen, reverse=True)]
        for name in removed:
            available.remove(name)
        events.append(DcopEvent(
            f"e{e}",
            actions=[
                EventAction("remove_agent", agent=a) for a in removed
            ],
        ))
        events.append(DcopEvent(f"d{e}", delay=delay))
    events.append(DcopEvent("end_delay", delay=end_delay))
    return Scenario(events)

"""Battery for the fault-tolerant request plane (ISSUE 8):

- the durable request journal (length-prefixed + crc32 records, torn
  tails truncated past the last valid record, compaction on recovery);
- crash recovery: ``recover=True`` replays exactly the
  accepted-but-unfinished entries through the normal queue, completed
  work never resurrects, unloadable records fail terminally instead
  of replaying forever;
- per-request deadlines: already-expired work is dropped before
  binning (terminal EXPIRED, ``rejected_deadline`` in the ledger,
  504 on the wire) and never contaminates a fresh batch;
- poison isolation: a failed multi-request bin dispatch bisects until
  the poison request fails ALONE and its bin-mates succeed, with
  ``pydcop_serve_dispatch_retries_total`` accounting and the breaker
  fed only by the isolated singleton failure;
- graceful drain under concurrent load: 6 submitter threads racing
  ``stop(drain=True)`` — every acknowledged request either completes
  or stays journaled-replayable, zero lost, zero duplicated;
- the front-end regression: a malformed ``timeout``/``deadline_s``
  in the POST /solve body is a 400 (``rejected_bad_request``), never
  a silent coercion to the default.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.dcop.yamldcop import dcop_yaml
from pydcop_tpu.serving.journal import (
    RequestJournal,
    accepted_record,
    completed_record,
    encode_record,
    pending_requests,
    scan_journal,
)
from pydcop_tpu.serving.service import SolveService

MAX_CYCLES = 40
PARAMS = {"max_cycles": MAX_CYCLES}


def _instance(n: int, seed: int) -> DCOP:
    """Ring coloring with random tables: same n -> same structure
    bin; seed varies the tables.  Carries an agent so the instance
    survives the journal's dcop_yaml round-trip."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"ft{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k, (i, j) in enumerate(
            [(i, (i + 1) % n) for i in range(n)]):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _service(**kw) -> SolveService:
    kw.setdefault("batch_window_s", 0.05)
    kw.setdefault("max_batch", 8)
    return SolveService(**kw)


def _wait_done(svc, rid, timeout=30.0):
    result = svc.result(rid, wait=timeout)
    assert result is not None, f"request {rid} never finished"
    return result


# ------------------------------------------------------------------ #
# journal file format


class TestJournalFormat:
    def test_roundtrip_scan(self, tmp_path):
        path = str(tmp_path / "requests.jnl")
        recs = [accepted_record("a", "yaml: 1", {"max_cycles": 10}),
                completed_record("a", "FINISHED"),
                accepted_record("b", "yaml: 2", {},
                                deadline_s=2.5, t_submit=1.0)]
        with open(path, "wb") as f:
            for rec in recs:
                f.write(encode_record(rec))
        out, valid, torn = scan_journal(path)
        assert out == recs
        assert valid == os.path.getsize(path)
        assert not torn

    def test_missing_file_is_empty_journal(self, tmp_path):
        out, valid, torn = scan_journal(str(tmp_path / "nope.jnl"))
        assert out == [] and valid == 0 and not torn

    @pytest.mark.parametrize("tail", [
        b"\xff",                       # lone garbage byte
        b"\x00\x00\x00\x08\x00\x00",   # header cut mid-way
        encode_record({"kind": "accepted", "id": "t"})[:-3],  # torn
        b"\x00\x00\x00\x04\xde\xad\xbe\xefABCD",  # crc mismatch
        b"\xff\xff\xff\xff\x00\x00\x00\x00",      # absurd length
    ])
    def test_torn_tail_detected_and_bounded(self, tmp_path, tail):
        """Every corruption class truncates to the last VALID record
        — the prefix is never lost, the tail never parses."""
        path = str(tmp_path / "requests.jnl")
        good = [accepted_record("a", "y", {}),
                accepted_record("b", "y", {})]
        blob = b"".join(encode_record(r) for r in good)
        with open(path, "wb") as f:
            f.write(blob + tail)
        out, valid, torn = scan_journal(path)
        assert out == good
        assert valid == len(blob)
        assert torn

    def test_pending_set_semantics(self):
        recs = [accepted_record("a", "y", {}),
                accepted_record("b", "y", {}),
                completed_record("a", "FINISHED"),
                accepted_record("c", "y", {}),
                completed_record("zombie", "ERROR")]
        pending = pending_requests(recs)
        assert [r["id"] for r in pending] == ["b", "c"]

    def test_recover_truncates_and_compacts(self, tmp_path):
        d = str(tmp_path)
        jnl = RequestJournal(d)
        jnl.append(accepted_record("a", "y", {}))
        jnl.append(accepted_record("b", "y", {}))
        jnl.append(completed_record("a", "FINISHED"))
        jnl.close()
        with open(jnl.path, "ab") as f:
            f.write(b"torn-mid-append")
        jnl2, pending = RequestJournal.recover(d)
        assert [r["id"] for r in pending] == ["b"]
        jnl2.close()
        # Compacted: only the pending record survives on disk, the
        # torn tail is gone; a second recovery sees the same set.
        out, _, torn = scan_journal(jnl2.path)
        assert [r["id"] for r in out] == ["b"] and not torn
        jnl3, pending2 = RequestJournal.recover(d)
        jnl3.close()
        assert [r["id"] for r in pending2] == ["b"]

    def test_append_after_close_raises(self, tmp_path):
        jnl = RequestJournal(str(tmp_path))
        jnl.close()
        with pytest.raises(RuntimeError):
            jnl.append(accepted_record("a", "y", {}))


# ------------------------------------------------------------------ #
# service-side journaling + crash recovery replay


class TestJournalRecovery:
    def test_submit_journals_before_ack(self, tmp_path):
        d = str(tmp_path)
        svc = _service(journal_dir=d)
        svc.start()
        try:
            rid = svc.submit(_instance(8, 0), params=PARAMS)
            # The accepted record is on disk the moment submit
            # returns — that IS the durability promise behind the 202.
            recs, _, _ = scan_journal(svc._journal.path)
            assert [r for r in recs
                    if r["kind"] == "accepted" and r["id"] == rid]
            result = _wait_done(svc, rid)
            assert result["status"] == "FINISHED"
            recs, _, _ = scan_journal(svc._journal.path)
            assert [r for r in recs
                    if r["kind"] == "completed" and r["id"] == rid]
        finally:
            svc.stop(drain=False)

    def test_crash_replay_loses_zero_acknowledged(self, tmp_path):
        """Crash-equivalent journal (accepted records, one completed,
        a torn tail) + ``recover=True``: exactly the unfinished
        requests replay through the queue, complete with their
        ORIGINAL ids, and match the solo solve."""
        from pydcop_tpu import api

        d = str(tmp_path)
        dcops = {f"q{i}": _instance(8, 10 + i) for i in range(4)}
        jnl = RequestJournal(d)
        for rid, dcop in dcops.items():
            jnl.append(accepted_record(rid, dcop_yaml(dcop), PARAMS))
        jnl.append(completed_record("q0", "FINISHED"))
        jnl.close()
        with open(jnl.path, "ab") as f:
            f.write(b"\x00\x00\x00\x09torn")
        svc = _service(journal_dir=d, recover=True)
        svc.start()
        try:
            for rid in ("q1", "q2", "q3"):
                result = _wait_done(svc, rid)
                assert result["status"] == "FINISHED"
                solo = api.solve(dcops[rid], "maxsum",
                                 backend="device",
                                 max_cycles=MAX_CYCLES)
                assert result["assignment"] == solo["assignment"]
            # The pre-crash completion must NOT resurrect.
            with pytest.raises(KeyError):
                svc.result("q0")
            assert svc.replayed == 3
            assert svc.stats()["replayed"] == 3
        finally:
            svc.stop(drain=False)
        # Once everything replayed-and-finished, a fresh recovery
        # has nothing to do: completions were journaled too.
        jnl2, pending = RequestJournal.recover(d)
        jnl2.close()
        assert pending == []

    def test_unloadable_record_fails_terminally(self, tmp_path):
        """A journaled request whose yaml no longer loads is failed
        (journaled terminal), not dropped and not replayed forever."""
        d = str(tmp_path)
        jnl = RequestJournal(d)
        jnl.append(accepted_record("bad", ":: not dcop yaml", PARAMS))
        jnl.append(accepted_record("ok", dcop_yaml(_instance(8, 3)),
                                   PARAMS))
        jnl.close()
        svc = _service(journal_dir=d, recover=True)
        svc.start()
        try:
            assert _wait_done(svc, "ok")["status"] == "FINISHED"
            assert svc.replayed == 1
        finally:
            svc.stop(drain=False)
        jnl2, pending = RequestJournal.recover(d)
        jnl2.close()
        assert pending == [], "bad record must not replay forever"

    def test_journal_append_failure_fails_submit(self, tmp_path):
        """A 202 the journal cannot back must not be issued: the
        submit raises and leaves no tracked request behind."""
        svc = _service(journal_dir=str(tmp_path))
        svc.start()
        try:
            svc._journal._f.close()  # simulate a dead disk
            with pytest.raises(RuntimeError,
                               match="journal append failed"):
                svc.submit(_instance(8, 1), params=PARAMS)
            assert svc.stats()["tracked_requests"] == 0
        finally:
            svc._journal = None  # already dead; stop() must not trip
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# deadlines


class TestDeadlines:
    def test_expired_before_dispatch_is_terminal_504(self):
        svc = _service(batch_window_s=0.05)
        # Hold the scheduler back so the deadline lapses while the
        # request is still queued.
        svc.start()
        gate = threading.Event()
        real = svc._run_batch
        svc._run_batch = lambda reqs, params: (
            gate.wait(30), real(reqs, params))[1]
        try:
            rid_live = svc.submit(_instance(8, 5), params=PARAMS)
            # Let the scheduler collect rid_live and block inside its
            # dispatch; THEN submit with a tight deadline — the
            # request must be stuck in the queue past the deadline,
            # not merely processed slowly.
            time.sleep(0.2)
            rid_dead = svc.submit(_instance(9, 6), params=PARAMS,
                                  deadline_s=0.01)
            time.sleep(0.15)  # let the deadline lapse in-queue
            gate.set()
            dead = _wait_done(svc, rid_dead)
            live = _wait_done(svc, rid_live)
            assert dead["status"] == "EXPIRED"
            assert "deadline" in dead["error"]
            assert live["status"] == "FINISHED", \
                "an expired bin-mate must not poison fresh work"
            assert svc.expired == 1
            assert svc.stats()["expired"] == 1
        finally:
            svc.stop(drain=False)

    def test_fresh_deadline_not_expired(self):
        svc = _service()
        svc.start()
        try:
            rid = svc.submit(_instance(8, 7), params=PARAMS,
                             deadline_s=60.0)
            assert _wait_done(svc, rid)["status"] == "FINISHED"
            assert svc.expired == 0
        finally:
            svc.stop(drain=False)

    @pytest.mark.parametrize("bad", [0, -1.5, "soon", float("nan")])
    def test_bad_deadline_rejected_as_400_class(self, bad):
        svc = _service()
        svc.start()
        try:
            with pytest.raises(ValueError):
                svc.submit(_instance(8, 8), params=PARAMS,
                           deadline_s=bad)
        finally:
            svc.stop(drain=False)

    def test_expired_request_never_resurrects(self, tmp_path):
        """EXPIRED is journaled terminal: a --recover restart must
        not replay it (the client already got its 504)."""
        d = str(tmp_path)
        svc = _service(journal_dir=d)
        svc.start()
        gate = threading.Event()
        real = svc._run_batch
        svc._run_batch = lambda reqs, params: (
            gate.wait(30), real(reqs, params))[1]
        try:
            decoy = svc.submit(_instance(8, 9), params=PARAMS)
            time.sleep(0.2)  # scheduler now blocked in dispatch
            rid = svc.submit(_instance(9, 9), params=PARAMS,
                             deadline_s=0.01)
            time.sleep(0.15)
            gate.set()
            assert _wait_done(svc, rid)["status"] == "EXPIRED"
            assert _wait_done(svc, decoy)["status"] == "FINISHED"
        finally:
            svc.stop(drain=False)
        jnl, pending = RequestJournal.recover(d)
        jnl.close()
        assert pending == []


# ------------------------------------------------------------------ #
# poison isolation


class TestPoisonIsolation:
    def _poisoned(self, svc, poison_ids):
        """Wrap the batch runner: any batch containing a poison id
        fails — the deterministic stand-in for one request whose
        tables break the engine."""
        real = svc._run_batch
        calls = []

        def wrapped(reqs, params):
            calls.append([r.id for r in reqs])
            if any(r.id in poison_ids for r in reqs):
                raise RuntimeError("poison request in batch")
            return real(reqs, params)

        svc._run_batch = wrapped
        return calls

    def test_bisection_isolates_single_poison(self):
        svc = _service(batch_window_s=0.3, max_batch=8)
        svc.start()
        poison = set()
        calls = self._poisoned(svc, poison)
        try:
            # Same-structure bin of 8; exactly one poison member.
            rids = [svc.submit(_instance(8, 20 + i), params=PARAMS)
                    for i in range(8)]
            poison.add(rids[3])
            results = {rid: _wait_done(svc, rid) for rid in rids}
            assert results[rids[3]]["status"] == "ERROR"
            assert "dispatch failed" in results[rids[3]]["error"]
            for rid in rids:
                if rid != rids[3]:
                    assert results[rid]["status"] == "FINISHED", \
                        "bin-mate of the poison request must succeed"
            # Log-bounded: one poison in a bin of n costs at most
            # 2·n - 1 dispatch attempts of that bin's work.
            bin_calls = [c for c in calls if len(c) <= 8]
            assert len(bin_calls) <= 2 * 8 - 1
            assert svc.dispatch_retries > 0
            assert svc.stats()["dispatch_retries"] == \
                svc.dispatch_retries
        finally:
            svc.stop(drain=False)

    def test_poison_does_not_trip_breaker(self):
        """Only the isolated singleton failure feeds the breaker: one
        poison client among healthy traffic must not open the circuit
        (the bin-mates' successes close any half-open state)."""
        from pydcop_tpu.serving.admission import AdmissionPolicy

        svc = _service(batch_window_s=0.3, max_batch=8,
                       admission=AdmissionPolicy(
                           high_water=64, breaker_failures=2))
        svc.start()
        poison = set()
        self._poisoned(svc, poison)
        try:
            rids = [svc.submit(_instance(8, 40 + i), params=PARAMS)
                    for i in range(6)]
            poison.add(rids[0])
            for rid in rids:
                _wait_done(svc, rid)
            assert svc.admission.breaker.state != "open", (
                "one isolated poison failure must not open the "
                "dispatch breaker")
            # A fresh submit still admits.
            rid = svc.submit(_instance(8, 60), params=PARAMS)
            assert _wait_done(svc, rid)["status"] == "FINISHED"
        finally:
            svc.stop(drain=False)

    def test_all_poison_bin_fails_every_member_alone(self):
        """A genuinely down engine (every singleton fails) still
        fails everything and still feeds the breaker."""
        svc = _service(batch_window_s=0.3, max_batch=4)
        svc.start()
        calls = []

        def all_fail(reqs, params):
            calls.append([r.id for r in reqs])
            raise RuntimeError("engine down")

        svc._run_batch = all_fail
        try:
            rids = [svc.submit(_instance(8, 70 + i), params=PARAMS)
                    for i in range(4)]
            for rid in rids:
                assert _wait_done(svc, rid)["status"] == "ERROR"
            # Bisection bottoms out at singletons: every request saw
            # an isolated attempt.
            singles = [c for c in calls if len(c) == 1]
            assert {c[0] for c in singles} == set(rids)
        finally:
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# graceful drain under concurrent load (satellite 3)


class TestDrainUnderLoad:
    N_SUBMITTERS = 6

    def test_stop_drain_races_submitters_zero_lost(self, tmp_path):
        """6 submitter threads racing ``stop(drain=True)``: every id
        submit() acknowledged either completes or survives in the
        journal as replayable — zero lost, zero duplicated."""
        d = str(tmp_path)
        svc = _service(journal_dir=d, batch_window_s=0.01,
                       max_batch=4, max_queue=512)
        svc.start()
        real = svc._run_batch

        def slowed(reqs, params):
            time.sleep(0.05)  # keep a backlog alive at stop time
            return real(reqs, params)

        svc._run_batch = slowed
        accepted = [[] for _ in range(self.N_SUBMITTERS)]
        refused = [0] * self.N_SUBMITTERS
        stopping = threading.Event()

        def submitter(k):
            i = 0
            while not stopping.is_set():
                try:
                    rid = svc.submit(
                        _instance(8, 100 + 7 * k + i), params=PARAMS,
                        request_id=f"load-{k}-{i}")
                except Exception:
                    # No ack, no durability promise: a submit that
                    # raced the shutdown (journal closed / queue
                    # full) was REFUSED, not lost.
                    refused[k] += 1
                else:
                    accepted[k].append(rid)
                i += 1

        threads = [threading.Thread(target=submitter, args=(k,))
                   for k in range(self.N_SUBMITTERS)]
        for t in threads:
            t.start()
        time.sleep(0.6)  # let a real backlog build
        stopping.set()
        summary = svc.stop(drain=True, timeout=3.0)
        for t in threads:
            t.join(timeout=10)
        acked = {rid for lane in accepted for rid in lane}
        assert len(acked) == sum(len(lane) for lane in accepted), \
            "duplicate ack"
        assert acked, "load test produced no accepted requests"
        finished = set()
        woken = set()
        for rid in acked:
            try:
                result = svc.result(rid)
            except KeyError:
                result = None
            assert result is not None, (
                f"acked request {rid} has no result after stop — a "
                "waiter would have slept out its whole window")
            if result["status"] == "FINISHED":
                finished.add(rid)
            else:
                # Not completed in-process: stop() must have woken it
                # as REPLAYABLE (the journal still holds it).
                assert result["status"] == "REPLAYABLE"
                woken.add(rid)
        jnl, pending = RequestJournal.recover(d)
        jnl.close()
        replayable = {r["id"] for r in pending}
        assert woken == replayable, (
            "REPLAYABLE wake-set must equal the journal's pending "
            f"set: {sorted(woken ^ replayable)[:5]}")
        # The accounting identity: acked = finished ⊎ replayable.
        assert finished | replayable == acked, (
            f"lost requests: "
            f"{sorted(acked - finished - replayable)[:5]}")
        assert not finished & replayable, (
            f"duplicated requests: "
            f"{sorted(finished & replayable)[:5]}")
        assert summary["failed_pending"] == 0, \
            "journaled service must never hard-fail pending work"
        assert summary["replayable"] == len(replayable)

    def test_stop_wakes_result_waiters_as_replayable(self, tmp_path):
        """A thread blocked in ``result(wait=...)`` when a journaled
        stop leaves its request replayable must be woken promptly
        with a REPLAYABLE result — not sleep out its whole window for
        an answer this process can no longer produce."""
        svc = _service(journal_dir=str(tmp_path),
                       batch_window_s=0.01, max_batch=2)
        svc.start()
        gate = threading.Event()
        real = svc._run_batch
        svc._run_batch = lambda reqs, params: (
            gate.wait(30), real(reqs, params))[1]
        rid = svc.submit(_instance(8, 950), params=PARAMS)
        out = {}
        waiter = threading.Thread(
            target=lambda: out.setdefault(
                "res", svc.result(rid, wait=30.0)))
        waiter.start()
        time.sleep(0.1)
        t0 = time.monotonic()
        svc.stop(drain=False, timeout=0.5)
        waiter.join(timeout=5.0)
        gate.set()  # release the parked scheduler thread
        assert not waiter.is_alive(), \
            "result() waiter still asleep after stop()"
        assert time.monotonic() - t0 < 5.0
        assert out["res"]["status"] == "REPLAYABLE"
        assert "recover" in out["res"]["error"]
        jnl, pending = RequestJournal.recover(str(tmp_path))
        jnl.close()
        assert rid in {r["id"] for r in pending}, \
            "the woken request must still replay on --recover"

    def test_journalless_stop_fails_pending_with_error(self):
        """Without a journal the same shutdown fails still-queued
        requests with an explicit error — never silence."""
        svc = _service(batch_window_s=0.01, max_batch=2,
                       max_queue=64)
        svc.start()
        real = svc._run_batch
        svc._run_batch = lambda reqs, params: (
            time.sleep(0.2), real(reqs, params))[1]
        rids = [svc.submit(_instance(8, 300 + i), params=PARAMS)
                for i in range(8)]
        summary = svc.stop(drain=False)
        statuses = {}
        for rid in rids:
            try:
                result = svc.result(rid)
            except KeyError:
                result = None
            if result is not None:
                statuses[rid] = result["status"]
        assert summary["replayable"] == 0
        errored = [r for r, s in statuses.items() if s == "ERROR"]
        assert len(errored) == summary["failed_pending"]
        for rid in errored:
            assert "stopped" in svc.result(rid)["error"]


# ------------------------------------------------------------------ #
# front-end regressions: strict wire-field validation


class TestHttpStrictFields:
    def _front(self, svc):
        from pydcop_tpu.serving.http import ServeFrontEnd

        return ServeFrontEnd(svc, port=0).start()

    def _post(self, url, body):
        req = urllib.request.Request(
            url + "/solve", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as err:
            return err.code, json.loads(err.read())

    @pytest.mark.parametrize("field,value", [
        ("timeout", "thirty"), ("timeout", None), ("timeout", -1),
        ("timeout", 0), ("timeout", []), ("timeout", "inf"),
        ("deadline_s", "soon"), ("deadline_s", -2),
        ("deadline_s", 0), ("deadline_s", {}),
        ("deadline_s", float("inf")), ("deadline_s", float("nan")),
    ])
    def test_malformed_wire_field_is_400(self, field, value):
        """Regression (ISSUE 8 satellite): a malformed ``timeout``
        was silently coerced to 30.0 by a bare except — now every
        malformed wire field is a 400 naming the field, ledgered as
        ``rejected_bad_request``, with nothing submitted behind it."""
        svc = _service()
        svc.start()
        front = self._front(svc)
        try:
            before = svc._req_total.value(
                status="rejected_bad_request")
            code, body = self._post(front.url, {
                "dcop": dcop_yaml(_instance(8, 1)),
                "wait": True, field: value, "params": PARAMS,
            })
            assert code == 400
            assert field in body["error"]
            assert svc.stats()["tracked_requests"] == 0, \
                "a 400 must not leave an orphaned accepted request"
            after = svc._req_total.value(
                status="rejected_bad_request")
            assert after == before + 1
        finally:
            front.stop()
            svc.stop(drain=False)

    def test_valid_timeout_still_waits(self):
        svc = _service()
        svc.start()
        front = self._front(svc)
        try:
            code, body = self._post(front.url, {
                "dcop": dcop_yaml(_instance(8, 2)),
                "wait": True, "timeout": 60, "params": PARAMS,
            })
            assert code == 200 and body["status"] == "FINISHED"
        finally:
            front.stop()
            svc.stop(drain=False)

    def test_journal_append_failure_is_500_not_400(self, tmp_path):
        """A server-side journal failure (disk full, closed file)
        must surface as a 500 — a 400 would tell a well-behaved
        client its valid request is malformed and to stop
        retrying."""
        svc = _service(journal_dir=str(tmp_path))
        svc.start()
        front = self._front(svc)
        try:
            svc._journal._f.close()  # every append now fails
            code, body = self._post(front.url, {
                "dcop": dcop_yaml(_instance(8, 5)), "params": PARAMS,
            })
            assert code == 500
            assert "journal" in body["error"]
            assert svc.stats()["tracked_requests"] == 0, \
                "a failed submit must not leave an orphaned request"
        finally:
            front.stop()
            svc.stop(drain=False)

    def test_expired_request_is_504_on_the_wire(self):
        svc = _service()
        svc.start()
        gate = threading.Event()
        real = svc._run_batch
        svc._run_batch = lambda reqs, params: (
            gate.wait(30), real(reqs, params))[1]
        front = self._front(svc)
        try:
            code, _ = self._post(front.url, {
                "dcop": dcop_yaml(_instance(8, 4)),
                "params": PARAMS,
            })
            assert code == 202
            time.sleep(0.2)  # scheduler now blocked in dispatch
            code, body = self._post(front.url, {
                "dcop": dcop_yaml(_instance(9, 3)),
                "deadline_s": 0.01, "params": PARAMS,
            })
            assert code == 202
            rid = body["id"]
            time.sleep(0.15)
            gate.set()
            deadline = time.monotonic() + 30
            code = None
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                            front.url + f"/result/{rid}",
                            timeout=10) as resp:
                        if resp.status == 200:
                            code = 200
                            break
                except urllib.error.HTTPError as err:
                    if err.code == 504:
                        code = 504
                        body = json.loads(err.read())
                        break
                    raise
                time.sleep(0.05)
            assert code == 504
            assert body["status"] == "EXPIRED"
        finally:
            front.stop()
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# sentinel: recovery-latency series are judged lower-is-better


class TestRecoverySentinelSeries:
    def _write(self, root, replay, shardrec):
        for i, (rv, sv) in enumerate(zip(replay, shardrec)):
            doc = {"n": i, "parsed": {
                "value": 800.0 + i, "backend": "cpu",
                "serve_recovery_replay_s": rv,
                "shard_recovery_s": sv,
                "sharded_backend": "cpu",
            }}
            with open(os.path.join(
                    root, f"BENCH_r{i:02d}.json"), "w") as f:
                json.dump(doc, f)

    def _sentinel(self):
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools"))
        import bench_sentinel

        return bench_sentinel

    def test_faster_recovery_is_never_a_regression(self, tmp_path):
        bench_sentinel = self._sentinel()
        d = str(tmp_path / "ok")
        os.makedirs(d)
        self._write(d, [0.5, 0.52, 0.48, 0.5, 0.2],
                    [0.02, 0.021, 0.019, 0.02, 0.01])
        report = bench_sentinel.run_check(d)
        assert report["series"]["serve_recovery:cpu"]["verdict"] \
            == "ok"
        assert report["series"]["shard_recovery:cpu"]["verdict"] \
            == "ok"
        assert not report["failed"]

    def test_recovery_time_spike_regresses(self, tmp_path):
        """A SLOWER recovery regresses on its own: the polarity is
        inverted relative to the throughput families."""
        bench_sentinel = self._sentinel()
        d = str(tmp_path / "bad")
        os.makedirs(d)
        self._write(d, [0.5, 0.52, 0.48, 0.5, 2.5],
                    [0.02, 0.021, 0.019, 0.02, 0.02])
        report = bench_sentinel.run_check(d)
        assert report["series"]["serve_recovery:cpu"]["verdict"] \
            == "regressed"
        assert report["failed"]
        assert any("serve_recovery[cpu]" in line
                   and "ceiling" in line
                   for line in report["lines"])

    def test_history_without_recovery_metric_unaffected(
            self, tmp_path):
        """Pre-PR-8 rows carry no recovery keys: the series simply
        starts later, never crashes the sentinel."""
        bench_sentinel = self._sentinel()
        d = str(tmp_path / "old")
        os.makedirs(d)
        for i in range(4):
            doc = {"n": i, "parsed": {
                "value": 800.0 + i, "backend": "cpu"}}
            with open(os.path.join(d, f"BENCH_r{i:02d}.json"),
                      "w") as f:
                json.dump(doc, f)
        report = bench_sentinel.run_check(d)
        assert "serve_recovery:cpu" not in report["series"]
        assert "shard_recovery:cpu" not in report["series"]
        assert not report["failed"]

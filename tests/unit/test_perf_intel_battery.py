"""Performance-intelligence battery: XLA cost attribution, live
telemetry endpoint, multi-process trace merge/diff, bench regression
sentinel, and the observability hardening satellites.

Acceptance targets (ISSUE 5): a ``run_checkpointed`` solve on CPU
records per-segment XLA cost/memory-analysis metrics (or an explicit
``unavailable`` marker); ``/metrics`` scraped mid-run parses with a
growing cycle counter (the mid-run leg lives in tools/trace_demo.py,
the endpoint contract here); ``pydcop trace merge`` of two
concurrent-process traces yields one well-nested trace with distinct
lanes; the bench sentinel passes on the repo's real history and fails
on a synthetic 30% regression; histogram Prometheus output survives a
promtool-style parser including ``+Inf``/``le``/escaping; and the
metrics registry + tracer lose nothing under 8-thread concurrency.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from pydcop_tpu.observability.metrics import (
    CycleSnapshotter,
    Histogram,
    MetricsRegistry,
    registry as global_registry,
)
from pydcop_tpu.observability.profiler import (
    XlaCostProfiler,
    key_str,
    profiler,
)
from pydcop_tpu.observability.server import (
    TelemetryServer,
    health_verdict,
    set_health_provider,
)
from pydcop_tpu.observability.trace import (
    HEADER_KEY,
    TraceFileError,
    Tracer,
    check_well_nested,
    diff_trace_summaries,
    load_trace,
    load_trace_file,
    merge_traces,
    tracer,
    trace_header,
)

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_sentinel  # noqa: E402  (tools/ is not a package)


@pytest.fixture(autouse=True)
def _clean_observability():
    """Tracer off, profiler off+cleared, health provider cleared,
    registry inactive around every test.  The registry flag is
    NORMALIZED to False at setup (not just restored at teardown):
    a battery that ran earlier in the process and leaked
    ``active=True`` — any started-service crash simulation can —
    must not change what this battery's tests observe."""
    tracer.disable()
    tracer.clear()
    profiler.enabled = False
    profiler.clear()
    set_health_provider(None)
    was_active = global_registry.active
    global_registry.active = False
    yield
    tracer.disable()
    tracer.clear()
    profiler.enabled = False
    profiler.clear()
    set_health_provider(None)
    global_registry.active = was_active


def _tiny_engine(n_vars=6):
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import constraint_from_str
    from pydcop_tpu.engine.compile import compile_dcop
    from pydcop_tpu.engine.runner import MaxSumEngine

    d = Domain("c", "", list(range(3)))
    dcop = DCOP("perfintel", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(n_vars):
        j = (i + 1) % n_vars
        dcop.add_constraint(constraint_from_str(
            f"c{i}", f"3 if v{i} == v{j} else 0",
            [variables[i], variables[j]],
        ))
    graph, meta = compile_dcop(dcop, noise_level=0.01)
    return MaxSumEngine(graph, meta)


# ------------------------------------------------------------------ #
# XLA cost attribution


class TestXlaCostAttribution:
    def test_run_checkpointed_records_per_segment_cost(self):
        """The acceptance criterion: a CPU run_checkpointed solve
        carries measured flops/bytes/peak metrics per segment key."""
        profiler.enabled = True
        engine = _tiny_engine()
        res = engine.run_checkpointed(
            max_cycles=30, segment_cycles=10,
            stop_on_convergence=False)
        xla = res.metrics.get("xla_cost")
        assert xla, "no xla_cost in DeviceRunResult.metrics"
        seg_keys = [k for k in xla if k.startswith("('segment'")]
        assert seg_keys
        for k in seg_keys:
            entry = xla[k]
            # CPU XLA supports cost analysis in this image; were it to
            # stop, the explicit marker is the accepted alternative.
            if entry["available"]:
                assert entry["flops"] > 0
                assert entry["bytes_accessed"] > 0
                assert entry["peak_bytes"] > 0
            else:
                assert entry["reason"]

    def test_flops_counted_per_loop_body_not_per_trip(self):
        """bench.py treats XLA flops as per-cycle numbers because XLA
        counts a while-loop body once; pin that invariant so a future
        XLA that scales by trip count fails HERE, not silently in a
        bench line."""
        profiler.enabled = True
        engine = _tiny_engine()
        for cycles in (8, 16):
            engine.run_checkpointed(
                max_cycles=cycles, segment_cycles=cycles,
                stop_on_convergence=False)
        entries = profiler.snapshot()
        flops = {
            k: v["flops"] for k, v in entries.items()
            if k.startswith("('segment'") and v.get("available")
        }
        assert len(flops) == 2
        a, b = sorted(flops.values())
        assert a == pytest.approx(b, rel=0.01), (
            "XLA flops now scale with trip count; bench.py's "
            "per-cycle normalization must divide by cycles")

    def test_unavailable_marker_on_analysis_failure(self, monkeypatch):
        profiler.enabled = True
        global_registry.active = True
        monkeypatch.setattr(
            XlaCostProfiler, "_analyze",
            staticmethod(lambda fn, args: (_ for _ in ()).throw(
                RuntimeError("backend said no"))))
        engine = _tiny_engine()
        res = engine.run_checkpointed(
            max_cycles=10, segment_cycles=10,
            stop_on_convergence=False)
        entries = list(res.metrics["xla_cost"].values())
        assert entries
        assert all(e["available"] is False for e in entries)
        assert "backend said no" in entries[0]["reason"]
        assert global_registry.value(
            "pydcop_xla_analysis_unavailable_total") >= 1

    def test_disabled_profiler_records_nothing(self):
        engine = _tiny_engine()
        res = engine.run_checkpointed(
            max_cycles=10, segment_cycles=10,
            stop_on_convergence=False)
        assert "xla_cost" not in res.metrics
        assert profiler.snapshot() == {}

    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("PYDCOP_XLA_PROFILE", "0")
        profiler.enabled = True
        assert profiler.enabled is False
        monkeypatch.setenv("PYDCOP_XLA_PROFILE", "1")
        profiler.enabled = False
        assert profiler.enabled is True

    def test_flops_counter_exported(self):
        profiler.enabled = True
        global_registry.active = True
        engine = _tiny_engine()
        engine.run_checkpointed(max_cycles=10, segment_cycles=10,
                                stop_on_convergence=False)
        metric = global_registry.get("pydcop_xla_flops_total")
        assert metric is not None
        assert sum(v for _, v in metric.samples()) > 0

    def test_registry_untouched_without_active(self):
        """profiler on + registry inactive (the bench.py mode): cost
        entries flow through DeviceRunResult only — no key-labeled
        series leak into the shared registry for a later solve's
        .prom dump."""
        global_registry.active = False
        profiler.enabled = True
        before = global_registry.get("pydcop_xla_flops_total")
        before_n = (sum(v for _, v in before.samples())
                    if before else 0.0)
        engine = _tiny_engine()
        res = engine.run_checkpointed(max_cycles=10, segment_cycles=10,
                                      stop_on_convergence=False)
        assert res.metrics["xla_cost"]  # entries still delivered
        after = global_registry.get("pydcop_xla_flops_total")
        after_n = (sum(v for _, v in after.samples())
                   if after else 0.0)
        assert after_n == before_n

    def test_jit_compile_span_carries_cost(self):
        profiler.enabled = True
        tracer.enable()
        engine = _tiny_engine()
        engine.run_checkpointed(max_cycles=10, segment_cycles=10,
                                stop_on_convergence=False)
        tracer.disable()
        compiles = [e for e in tracer.events()
                    if e["name"] == "jit_compile"]
        assert compiles
        assert any("xla_cost" in (e.get("args") or {})
                   for e in compiles)

    def test_warm_cold_accounting_per_key(self):
        global_registry.active = True
        engine = _tiny_engine()
        engine.run_checkpointed(max_cycles=20, segment_cycles=10,
                                stop_on_convergence=False)
        calls = global_registry.get("pydcop_jit_calls_total")
        assert calls is not None
        cold = [(k, v) for k, v in calls.samples()
                if ("warmth", "cold") in k]
        warm = [(k, v) for k, v in calls.samples()
                if ("warmth", "warm") in k]
        assert cold and warm
        secs = global_registry.get("pydcop_jit_compile_seconds_total")
        assert sum(v for _, v in secs.samples()) > 0

    def test_dynamic_engine_records_cost(self):
        from pydcop_tpu.dcop.objects import Domain, Variable
        from pydcop_tpu.dcop.relations import constraint_from_str
        from pydcop_tpu.engine.dynamic import DynamicMaxSumEngine

        profiler.enabled = True
        d = Domain("c", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        c = constraint_from_str("c", "1 if x == y else 0", [x, y])
        engine = DynamicMaxSumEngine([x, y], [c])
        res = engine.run(max_cycles=10)
        assert "xla_cost" in res.metrics
        entry = list(res.metrics["xla_cost"].values())[0]
        assert entry["available"] in (True, False)

    def test_roofline_measured_override(self):
        from pydcop_tpu.engine.roofline import roofline_report

        engine = _tiny_engine()
        graph = engine.graph
        model = roofline_report(graph, 100.0, "cpu")
        assert model["cost_source"] == "model"
        assert "model_flops_per_cycle" not in model
        measured = roofline_report(
            graph, 100.0, "cpu",
            measured={"flops_per_cycle": 1234.0,
                      "bytes_per_cycle": 5678.0})
        assert measured["cost_source"] == "xla"
        assert measured["flops_per_cycle"] == 1234.0
        assert measured["bytes_per_cycle"] == 5678.0
        assert measured["model_flops_per_cycle"] == \
            model["flops_per_cycle"]
        # Empty/None measured: clean model fallback.
        assert roofline_report(graph, 100.0, "cpu", measured={})[
            "cost_source"] == "model"

    def test_compile_cache_metrics(self):
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import Domain, Variable
        from pydcop_tpu.dcop.relations import constraint_from_str
        from pydcop_tpu.engine.compile import compile_dcop

        global_registry.active = True
        d = Domain("c", "", [0, 1])
        dcop = DCOP("cachemetrics", objective="min")
        x, y = Variable("x", d), Variable("y", d)
        dcop.add_variable(x)
        dcop.add_variable(y)
        dcop.add_constraint(
            constraint_from_str("k", "x + y", [x, y]))
        counter = global_registry.counter("pydcop_compile_cache_total")
        before_hit = counter.value(outcome="hit")
        compile_dcop(dcop)
        compile_dcop(dcop)
        assert counter.value(outcome="hit") > before_hit


# ------------------------------------------------------------------ #
# live telemetry endpoint


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


class TestTelemetryServer:
    def test_metrics_endpoint_serves_prometheus(self):
        reg = MetricsRegistry()
        reg.counter("t_total", "help me").inc(7, kind="x")
        with TelemetryServer(port=0, registry=reg) as srv:
            assert srv.port and srv.port > 0
            status, body = _get(f"{srv.url}/metrics")
        assert status == 200
        assert '# TYPE t_total counter' in body
        assert 't_total{kind="x"} 7' in body

    def test_port_zero_assigns_distinct_ports(self):
        with TelemetryServer(port=0) as a, TelemetryServer(port=0) as b:
            assert a.port != b.port

    def test_healthz_default_ok(self):
        with TelemetryServer(port=0) as srv:
            status, body = _get(f"{srv.url}/healthz")
        assert status == 200
        assert json.loads(body)["status"] == "ok"

    def test_healthz_rolls_up_provider_statuses(self):
        set_health_provider(lambda: {
            "statuses": {"a1": "alive", "a2": "suspect"}})
        assert health_verdict()["status"] == "degraded"
        set_health_provider(lambda: {
            "statuses": {"a1": "dead"}})
        with TelemetryServer(port=0) as srv:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"{srv.url}/healthz")
            assert err.value.code == 503
            assert json.loads(err.value.read())["status"] == "failing"

    def test_healthz_survives_broken_provider(self):
        set_health_provider(lambda: 1 / 0)
        verdict = health_verdict()
        assert verdict["status"] == "unknown"

    def test_unknown_path_404(self):
        with TelemetryServer(port=0) as srv:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"{srv.url}/nope")
            assert err.value.code == 404

    def test_events_streams_cycle_snapshots(self):
        with TelemetryServer(port=0) as srv:
            # Private registry: the snapshotter must not advance the
            # process-global cycle counter other tests assert on.
            snapshotter = CycleSnapshotter(reg=MetricsRegistry())
            got = []

            def reader():
                req = urllib.request.urlopen(
                    f"{srv.url}/events", timeout=10)
                while len(got) < 2:
                    line = req.readline()
                    if line.startswith(b"data: "):
                        got.append(json.loads(line[6:]))

            thread = threading.Thread(target=reader, daemon=True)
            thread.start()
            deadline = time.time() + 5
            cycle = 0
            # Keep emitting until the reader has subscribed and seen
            # two events (subscription timing is not observable).
            while len(got) < 2 and time.time() < deadline:
                cycle += 10
                snapshotter(cycle, float(100 - cycle))
                time.sleep(0.05)
            thread.join(timeout=5)
        assert len(got) >= 2
        assert got[1]["cycle"] > got[0]["cycle"]
        assert "cost" in got[0]

    def test_observability_session_serves(self, tmp_path):
        from pydcop_tpu.observability import ObservabilitySession

        session = ObservabilitySession(serve_port=0).start()
        try:
            assert session.server is not None
            status, body = _get(f"{session.server.url}/metrics")
            assert status == 200
            # Serving implies the profiler + detail instrumentation.
            assert global_registry.active is True
            assert profiler.enabled is True
        finally:
            session.finish()
        assert session.server is None

    def test_session_start_failure_leaks_nothing(self):
        """A server bind failure out of start() must leave the
        process-wide tracer/registry/profiler flags untouched —
        api.solve's caller never gets a session, so finish() never
        runs."""
        from pydcop_tpu.observability import ObservabilitySession

        blocker = TelemetryServer(port=0).start()
        try:
            session = ObservabilitySession(
                trace_path="never.json", metrics_path="never.jsonl",
                serve_port=blocker.port)
            with pytest.raises(OSError):
                session.start()
        finally:
            blocker.stop()
        assert tracer.enabled is False
        assert global_registry.active is False
        assert profiler.enabled is False

    def test_thread_backend_serve_only_feeds_snapshotter(self):
        """serve_metrics without metrics_file on the thread backend
        still wires the orchestrator's CycleSnapshotter, so /metrics
        and /events have live cycle/cost data to serve."""
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
        from pydcop_tpu.dcop.relations import constraint_from_str
        from pydcop_tpu.api import solve

        d = Domain("c", "", ["R", "G", "B"])
        dcop = DCOP("serveonly", objective="min")
        variables = [Variable(f"v{i}", d) for i in range(3)]
        for v in variables:
            dcop.add_variable(v)
        for i in range(2):
            dcop.add_constraint(constraint_from_str(
                f"c{i}", f"10 if v{i} == v{i + 1} else 0",
                [variables[i], variables[i + 1]]))
        # oneagent distribution: one agent per computation node
        # (3 variables + 2 factors).
        dcop.add_agents([AgentDef(f"a{i}") for i in range(5)])
        before = global_registry.value("pydcop_cycles_total")
        res = solve(dcop, "amaxsum", backend="thread", timeout=4.0,
                    serve_metrics=0)
        assert res["assignment"]
        assert global_registry.value("pydcop_cycles_total") > before

    def test_cli_exposes_serve_metrics_knob(self):
        import argparse

        from pydcop_tpu.commands import solve as solve_cmd

        parser = argparse.ArgumentParser()
        parser.add_argument("--output", default=None)
        parser.add_argument("--timeout", type=float, default=None)
        sub = parser.add_subparsers()
        solve_cmd.set_parser(sub)
        args = parser.parse_args(
            ["solve", "-a", "maxsum", "--serve_metrics", "0", "f.yaml"])
        assert args.serve_metrics == 0


# ------------------------------------------------------------------ #
# multi-process trace aggregation


def _spawn_trace(path, span_name, fmt="chrome"):
    """Export a small trace from a REAL second process."""
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from pydcop_tpu.observability.trace import tracer\n"
        "import time\n"
        "tracer.enable()\n"
        f"with tracer.span({span_name!r}, 'proc'):\n"
        "    time.sleep(0.002)\n"
        "    with tracer.span('inner', 'proc'):\n"
        "        tracer.instant('mark', 'proc')\n"
        "tracer.disable()\n"
        f"tracer.export({str(path)!r}, {fmt!r})\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True,
                   cwd=REPO, timeout=120)


class TestTraceAggregation:
    def test_exports_carry_header(self, tmp_path):
        tracer.enable()
        with tracer.span("s", "t"):
            pass
        tracer.disable()
        chrome = tmp_path / "t.json"
        jsonl = tmp_path / "t.jsonl"
        tracer.export_chrome(str(chrome))
        tracer.export_jsonl(str(jsonl))
        for path in (chrome, jsonl):
            header, events = load_trace(str(path))
            assert header["pid"] == os.getpid()
            assert header["host"]
            assert header["anchor_unix_us"] > 0
            assert header["anchor_perf_us"] >= 0
            assert len(events) == 1
        # Raw JSONL: the header is line 1, and load_trace_file
        # excludes it from the event list.
        first = json.loads(
            jsonl.read_text().splitlines()[0])
        assert HEADER_KEY in first
        assert all("ph" in e for e in load_trace_file(str(jsonl)))

    def test_merge_two_process_traces(self, tmp_path):
        """Acceptance: merging two concurrent-process traces yields a
        single well-nested trace with distinct lanes."""
        mine = tmp_path / "local.json"
        other = tmp_path / "other.json"
        tracer.enable()
        with tracer.span("local_phase", "proc"):
            with tracer.span("inner", "proc"):
                pass
        tracer.disable()
        tracer.export_chrome(str(mine))
        _spawn_trace(other, "other_phase")
        out = tmp_path / "merged.json"
        info = merge_traces([str(mine), str(other)], str(out))
        assert info["files"] == 2
        assert info["anchored"] == 2
        header, events = load_trace(str(out))
        assert header["merged_from"][0]["clock_anchor"] is True
        check_well_nested(events)
        by_name = {e["name"]: e for e in events if e.get("ph") == "X"}
        assert {"local_phase", "other_phase", "inner"} <= set(by_name)
        # Distinct lanes: the two processes' spans never share a tid.
        assert by_name["local_phase"]["tid"] != \
            by_name["other_phase"]["tid"]
        # Span ids are namespaced per file — no cross-process
        # collision even though both processes count from 1.
        ids = [e["args"]["span_id"] for e in events
               if e.get("ph") == "X"]
        assert len(ids) == len(set(ids))

    def test_merge_corrects_clock_offset(self, tmp_path):
        """Two synthetic traces whose perf epochs differ by an hour
        but whose anchors say they ran simultaneously must land
        interleaved, not an hour apart."""
        def write(path, perf_base_us, anchor_unix_us):
            header = dict(trace_header())
            header["anchor_perf_us"] = float(perf_base_us)
            header["anchor_unix_us"] = float(anchor_unix_us)
            rows = [{HEADER_KEY: header}]
            rows.append({"name": "work", "cat": "t", "ph": "X",
                         "ts": perf_base_us + 100.0, "dur": 50.0,
                         "id": 1, "parent": 0, "tid": 1, "args": {}})
            with open(path, "w", encoding="utf-8") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")

        wall = 1.7e15  # some unix epoch in µs
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        write(a, perf_base_us=0.0, anchor_unix_us=wall)
        write(b, perf_base_us=3.6e9, anchor_unix_us=wall)  # +1h epoch
        out = tmp_path / "m.json"
        merge_traces([str(a), str(b)], str(out))
        _, events = load_trace(str(out))
        ts = sorted(float(e["ts"]) for e in events)
        # Aligned: both events at ~+100µs from their anchors.
        assert abs(ts[1] - ts[0]) < 1.0

    def test_merge_needs_two_files(self, tmp_path):
        with pytest.raises(TraceFileError):
            merge_traces(["only.json"], str(tmp_path / "o.json"))

    def test_merge_mixed_anchor_degrades_not_scatters(self, tmp_path):
        """An anchored trace merged with a headerless legacy one must
        NOT land decades apart (wall-rebased vs raw perf_counter):
        alignment degrades to per-file rebase and is flagged."""
        anchored = tmp_path / "new.json"
        tracer.enable()
        with tracer.span("modern", "t"):
            pass
        tracer.disable()
        tracer.export_chrome(str(anchored))
        legacy = tmp_path / "legacy.jsonl"
        legacy.write_text(json.dumps(
            {"name": "old", "cat": "t", "ph": "X", "ts": 5_000.0,
             "dur": 10.0, "id": 1, "parent": 0, "tid": 1,
             "args": {}}) + "\n")
        out = tmp_path / "mixed.json"
        info = merge_traces([str(anchored), str(legacy)], str(out))
        assert info["aligned"] is False
        assert info["anchored"] == 1
        # Both lanes start near 0 on the merged axis: the whole span
        # is bounded by real durations, not epoch deltas.
        assert info["span_us"] < 60e6
        _, events = load_trace(str(out))
        check_well_nested(events)

    def test_merge_labels_lanes_from_chrome_thread_names(
            self, tmp_path):
        mine = tmp_path / "a.json"
        other = tmp_path / "b.json"
        tracer.enable()
        with tracer.span("s", "t"):
            pass
        tracer.disable()
        tracer.export_chrome(str(mine))
        _spawn_trace(other, "s2")
        out = tmp_path / "m.json"
        merge_traces([str(mine), str(other)], str(out))
        # Lane labels carry host:pid + the ORIGINAL thread name
        # (recovered from the chrome thread_name metadata), not a
        # bare tid number.
        raw = json.loads(out.read_text())
        labels = [e["args"]["name"] for e in raw["traceEvents"]
                  if e.get("ph") == "M"
                  and e.get("name") == "thread_name"]
        assert len(labels) == 2
        assert any("MainThread" in l for l in labels), labels

    def test_merge_tolerates_foreign_string_ids(self, tmp_path):
        """Chrome traces from other tools (JAX profiler, async
        events) carry string ids like '0x42': merge must pass them
        through, not crash on int arithmetic."""
        mine = tmp_path / "own.json"
        tracer.enable()
        with tracer.span("own", "t"):
            pass
        tracer.disable()
        tracer.export_chrome(str(mine))
        foreign = tmp_path / "foreign.json"
        foreign.write_text(json.dumps({"traceEvents": [
            {"name": "ext", "cat": "x", "ph": "X", "ts": 1.0,
             "dur": 2.0, "tid": 7, "pid": 1, "id": "0x42",
             "args": {}}]}))
        out = tmp_path / "m.json"
        info = merge_traces([str(mine), str(foreign)], str(out))
        assert info["events"] == 2
        _, events = load_trace(str(out))
        assert {e["name"] for e in events} == {"own", "ext"}

    def test_non_trace_json_object_rejected(self, tmp_path):
        bogus = tmp_path / "bogus.json"
        bogus.write_text('{"foo": 1}')
        with pytest.raises(TraceFileError, match="not a trace"):
            load_trace_file(str(bogus))
        meta_only = tmp_path / "meta.json"
        meta_only.write_text(json.dumps({"traceEvents": [
            {"name": "thread_name", "ph": "M", "tid": 1,
             "args": {"name": "x"}}]}))
        with pytest.raises(TraceFileError, match="no trace events"):
            load_trace_file(str(meta_only))

    def test_load_trace_error_contract(self, tmp_path):
        missing = tmp_path / "missing.json"
        with pytest.raises(TraceFileError, match="cannot read"):
            load_trace_file(str(missing))
        empty = tmp_path / "empty.json"
        empty.write_text("")
        with pytest.raises(TraceFileError, match="empty"):
            load_trace_file(str(empty))
        trunc = tmp_path / "trunc.jsonl"
        trunc.write_text('{"name": "a", "ph": "i", "ts": 1}\n'
                         '{"name": "b", "ph"')
        with pytest.raises(TraceFileError, match="truncated"):
            load_trace_file(str(trunc))
        garbage = tmp_path / "garbage.json"
        garbage.write_text("this is not a trace\n")
        with pytest.raises(TraceFileError):
            load_trace_file(str(garbage))

    def test_diff_flags_regression(self):
        def spans(name, n, dur_us):
            return [{"name": name, "ph": "X", "ts": i * 1000.0,
                     "dur": dur_us, "tid": 1}
                    for i in range(n)]

        a = spans("hot", 10, 1000.0) + spans("steady", 5, 2000.0)
        b = spans("hot", 10, 2000.0) + spans("steady", 5, 2000.0)
        rows = diff_trace_summaries(a, b, threshold=0.25)
        by_name = {r["name"]: r for r in rows}
        assert by_name["hot"]["regressed"] is True
        assert by_name["steady"]["regressed"] is False
        assert by_name["hot"]["delta_total_ms"] == pytest.approx(10.0)
        assert by_name["hot"]["p50_ms_b"] == pytest.approx(2.0)
        # Noise floor: a huge relative delta under min_delta_ms never
        # flags.
        tiny_a = spans("tiny", 2, 1.0)
        tiny_b = spans("tiny", 2, 10.0)
        rows = diff_trace_summaries(tiny_a, tiny_b, threshold=0.25,
                                    min_delta_ms=1.0)
        assert rows[0]["regressed"] is False

    def test_diff_one_sided_names_stay_json_serializable(self):
        """A span name absent from the baseline has no defined
        relative growth: delta_rel must be None (json-valid), never
        float('inf') (json.dumps emits the non-JSON token Infinity),
        and the absolute floor alone gates its flag."""
        only_b = [{"name": "new_span", "ph": "X", "ts": 0.0,
                   "dur": 5000.0, "tid": 1}]
        rows = diff_trace_summaries([], only_b)
        assert rows[0]["delta_rel"] is None
        assert rows[0]["regressed"] is True  # 5 ms from nothing
        doc = json.dumps({"rows": rows})
        assert "Infinity" not in doc
        json.loads(doc)  # strict round-trip

    def test_trace_cli_summary_json_merge_diff(self, tmp_path,
                                               capsys):
        from pydcop_tpu.dcop_cli import main as cli_main

        t1 = tmp_path / "one.json"
        t2 = tmp_path / "two.json"
        tracer.enable()
        with tracer.span("phase", "cli"):
            pass
        tracer.disable()
        tracer.export_chrome(str(t1))
        _spawn_trace(t2, "phase")

        rc = cli_main(["trace", "summary", "--json", str(t1)])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out.strip())
        assert doc["spans"] == 1
        assert doc["rows"][0]["name"] == "phase"

        out = tmp_path / "merged.json"
        rc = cli_main(["trace", "merge", str(out), str(t1), str(t2)])
        assert rc == 0
        capsys.readouterr()
        assert out.exists()

        rc = cli_main(["trace", "diff", "--json", str(t1), str(t2)])
        capsys.readouterr()
        assert rc in (0, 1)  # depends on measured durations

        # Clean error, not a traceback, on a truncated file.
        bad = tmp_path / "bad.json"
        bad.write_text('{"traceEvents": [')
        rc = cli_main(["trace", "summary", str(bad)])
        assert rc == 2
        err = capsys.readouterr().err
        assert "pydcop trace:" in err
        assert "Traceback" not in err


# ------------------------------------------------------------------ #
# Prometheus exposition hardening (promtool-style line parser)


_METRIC_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})? "
    r"(?P<value>[+-]?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|NaN))$"
)
_LABEL = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(?:,|$)')


def _parse_exposition(text):
    """Strict promtool-style parse: returns {(name, labels): value};
    raises AssertionError on any malformed line, un-escaped label
    value, or histogram family violation."""
    samples = {}
    types = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            assert "\n" not in line
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
            continue
        match = _METRIC_LINE.match(line)
        assert match, f"malformed sample line: {line!r}"
        labels = {}
        raw = match.group("labels")
        if raw:
            consumed = "".join(
                m.group(0) for m in _LABEL.finditer(raw))
            assert consumed == raw, f"malformed labels: {raw!r}"
            for m in _LABEL.finditer(raw):
                value = (m.group(2)
                         .replace("\\\\", "\x00")
                         .replace('\\"', '"')
                         .replace("\\n", "\n")
                         .replace("\x00", "\\"))
                labels[m.group(1)] = value
        value = match.group("value")
        samples[(match.group("name"),
                 tuple(sorted(labels.items())))] = (
            float("inf") if value == "+Inf" else float(value))
    return samples, types


class TestPrometheusExposition:
    def test_histogram_inf_bucket_and_le_labels(self):
        reg = MetricsRegistry()
        hist = reg.histogram("lat_seconds", "latency",
                             buckets=(0.1, 1.0, 5.0))
        hist.observe(0.05, op="solve")
        hist.observe(2.0, op="solve")
        hist.observe(99.0, op="solve")
        samples, types = _parse_exposition(reg.to_prometheus())
        assert types["lat_seconds"] == "histogram"
        key = lambda le: ("lat_seconds_bucket",  # noqa: E731
                          (("le", le), ("op", "solve")))
        assert samples[key("0.1")] == 1
        assert samples[key("1")] == 1
        assert samples[key("5")] == 2
        assert samples[key("+Inf")] == 3  # every observation
        assert samples[("lat_seconds_count",
                        (("op", "solve"),))] == 3
        assert samples[("lat_seconds_sum",
                        (("op", "solve"),))] == pytest.approx(101.05)
        # Cumulative: each bucket >= all lower buckets.
        assert samples[key("0.1")] <= samples[key("1")] \
            <= samples[key("5")] <= samples[key("+Inf")]

    def test_label_escaping_backslash_newline_quote(self):
        reg = MetricsRegistry()
        nasty = 'back\\slash and\nnewline and "quote"'
        reg.counter("nasty_total", "n").inc(3, path=nasty)
        reg.histogram("nasty_seconds", "n",
                      buckets=(1.0,)).observe(0.5, path=nasty)
        text = reg.to_prometheus()
        # Raw control characters never appear inside a sample line.
        for line in text.splitlines():
            assert "\n" not in line
        samples, _ = _parse_exposition(text)
        assert samples[("nasty_total",
                        (("path", nasty),))] == 3
        assert samples[("nasty_seconds_bucket",
                        (("le", "1"), ("path", nasty)))] == 1

    def test_help_text_escaped(self):
        reg = MetricsRegistry()
        reg.counter("h_total", "line one\nline two \\ backslash")
        text = reg.to_prometheus()
        help_lines = [l for l in text.splitlines()
                      if l.startswith("# HELP h_total")]
        assert help_lines == [
            "# HELP h_total line one\\nline two \\\\ backslash"]
        _parse_exposition(text)  # still parses as a whole


# ------------------------------------------------------------------ #
# thread-safety battery


class TestThreadSafety:
    N_THREADS = 8
    N_OPS = 5000

    def test_concurrent_counter_inc_loses_nothing(self):
        reg = MetricsRegistry()
        counter = reg.counter("conc_total", "t")
        barrier = threading.Barrier(self.N_THREADS)

        def work(i):
            bound = counter.bind(worker=str(i % 2))
            barrier.wait()
            for _ in range(self.N_OPS):
                counter.inc()
                bound.inc()

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(self.N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert counter.value() == self.N_THREADS * self.N_OPS
        assert (counter.value(worker="0") + counter.value(worker="1")
                == self.N_THREADS * self.N_OPS)

    def test_concurrent_histogram_observe(self):
        reg = MetricsRegistry()
        hist = reg.histogram("conc_seconds", "t", buckets=(0.5,))
        barrier = threading.Barrier(self.N_THREADS)

        def work():
            barrier.wait()
            for i in range(self.N_OPS):
                hist.observe(0.25 if i % 2 else 0.75)

        threads = [threading.Thread(target=work)
                   for _ in range(self.N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = self.N_THREADS * self.N_OPS
        assert hist.count() == total
        assert hist.sum() == pytest.approx(total * 0.5)

    def test_export_during_active_recording(self, tmp_path):
        """export_chrome while other threads record: the export is a
        consistent snapshot (valid JSON, well-formed events), no
        crash, and recording continues unhindered.  Each recorder is
        BOUNDED (an unbounded spin would grow the buffers faster than
        the ever-larger exports can serialize them)."""
        t = Tracer()
        t.enable()
        started = threading.Event()
        errors = []
        spans_per_thread = 2000

        def recorder(i):
            try:
                for _ in range(spans_per_thread):
                    with t.span(f"work{i}", "t", n=i):
                        t.instant("tick", "t")
                    started.set()
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=recorder, args=(i,))
                   for i in range(self.N_THREADS)]
        for th in threads:
            th.start()
        started.wait(10)
        rounds = 0
        while any(th.is_alive() for th in threads) and rounds < 5:
            path = tmp_path / f"live{rounds}.json"
            t.export_chrome(str(path))
            events = load_trace_file(str(path))
            for ev in events:
                assert "name" in ev and "ts" in ev
            rounds += 1
        for th in threads:
            th.join(timeout=30)
        assert rounds >= 1, "recorders finished before any export"
        assert not errors
        t.disable()
        # The buffers survived concurrent export: the final export
        # holds every span from every worker lane.
        final = tmp_path / "final.json"
        t.export_chrome(str(final))
        events = load_trace_file(str(final))
        spans = [e for e in events
                 if e["name"].startswith("work")]
        assert len(spans) == self.N_THREADS * spans_per_thread


# ------------------------------------------------------------------ #
# bench regression sentinel


def _write_history(path, values, backend="cpu", start=1):
    for i, v in enumerate(values, start):
        with open(os.path.join(path, f"BENCH_r{i:02d}.json"),
                  "w", encoding="utf-8") as f:
            json.dump({"n": i, "parsed": {
                "value": v, "backend": backend,
                "unit": "cycles/s"}}, f)


class TestBenchSentinel:
    STEADY = [900.0, 860.0, 910.0, 880.0, 895.0, 905.0]

    def test_passes_on_repo_history(self):
        report = bench_sentinel.run_check(REPO)
        assert report["failed"] is False
        assert "cpu" in report["series"]
        assert report["series"]["cpu"]["verdict"] == "ok"
        # TPU: one artifact point only — tracked separately, judged
        # insufficient rather than crashed or merged into CPU.
        assert report["series"]["tpu"]["verdict"] == "insufficient"
        assert any("bench[cpu]" in line for line in report["lines"])

    def test_fails_on_synthetic_30pct_regression(self, tmp_path):
        """The acceptance fixture: steady history, newest 30% down."""
        _write_history(str(tmp_path), self.STEADY + [0.7 * 890.0])
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is True
        assert report["series"]["cpu"]["verdict"] == "regressed"
        assert bench_sentinel.main(["--root", str(tmp_path)]) == 1

    def test_noise_within_mad_passes(self, tmp_path):
        _write_history(str(tmp_path), self.STEADY + [850.0])
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        assert bench_sentinel.main(["--root", str(tmp_path)]) == 0

    def test_backends_tracked_separately(self, tmp_path):
        _write_history(str(tmp_path), self.STEADY, backend="cpu")
        # A TPU series two orders of magnitude faster, also steady,
        # appended AFTER the cpu rounds — per-backend split means
        # neither series sees the other's values.
        _write_history(str(tmp_path),
                       [50_000.0, 52_000.0, 51_000.0, 50_500.0],
                       backend="tpu", start=len(self.STEADY) + 1)
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        assert report["series"]["cpu"]["points"] == len(self.STEADY)
        assert report["series"]["tpu"]["points"] == 4

    def test_insufficient_history_never_fails(self, tmp_path):
        _write_history(str(tmp_path), [900.0, 100.0])
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        assert report["series"]["cpu"]["verdict"] == "insufficient"

    def test_unreadable_files_skipped_not_fatal(self, tmp_path):
        _write_history(str(tmp_path), self.STEADY)
        with open(os.path.join(str(tmp_path), "BENCH_r99.json"),
                  "w", encoding="utf-8") as f:
            f.write("{torn")
        # Glob-matched but not a numbered round: ignored, not a crash.
        with open(os.path.join(str(tmp_path), "BENCH_rerun.json"),
                  "w", encoding="utf-8") as f:
            f.write("{}")
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["skipped"] == ["BENCH_r99.json"]
        assert report["failed"] is False

    def test_stale_tpu_artifact_ignored_once_tpu_rounds_exist(
            self, tmp_path):
        """BENCH_TPU_LAST.json has no position in the round
        chronology: with real TPU rounds present, a stale artifact
        must not be judged as 'the newest run' (spurious REGRESSED
        or masked real regression)."""
        _write_history(str(tmp_path),
                       [1000.0, 1050.0, 990.0, 1020.0],
                       backend="tpu")
        with open(os.path.join(str(tmp_path), "BENCH_TPU_LAST.json"),
                  "w", encoding="utf-8") as f:
            json.dump({"value": 500.0, "backend": "tpu",
                       "recorded_unix": 1.0}, f)
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["series"]["tpu"]["values"] == \
            [1000.0, 1050.0, 990.0, 1020.0]
        assert report["failed"] is False

    def test_tpu_artifact_seeds_series_without_tpu_rounds(
            self, tmp_path):
        _write_history(str(tmp_path), self.STEADY, backend="cpu")
        with open(os.path.join(str(tmp_path), "BENCH_TPU_LAST.json"),
                  "w", encoding="utf-8") as f:
            json.dump({"value": 50_000.0, "backend": "tpu"}, f)
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["series"]["tpu"]["values"] == [50_000.0]
        assert report["series"]["tpu"]["verdict"] == "insufficient"

    def test_device_fn_profile_label_is_stable(self):
        from functools import partial

        from pydcop_tpu.engine.runner import _fn_label

        def run_solver(graph, max_cycles=10):
            return graph

        assert _fn_label(run_solver) == "run_solver"
        label = _fn_label(partial(run_solver, max_cycles=99))
        assert label == "run_solver"
        assert "0x" not in label  # never a repr with an address

    def test_missing_backend_key_treated_as_cpu(self, tmp_path):
        for i, v in enumerate(self.STEADY, 1):
            with open(os.path.join(str(tmp_path),
                                   f"BENCH_r{i:02d}.json"),
                      "w", encoding="utf-8") as f:
                json.dump({"n": i, "parsed": {"value": v}}, f)
        report = bench_sentinel.run_check(str(tmp_path))
        assert list(report["series"]) == ["cpu"]

    def test_sparkline_shape(self):
        line = bench_sentinel.sparkline([1.0, 2.0, 3.0, 2.0])
        assert len(line) == 4
        assert line[0] == "▁" and line[2] == "█"
        assert bench_sentinel.sparkline([5.0, 5.0]) == "▄▄"

    def test_json_output(self, tmp_path, capsys):
        _write_history(str(tmp_path), self.STEADY)
        rc = bench_sentinel.main(["--root", str(tmp_path), "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["series"]["cpu"]["verdict"] == "ok"
        assert doc["series"]["cpu"]["values"] == self.STEADY


def _write_serving_history(path, rounds):
    """Rounds with the closed-loop serving families alongside the
    compute headline — the population the host-shift guard pools.
    Each round is a dict of parsed keys; ``value``/``backend`` are
    filled in when absent."""
    for i, parsed in enumerate(rounds, 1):
        doc = {"value": 890.0, "backend": "cpu", **parsed}
        with open(os.path.join(path, f"BENCH_r{i:02d}.json"),
                  "w", encoding="utf-8") as f:
            json.dump({"n": i, "parsed": doc}, f)


class TestHostShiftGuard:
    """Common-mode rejection for host-scheduler-bound serving legs
    (ISSUE 19): a drop shared by the whole host-bound population —
    including the envelope-off control arm — is a host-class change
    and must not gate, while an isolated family drop (which cannot
    move the population median) must still fail the sentinel."""

    STEADY = {
        "serve_problems_per_sec": 120.0,
        "serve_mixed_problems_per_sec": 270.0,
        "serve_mixed_baseline_problems_per_sec": 210.0,
        "fleet_elastic_problems_per_sec": 8.0,
    }

    def _history(self, newest):
        return [dict(self.STEADY) for _ in range(6)] + [newest]

    def test_common_mode_drop_held_not_gated(self, tmp_path):
        """Every host-bound series (and the control arm) at 55% of
        its median: a host shift — reported, estimator recorded, but
        ``failed`` stays False."""
        newest = {k: 0.55 * v for k, v in self.STEADY.items()}
        _write_serving_history(str(tmp_path), self._history(newest))
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        guard = report["host_shift"]
        assert guard["fired"] is True
        assert guard["estimator"] == pytest.approx(0.55, abs=0.01)
        assert (report["series"]["serve_mixed:cpu"]["verdict"]
                == "host-shift")
        assert (report["series"]["serve_mixed:cpu"]["gating"]
                is False)
        assert any("host-shift guard" in line
                   for line in report["lines"])
        # The compute headline was steady and still judges normally.
        assert report["series"]["cpu"]["verdict"] == "ok"
        assert bench_sentinel.main(["--root", str(tmp_path)]) == 0

    def test_isolated_drop_still_gates(self, tmp_path):
        """Only serve_mixed collapses; the rest of the population
        (control arm included) is steady, so the median ratio stays
        ~1 and the regression gates exactly as before the guard."""
        newest = dict(self.STEADY)
        newest["serve_mixed_problems_per_sec"] = (
            0.55 * self.STEADY["serve_mixed_problems_per_sec"])
        _write_serving_history(str(tmp_path), self._history(newest))
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is True
        assert report["host_shift"]["fired"] is False
        assert (report["series"]["serve_mixed:cpu"]["verdict"]
                == "regressed")
        assert bench_sentinel.main(["--root", str(tmp_path)]) == 1

    def test_compute_regression_gates_through_host_shift(
            self, tmp_path):
        """A genuine compute regression coinciding with a host shift
        still fails: the headline family is not host-bound, so the
        guard never holds it."""
        newest = {k: 0.55 * v for k, v in self.STEADY.items()}
        newest["value"] = 0.6 * 890.0
        _write_serving_history(str(tmp_path), self._history(newest))
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is True
        assert report["host_shift"]["fired"] is True
        assert report["series"]["cpu"]["verdict"] == "regressed"

    def test_control_arm_alone_never_fails(self, tmp_path):
        """The control arm regressing by itself is host evidence, not
        a PR regression — too few host-bound series for the guard to
        conclude anything, and the control family never gates."""
        rounds = [{"serve_mixed_baseline_problems_per_sec": 210.0}
                  for _ in range(6)]
        rounds.append({"serve_mixed_baseline_problems_per_sec": 80.0})
        _write_serving_history(str(tmp_path), rounds)
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        assert (report["series"]["serve_mixed_baseline:cpu"]["gating"]
                is False)


# ------------------------------------------------------------------ #
# bench probe observability satellites


class TestProbeObservability:
    def test_probe_timeout_env(self, monkeypatch):
        from pydcop_tpu.utils.cleanenv import default_probe_timeout

        monkeypatch.delenv("PYDCOP_BENCH_PROBE_TIMEOUT",
                           raising=False)
        assert default_probe_timeout() == 120.0
        assert default_probe_timeout(60) == 60
        monkeypatch.setenv("PYDCOP_BENCH_PROBE_TIMEOUT", "7.5")
        assert default_probe_timeout() == 7.5
        assert default_probe_timeout(60) == 7.5
        monkeypatch.setenv("PYDCOP_BENCH_PROBE_TIMEOUT", "bogus")
        assert default_probe_timeout(60) == 60
        monkeypatch.setenv("PYDCOP_BENCH_PROBE_TIMEOUT", "-3")
        assert default_probe_timeout(60) == 60

    def test_record_diag_counts_failures_by_reason(self, monkeypatch):
        from pydcop_tpu.utils.cleanenv import DIAG_ENV, record_diag

        monkeypatch.setenv(DIAG_ENV, "[]")
        counter = global_registry.counter(
            "pydcop_bench_probe_failures_total")
        t0 = counter.value(reason="timeout")
        e0 = counter.value(reason="init_error")
        f0 = counter.value(reason="cpu_fallback")
        record_diag("probe", tag="t", ok=False,
                    error="timeout after 120s")
        record_diag("probe", tag="t", ok=False,
                    error="exit 1: ImportError")
        record_diag("probe", tag="t", ok=True, error=None)
        record_diag("cpu_fallback", tag="t")
        record_diag("revival_probe", ok=False,
                    error="timeout after 60s")
        assert counter.value(reason="timeout") == t0 + 2
        assert counter.value(reason="init_error") == e0 + 1
        assert counter.value(reason="cpu_fallback") == f0 + 1

    def test_record_diag_emits_trace_instant(self, monkeypatch):
        from pydcop_tpu.utils.cleanenv import DIAG_ENV, record_diag

        monkeypatch.setenv(DIAG_ENV, "[]")
        tracer.enable()
        record_diag("probe", tag="t", ok=False,
                    error="timeout after 9s")
        tracer.disable()
        instants = [e for e in tracer.events()
                    if e["name"] == "bench_probe"]
        assert len(instants) == 1
        assert instants[0]["args"]["kind"] == "probe"
        assert instants[0]["args"]["ok"] is False

"""Agent-removal analysis: what broke, who can fix it, with what.

Reference parity: pydcop/reparation/removal.py
(_removal_orphaned_computations :38, _removal_candidate_agents :61,
_removal_candidate_computations_for_agt :84,
_removal_candidate_computation_info :101,
_removal_candidate_agt_info :145).
"""

from typing import Dict, List, Tuple

from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.replication.objects import ReplicaDistribution


def orphaned_computations(departed: List[str],
                          distribution: Distribution) -> List[str]:
    """Computations left without a host after `departed` agents left."""
    orphaned = []
    for agent in departed:
        orphaned.extend(distribution.computations_hosted(agent))
    return sorted(set(orphaned))


def candidate_agents(orphaned: List[str],
                     replicas: ReplicaDistribution,
                     departed: List[str]) -> Dict[str, List[str]]:
    """For each orphaned computation, the live agents holding one of
    its replicas — the only agents able to restart it."""
    departed_set = set(departed)
    candidates: Dict[str, List[str]] = {}
    for comp in orphaned:
        try:
            hosts = replicas.agents_for_computation(comp)
        except KeyError:
            hosts = []
        candidates[comp] = sorted(
            a for a in hosts if a not in departed_set
        )
    return candidates


def candidate_computations_for_agent(
    agent: str, candidates: Dict[str, List[str]]
) -> List[str]:
    """The orphaned computations `agent` could take over."""
    return sorted(c for c, agts in candidates.items() if agent in agts)


def unrepairable_computations(
    candidates: Dict[str, List[str]]
) -> List[str]:
    """Orphans with no live replica: lost until agents come back."""
    return sorted(c for c, agts in candidates.items() if not agts)


def removal_info(
    departed: List[str],
    distribution: Distribution,
    replicas: ReplicaDistribution,
) -> Tuple[List[str], Dict[str, List[str]], List[str]]:
    """One-call summary: (orphaned, candidates per orphan, lost)."""
    orphaned = orphaned_computations(departed, distribution)
    candidates = candidate_agents(orphaned, replicas, departed)
    lost = unrepairable_computations(candidates)
    return orphaned, candidates, lost

"""``pydcop consolidate`` — placeholder, implemented later this round.

Reference parity target: pydcop/commands/consolidate.py.
"""


def set_parser(subparsers):
    parser = subparsers.add_parser("consolidate", help="consolidate (not yet implemented)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    print("pydcop consolidate: not implemented yet in pydcop-tpu")
    return 3

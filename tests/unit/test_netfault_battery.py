"""Netfault battery (ISSUE 19): the injectable link-fault plane and
the epoch-fenced ownership it exists to prove.

- plan grammar: clause/partition parsing, bad specs rejected, the
  ``PYDCOP_NETFAULT`` / install() / clear() registry;
- determinism: the same seeded plan over the same call sequence
  injects the identical fault pattern (thread timing elsewhere must
  not perturb a chaos replay);
- seam semantics: drop/blackhole/partition raise the retry-safe
  :class:`NotSent`, ``lose_response`` surfaces as a plain ambiguous
  ``OSError`` *after* delivery, ``times=`` retires clauses,
  ``path=`` scopes a clause away from the probes sharing its link;
- seam coverage: nothing in ``pydcop_tpu/serving/`` opens a socket
  outside the seam (the tools/static_check.py lint, run in-process);
- epoch monotonicity: the router's per-session epoch authority only
  advances — across note/bump/floor — and fences merge by max;
- the 409 fencing surface over real HTTP: a stale-epoch PATCH and a
  PATCH against a fenced session both answer a structured 409
  (``stale_epoch: true`` + both epochs), fencing is idempotent, and
  a fence carrying a lower epoch than the copy's is itself rejected.
"""

import os

import pytest

from pydcop_tpu.serving import netfault
from pydcop_tpu.serving.netfault import FaultPlan, NotSent

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _clean_plan():
    netfault.clear()
    yield
    netfault.clear()


# ------------------------------------------------------------------ #
# plan grammar


class TestPlanGrammar:
    def test_clause_parse(self):
        p = FaultPlan.parse(
            "seed=7;link=router>replica-*,drop=0.25,delay_ms=20;"
            "link=*>hostB,lose_response=1.0,times=1,path=/solve")
        assert p.seed == 7
        assert len(p.clauses) == 2
        c0, c1 = p.clauses
        assert (c0.src, c0.dst, c0.drop, c0.delay_ms) == \
            ("router", "replica-*", 0.25, 20.0)
        assert (c1.dst, c1.lose_response, c1.times, c1.path) == \
            ("hostB", 1.0, 1, "/solve")

    def test_partition_parse(self):
        p = FaultPlan.parse("partition=host0+host1/hostB,hold_s=0.01")
        assert len(p.partitions) == 1
        part = p.partitions[0]
        assert part.group_a == ["host0", "host1"]
        assert part.group_b == ["hostB"]
        assert part.hold_s == 0.01
        assert part.severs(("router", "host0"), ("replica-2", "hostB"))
        assert part.severs(("worker", "hostB"), ("router", "host1"))
        assert not part.severs(("router", "host0"),
                               ("replica-1", "host1"))

    @pytest.mark.parametrize("spec", [
        "link=router,drop=0.1",          # no '>'
        "drop",                          # not key=value
        "link=a>b,wobble=1",             # unknown key
        "partition=justonegroup",        # no '/'
        "partition=a/b,drop=0.5",        # stray key on a partition
    ])
    def test_bad_specs_rejected(self, spec):
        with pytest.raises(ValueError):
            FaultPlan.parse(spec)

    def test_install_clear_registry(self):
        assert netfault.plan() is None
        p = netfault.install("link=a>b,drop=1.0")
        assert netfault.plan() is p
        assert netfault.counters() == {}
        netfault.clear()
        assert netfault.plan() is None
        assert netfault.counters() == {}


# ------------------------------------------------------------------ #
# determinism + fault semantics (decide() directly — no sockets)


def _pattern(plan, n=64):
    out = []
    for _ in range(n):
        try:
            post = plan.decide("router", ("replica-1", "hostB"),
                               timeout=0.01)
            out.append("L" if post["lose_response"]
                       else "D" if post["dup"] else ".")
        except NotSent:
            out.append("x")
    return "".join(out)


class TestDeterminism:
    def test_same_plan_same_sequence_same_faults(self):
        spec = "seed=11;link=*>replica-*,drop=0.3,dup=0.1"
        a = _pattern(FaultPlan.parse(spec))
        b = _pattern(FaultPlan.parse(spec))
        assert a == b
        assert "x" in a  # drops actually fired at p=0.3 over 64 draws

    def test_seed_changes_the_pattern(self):
        a = _pattern(FaultPlan.parse("seed=1;drop=0.5"))
        b = _pattern(FaultPlan.parse("seed=2;drop=0.5"))
        assert a != b

    def test_link_scoping_misses_other_links(self):
        p = FaultPlan.parse("link=router>hostB,drop=1.0")
        with pytest.raises(NotSent):
            p.decide("router", ("replica-2", "hostB"), timeout=0.01)
        assert p.decide("router", ("replica-0", "host0"),
                        timeout=0.01) == \
            {"dup": False, "lose_response": False}

    def test_times_retires_the_clause(self):
        p = FaultPlan.parse("link=*>*,lose_response=1.0,times=1")
        first = p.decide("router", "replica-0", timeout=0.01)
        assert first["lose_response"] is True
        for _ in range(5):
            post = p.decide("router", "replica-0", timeout=0.01)
            assert post["lose_response"] is False
        assert p.clauses[0].fired == 1

    def test_path_scope_spares_the_probes(self):
        p = FaultPlan.parse("link=*>*,blackhole=1,path=/solve,"
                            "hold_s=0.0")
        # The probe sharing the link is untouched...
        p.decide("router", "replica-0", timeout=0.01,
                 path="/healthz")
        # ...the scoped path is eaten.
        with pytest.raises(NotSent):
            p.decide("router", "replica-0", timeout=0.01,
                     path="/solve")
        assert p.injected() == {"blackhole": 1}

    def test_partition_is_bidirectional_notsent(self):
        p = FaultPlan.parse("partition=host0/hostB,hold_s=0.0")
        with pytest.raises(NotSent):
            p.decide(("router", "host0"), ("w", "hostB"),
                     timeout=0.01)
        with pytest.raises(NotSent):
            p.decide(("w", "hostB"), ("router", "host0"),
                     timeout=0.01)
        assert p.injected()["partition"] == 2


# ------------------------------------------------------------------ #
# seam coverage


class TestSeamCoverage:
    def test_connect_failure_is_notsent(self):
        # Port 9 unbound: a real connect refusal maps to the
        # retry-safe class, with or without a plan installed.
        with pytest.raises(NotSent):
            netfault.exchange("a", "b", "127.0.0.1", 9, "GET", "/x",
                              timeout=0.2)

    def test_injected_blackhole_never_touches_the_socket(self):
        netfault.install("link=a>b,blackhole=1,hold_s=0.0")
        with pytest.raises(NotSent):
            # Host that would hang a real connect: the injected fault
            # must fire before any socket work.
            netfault.exchange("a", "b", "203.0.113.1", 80, "GET",
                              "/x", timeout=0.05)
        assert netfault.counters() == {"blackhole": 1}

    def test_router_notsent_is_the_seam_class(self):
        from pydcop_tpu.serving import router as router_mod

        assert router_mod.ForwardNotSent is NotSent

    def test_serving_has_no_raw_socket_io(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "static_check",
            os.path.join(REPO, "tools", "static_check.py"))
        static_check = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(static_check)
        assert static_check.check_netfault_seam() == 0


# ------------------------------------------------------------------ #
# epoch monotonicity (router authority, no processes)


class TestEpochMonotonicity:
    def _router(self):
        from pydcop_tpu.serving.router import FleetRouter

        return FleetRouter(replicas=1)

    def test_note_then_bump_only_advances(self):
        router = self._router()
        assert router.session_epoch("s1") == 1
        router.note_session("s1")
        assert router.session_epoch("s1") == 1
        seen = [router.bump_epoch("s1") for _ in range(4)]
        assert seen == [2, 3, 4, 5]
        assert router.session_epoch("s1") == 5

    def test_floor_keeps_the_advance_strict(self):
        router = self._router()
        assert router.bump_epoch("s1", floor=7) == 7
        # A floor BELOW the tracked epoch still advances past it.
        assert router.bump_epoch("s1", floor=3) == 8

    def test_fences_merge_by_max(self):
        router = self._router()
        router.record_fence(0, "s1", 3)
        router.record_fence(0, "s1", 2)
        router.record_fence(0, "s2", 4)
        assert router._fences[0] == {"s1": 3, "s2": 4}


# ------------------------------------------------------------------ #
# the 409 fencing surface (real single service over HTTP)


def _path_dcop(seed=3):
    import numpy as np

    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"netfault_fence_{seed}", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(3):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[k + 1]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    batch = [{"type": "change_factor", "name": "c1",
              "table": rng.integers(0, 10, size=(3, 3))
              .astype(float).tolist()}]
    return dcop, batch


@pytest.mark.slow
class TestFencingSurface:
    def _request(self, url, method="GET", payload=None):
        import json
        import urllib.error
        import urllib.request

        data = (json.dumps(payload).encode()
                if payload is not None else None)
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as err:
            return err.code, json.loads(err.read())

    def test_stale_epoch_patch_and_fence(self):
        from pydcop_tpu import api
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        dcop, batch = _path_dcop()
        handle = api.serve(port=0, batch_window_s=0.05)
        try:
            url = handle.url
            status, body = self._request(
                url + "/session", "POST",
                {"dcop": dcop_yaml(dcop),
                 "params": {"noise": 0.01, "stability": 0.001,
                            "max_cycles": 200}})
            assert status == 201, body
            sid = body["session_id"]

            # Correct epoch applies; a stale one is a structured 409.
            status, out = self._request(
                url + f"/session/{sid}/events", "PATCH",
                {"events": batch, "epoch": 1})
            assert status == 200, out
            status, out = self._request(
                url + f"/session/{sid}/events", "PATCH",
                {"events": batch, "epoch": 99})
            assert status == 409 and out["stale_epoch"] is True, out
            assert out["session_epoch"] == 1
            assert out["request_epoch"] == 99

            # A fence below the copy's epoch is itself stale...
            status, out = self._request(
                url + "/admin/fence_session", "POST",
                {"session_id": sid, "epoch": 0})
            assert status == 409 and out["stale_epoch"] is True, out
            # ...a current-or-higher one revokes the copy, terminally
            # and idempotently.
            for _ in range(2):
                status, out = self._request(
                    url + "/admin/fence_session", "POST",
                    {"session_id": sid, "epoch": 3})
                assert status == 200, out
                assert out["status"] == "FENCED"
            status, st = self._request(url + f"/session/{sid}")
            assert st["status"] == "FENCED" and st["epoch"] == 3, st

            # Every write against the fenced copy — even carrying the
            # new epoch — answers the structured 409.
            status, out = self._request(
                url + f"/session/{sid}/events", "PATCH",
                {"events": batch, "epoch": 3})
            assert status == 409 and out["stale_epoch"] is True, out
        finally:
            handle.stop()

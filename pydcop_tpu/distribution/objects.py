"""Distribution data objects.

Reference parity: pydcop/distribution/objects.py (Distribution :36,
DistributionHints :223, ImpossibleDistributionException :269).
"""

from typing import Dict, Iterable, List, Optional

from pydcop_tpu.utils.simple_repr import SimpleRepr


class ImpossibleDistributionException(Exception):
    pass


class Distribution(SimpleRepr):
    """A mapping agent-name -> list of computation names hosted there.

    >>> d = Distribution({'a1': ['c1', 'c2'], 'a2': ['c3']})
    >>> d.agent_for('c3')
    'a2'
    >>> d.computations_hosted('a1')
    ['c1', 'c2']
    """

    def __init__(self, mapping: Dict[str, List[str]]):
        self._mapping: Dict[str, List[str]] = {
            a: list(cs) for a, cs in mapping.items()
        }

    @property
    def mapping(self) -> Dict[str, List[str]]:
        return {a: list(cs) for a, cs in self._mapping.items()}

    @property
    def agents(self) -> List[str]:
        return list(self._mapping)

    @property
    def computations(self) -> List[str]:
        return [c for cs in self._mapping.values() for c in cs]

    def agent_for(self, computation: str) -> str:
        for a, cs in self._mapping.items():
            if computation in cs:
                return a
        raise KeyError(f"No agent hosts computation {computation}")

    def computations_hosted(self, agent: str) -> List[str]:
        return list(self._mapping.get(agent, []))

    def host_on_agent(self, agent: str, computations: List[str]):
        """Add computations to an agent's hosting list.

        Hosting an already-hosted computation raises (reference
        objects.py:156-175) — a silent duplicate would corrupt
        ``agent_for``; move a computation by rebuilding the mapping.
        """
        hosted = set(self.computations)
        for c in computations:
            if c in hosted:
                raise ValueError(
                    f"Computation {c} is already hosted"
                    + (f" on agent {self.agent_for(c)}"
                       if self.is_hosted(c) else " (duplicate in call)")
                )
            hosted.add(c)
        self._mapping.setdefault(agent, []).extend(computations)

    def is_hosted(self, computations) -> bool:
        if isinstance(computations, str):
            computations = [computations]
        hosted = set(self.computations)
        return all(c in hosted for c in computations)

    def has_computation(self, computation: str) -> bool:
        return computation in set(self.computations)

    def __eq__(self, other):
        return (
            isinstance(other, Distribution)
            and self._mapping == other._mapping
        )

    def __repr__(self):
        return f"Distribution({self._mapping})"

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "mapping": self.mapping,
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["mapping"])


class DistributionHints(SimpleRepr):
    """Placement hints: must_host (agent -> comps) and host_with
    (comp -> comps that should be co-located)."""

    def __init__(self, must_host: Optional[Dict[str, List[str]]] = None,
                 host_with: Optional[Dict[str, List[str]]] = None):
        self._must_host = {a: list(c) for a, c in (must_host or {}).items()}
        host_with = host_with or {}
        # host_with is symmetric: close it over all named computations.
        closed: Dict[str, set] = {}
        for c, others in host_with.items():
            group = {c, *others}
            merged = set(group)
            for g in group:
                if g in closed:
                    merged |= closed[g]
            for g in merged:
                closed[g] = merged
        self._host_with = {
            c: sorted(group - {c}) for c, group in closed.items()
        }

    def must_host(self, agent: str) -> List[str]:
        return list(self._must_host.get(agent, []))

    def host_with(self, computation: str) -> List[str]:
        return list(self._host_with.get(computation, []))

    @property
    def must_host_map(self) -> Dict[str, List[str]]:
        return {a: list(c) for a, c in self._must_host.items()}

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "must_host": self.must_host_map,
            "host_with": {c: list(o) for c, o in self._host_with.items()},
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r.get("must_host"), r.get("host_with"))

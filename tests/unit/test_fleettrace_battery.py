"""Fleet trace plane battery (ISSUE 20): wire-propagated trace
context, lossy span shipping, the router-merged collector, and the
aggregated fleet surfaces.

- the ``X-Pydcop-Trace`` codec: roundtrip, parent annotation, and
  garbage tolerance (a malformed header must yield None, never an
  error on the request path);
- :class:`SpanShipper` is provably non-blocking and lossy-honest: a
  stalled/dead collector bounds the queue, counts every drop, and
  ``record()`` stays O(1) fast; a live collector receives everything
  with ``dropped_spans == 0``;
- :class:`FleetCollector` merges per-source lanes onto one clock
  (anchor rebase, tid namespacing, id striding) such that
  ``query_request`` reconstructs a well-nested tree from it;
- ``merge_snapshots``/``render_snapshot_prometheus`` preserve every
  per-replica sample under a ``replica`` label (the conservation
  property ``/fleet/metrics`` is built on) and render valid
  exposition text;
- ``efficiency.pooled_rollup`` sums ledgers and device-time-weights
  attainment;
- a REAL 2-replica fleet over HTTP: submit/session/SSE context
  propagation (the worker adopts the router-minted trace_id),
  ``/fleet/metrics`` conservation against the router's admission
  ledger, pooled ``/fleet/profile``, live + offline forensics (the
  ``pydcop fleet forensics`` command), unknown-request 404;
- the acceptance proof: under a seeded netfault plan that loses a
  /solve response after execution, ``/fleet/forensics/<id>`` shows
  ONE well-nested tree containing the route pick, the injected
  fault, the retry hop, the dedupe hit, and exactly one execute
  span — idempotency proven from telemetry alone.
"""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.observability import fleettrace
from pydcop_tpu.observability.fleettrace import (
    FleetCollector,
    SpanShipper,
    TraceContext,
)
from pydcop_tpu.observability.trace import query_request


def _ring(n: int, seed: int) -> DCOP:
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"ftrace_{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[(k + 1) % n]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _req(url, method="GET", payload=None, timeout=30, raw=False):
    data = (json.dumps(payload).encode()
            if payload is not None else None)
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            return resp.status, (body if raw else json.loads(body))
    except urllib.error.HTTPError as err:
        body = err.read()
        if not raw:
            try:
                body = json.loads(body)
            except ValueError:
                pass
        return err.code, body


def _tree_nodes(roots):
    for node in roots:
        yield node
        yield from _tree_nodes(node["children"])


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ------------------------------------------------------------------ #
# wire codec


class TestTraceContextCodec:
    def test_roundtrip(self):
        ctx = TraceContext("abc123")
        assert TraceContext.decode(ctx.encode()).trace_id == "abc123"
        assert TraceContext.decode(ctx.encode()).parent is None

    def test_roundtrip_with_parent(self):
        ctx = TraceContext("abc123", parent="42")
        back = TraceContext.decode(ctx.encode())
        assert (back.trace_id, back.parent) == ("abc123", "42")

    def test_garbage_tolerant(self):
        for bad in (None, "", "   ", ";;;", ";parent=5",
                    "x" * 300, 17):
            assert TraceContext.decode(bad) is None, bad

    def test_decode_headers(self):
        class Headers(dict):
            pass

        hdrs = Headers({fleettrace.HEADER: "tid9;parent=7"})
        ctx = fleettrace.decode_headers(hdrs)
        assert (ctx.trace_id, ctx.parent) == ("tid9", "7")
        assert fleettrace.decode_headers(Headers()) is None

    def test_mint_is_unique(self):
        ids = {fleettrace.mint().trace_id for _ in range(64)}
        assert len(ids) == 64


# ------------------------------------------------------------------ #
# span shipper: bounded, non-blocking, lossy-honest


class TestSpanShipper:
    def _event(self, i):
        return {"name": f"s{i}", "cat": "t", "ph": "X",
                "ts": float(i), "dur": 1.0, "id": i, "tid": 0,
                "args": {"trace_id": "t0"}}

    def test_bounded_and_fast_under_stalled_collector(self):
        """10k records against a dead collector: the queue never
        exceeds its cap, every overflow is counted, and record()
        stays O(1) — span shipping must not backpressure solves."""
        shipper = SpanShipper("test", max_queue=512, batch_max=64,
                              flush_interval_s=3600.0)
        shipper.set_target(
            f"http://127.0.0.1:{_free_port()}", "test")
        t0 = time.perf_counter()
        for i in range(10_000):
            shipper.record(self._event(i))
        elapsed = time.perf_counter() - t0
        assert len(shipper._queue) <= 512
        assert shipper.dropped_spans >= 10_000 - 512
        assert elapsed < 2.0, (
            f"record() of 10k events took {elapsed:.2f}s — the "
            "bounded queue must make drops O(1)")
        # The dead collector turns the next flush's batch into
        # counted drops, never an exception, never a retry.
        before = shipper.dropped_spans
        shipped = shipper.flush()
        assert shipped == 0
        assert shipper.dropped_spans == before + 64
        assert shipper.shipped == 0

    def test_unconfigured_url_counts_drops(self):
        shipper = SpanShipper("test", max_queue=64)
        for i in range(10):
            shipper.record(self._event(i))
        assert shipper.flush() == 0
        assert shipper.dropped_spans == 10

    def test_live_collector_receives_everything(self):
        """A reachable collector gets every queued event batch-wise
        with zero drops — lossiness is a failure-mode contract, not a
        sampling strategy."""
        from http.server import BaseHTTPRequestHandler, HTTPServer

        collector = FleetCollector()

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                raw = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                collector.ingest(json.loads(raw))
                out = b"{}"
                self.send_response(200)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def log_message(self, *a):  # noqa: D102
                pass

        server = HTTPServer(("127.0.0.1", 0), Handler)
        thread = threading.Thread(target=server.serve_forever,
                                  daemon=True)
        thread.start()
        try:
            shipper = SpanShipper("replica-9", batch_max=16)
            shipper.set_target(
                f"http://127.0.0.1:{server.server_port}",
                "replica-9")
            for i in range(40):
                shipper.record(self._event(i))
            total = 0
            while total < 40:
                n = shipper.flush()
                assert n > 0, "flush stalled with events queued"
                total += n
            assert shipper.dropped_spans == 0
            assert shipper.shipped == 40
            assert collector.sources() == ["replica-9"]
            merged = collector.merged_events()
            assert len(merged) == 40
            assert all(ev["tid"] == "replica-9:0" for ev in merged)
        finally:
            server.shutdown()
            server.server_close()

    def test_record_copies_live_events(self):
        """Recorded events are LIVE dicts on the hot path; the
        shipper must snapshot event + args at record time."""
        shipper = SpanShipper("test")
        ev = self._event(0)
        shipper.record(ev)
        ev["args"]["trace_id"] = "mutated"
        ev["name"] = "mutated"
        queued = shipper._queue[0]
        assert queued["name"] == "s0"
        assert queued["args"]["trace_id"] == "t0"


# ------------------------------------------------------------------ #
# collector merge


class TestFleetCollector:
    def _batch(self, source, anchor_unix_us, events):
        return {
            "source": source,
            "header": {"anchor_perf_us": 0.0,
                       "anchor_unix_us": anchor_unix_us},
            "dropped_spans": 0,
            "events": events,
        }

    def test_merge_rebases_and_namespaces(self):
        """Two sources with different clock anchors merge onto one
        axis: a replica event stamped 'earlier' in perf time but
        anchored later lands later; tids are namespaced and span ids
        strided so query_request can't cross-wire lanes."""
        collector = FleetCollector()
        collector.ingest(self._batch("router", 1_000_000.0, [
            {"name": "router_request", "cat": "fleet", "ph": "X",
             "ts": 0.0, "dur": 500.0, "id": 1, "tid": 5,
             "args": {"trace_id": "tA", "request": "r1"}},
        ]))
        collector.ingest(self._batch("replica-0", 1_000_100.0, [
            {"name": "serve_dispatch", "cat": "serving", "ph": "X",
             "ts": 10.0, "dur": 200.0, "id": 1, "tid": 5,
             "args": {"trace_ids": ["tA"]}},
            {"name": "serve_dedupe", "cat": "serving", "ph": "i",
             "ts": 300.0, "id": 2, "tid": 5,
             "args": {"trace_id": "tA"}},
        ]))
        merged = collector.merged_events()
        assert len(merged) == 3
        tids = {ev["tid"] for ev in merged}
        assert tids == {"router:5", "replica-0:5"}
        by_name = {ev["name"]: ev for ev in merged}
        # Anchor rebase: replica-0's perf ts=10 sits at unix
        # 1_000_110 vs the router span's 1_000_000 -> +110us.
        assert by_name["router_request"]["ts"] == pytest.approx(0.0)
        assert by_name["serve_dispatch"]["ts"] == pytest.approx(110.0)
        # Id striding keeps same-valued per-process ids distinct.
        assert (by_name["router_request"]["id"]
                != by_name["serve_dispatch"]["id"])

        doc = query_request(merged, "tA")
        assert doc["events"] == 3
        assert doc["well_nested"]
        assert doc["lanes"] == 2
        # The dispatch nests under the router span in time; the
        # dedupe instant attaches to the dispatch's lane.
        names = set(doc["names"])
        assert names == {"router_request", "serve_dispatch",
                         "serve_dedupe"}

    def test_lane_bound_and_drop_ledger(self):
        collector = FleetCollector(lane_events=100)
        events = [{"name": f"e{i}", "cat": "t", "ph": "i",
                   "ts": float(i), "id": i, "tid": 0, "args": {}}
                  for i in range(250)]
        out = collector.ingest(self._batch("replica-1", 0.0, events))
        assert out == {"accepted": 250, "source": "replica-1"}
        collector.ingest({"source": "replica-1", "header": {},
                          "dropped_spans": 17, "events": []})
        doc = collector.merged_doc()
        assert doc["sources"][0]["events"] == 100  # bounded lane
        assert doc["dropped_spans"] == 17

    def test_ingest_rejects_bad_batch(self):
        collector = FleetCollector()
        with pytest.raises(ValueError):
            collector.ingest({"source": "x", "events": 3})


# ------------------------------------------------------------------ #
# merged metrics + pooled profile (pure functions)


class TestMergeSnapshots:
    SNAPS = {
        "replica-0": {
            "pydcop_requests_total": {
                "kind": "counter",
                "samples": [
                    {"labels": {"status": "ok"}, "value": 3.0},
                    {"labels": {"status": "deduped"}, "value": 1.0},
                ]},
            "pydcop_request_latency_seconds": {
                "kind": "histogram",
                "samples": [{
                    "labels": {}, "count": 3, "sum": 0.3,
                    "buckets": {0.1: 1, 1.0: 3},
                    "exemplars": {}}]},
        },
        "replica-1": {
            "pydcop_requests_total": {
                "kind": "counter",
                "samples": [
                    {"labels": {"status": "ok"}, "value": 2.0},
                ]},
        },
    }

    def test_conservation_under_merge(self):
        """Merging must PRESERVE per-source samples (labeled, not
        summed): the /fleet/metrics conservation check — summed
        ``pydcop_requests_total`` across replica labels equals the
        router admission ledger — reads directly off the output."""
        from pydcop_tpu.observability.metrics import merge_snapshots

        merged = merge_snapshots(self.SNAPS)
        samples = merged["pydcop_requests_total"]["samples"]
        assert len(samples) == 3
        assert all("replica" in s["labels"] for s in samples)
        ok = sum(s["value"] for s in samples
                 if s["labels"]["status"] == "ok")
        assert ok == 5.0
        per_replica = {s["labels"]["replica"]: s["value"]
                       for s in samples
                       if s["labels"]["status"] == "ok"}
        assert per_replica == {"replica-0": 3.0, "replica-1": 2.0}

    def test_prometheus_render(self):
        from pydcop_tpu.observability.metrics import (
            merge_snapshots,
            render_snapshot_prometheus,
        )

        text = render_snapshot_prometheus(
            merge_snapshots(self.SNAPS))
        assert ("pydcop_requests_total{replica=\"replica-0\","
                "status=\"ok\"} 3" in text)
        assert "# TYPE pydcop_requests_total counter" in text
        assert ("pydcop_request_latency_seconds_count"
                "{replica=\"replica-0\"} 3" in text)
        assert "le=\"+Inf\"" in text


class TestPooledRollup:
    def test_sums_and_weighted_attainment(self):
        from pydcop_tpu.observability.efficiency import pooled_rollup

        docs = {
            "replica-0": {
                "backends": {"cpu": {"attainment": 0.2,
                                     "execute_s": 3.0}},
                "ledger": {"components_s": {"execute": 3.0},
                           "counts": {"requests": 4},
                           "total_s": 4.0,
                           "unaccounted_abs_s": 0.1},
                "waste_by_cause": {"padding": 0.5},
                "jit": {"cold_dispatches": 1, "warm_dispatches": 9,
                        "cold_compile_s": 2.0},
                "pipeline": {"overlap_s": 1.0, "execute_s": 3.0,
                             "dispatches": 10},
            },
            "replica-1": {
                "backends": {"cpu": {"attainment": 0.6,
                                     "execute_s": 1.0}},
                "ledger": {"components_s": {"execute": 1.0},
                           "counts": {"requests": 2},
                           "total_s": 2.0,
                           "unaccounted_abs_s": 0.0},
                "waste_by_cause": {},
                "jit": {"cold_dispatches": 0, "warm_dispatches": 5,
                        "cold_compile_s": 0.0},
                "pipeline": {"overlap_s": 0.5, "execute_s": 1.0,
                             "dispatches": 5},
            },
        }
        pooled = pooled_rollup(docs)
        assert pooled["n_replicas"] == 2
        # Device-time weighting: (0.2*3 + 0.6*1) / 4 = 0.3 — the
        # busy replica dominates.
        assert pooled["attainment"] == pytest.approx(0.3)
        assert pooled["ledger"]["components_s"]["execute"] \
            == pytest.approx(4.0)
        assert pooled["ledger"]["counts"]["requests"] == 6
        assert pooled["jit"]["warm_dispatches"] == 14
        assert pooled["pipeline"]["dispatches"] == 15
        assert set(pooled["replicas"]) == set(docs)

    def test_empty_fleet(self):
        from pydcop_tpu.observability.efficiency import pooled_rollup

        pooled = pooled_rollup({})
        assert pooled["n_replicas"] == 0
        assert pooled["attainment"] is None


# ------------------------------------------------------------------ #
# real 2-replica fleet: propagation, conservation, forensics


@pytest.fixture(scope="module")
def fleet():
    from pydcop_tpu import api

    handle = api.serve(port=0, replicas=2, batch_window_s=0.05,
                       heartbeat_s=0.2)
    try:
        yield handle
    finally:
        handle.stop()


def _ok_total(url):
    """Summed ``pydcop_requests_total{status=ok}`` across replica
    labels off the merged fleet scrape."""
    status, doc = _req(url + "/fleet/metrics?format=json")
    assert status == 200, doc
    fam = doc["metrics"].get("pydcop_requests_total",
                             {"samples": []})
    return sum(s["value"] for s in fam["samples"]
               if s["labels"].get("status") == "ok")


class TestFleetSurfaces:
    def test_metrics_conservation_against_router_ledger(self, fleet):
        """Delta-based conservation on a seeded burst: N routed
        solves move BOTH the summed replica-labeled ok-counter and
        the router's admission ledger by exactly N."""
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        url = fleet.url
        ok_before = _ok_total(url)
        routed_before = fleet.router.stats()["routed"]
        n = 4
        for i in range(n):
            status, out = _req(url + "/solve", "POST", {
                "dcop": dcop_yaml(_ring(7 + (i % 2), 40 + i)),
                "params": {"max_cycles": 50},
                "wait": True, "timeout": 120})
            assert status == 200 and out["status"] == "FINISHED", out
        assert fleet.router.stats()["routed"] - routed_before == n
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if _ok_total(url) - ok_before == n:
                break
            time.sleep(0.2)
        assert _ok_total(url) - ok_before == n, (
            "merged replica counters do not conserve the admission "
            "ledger")
        # The text rendering carries the same labeled rows.
        status, text = _req(url + "/fleet/metrics", raw=True)
        assert status == 200
        assert b'replica="replica-' in text
        assert b"pydcop_requests_total" in text

    def test_submit_propagation_and_live_forensics(self, fleet):
        """The worker adopts the router-minted trace_id (the submit
        ack's trace_id matches the forensics doc) and the live tree
        contains the route pick plus the winning replica's serve
        ledger on a separate lane."""
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        url = fleet.url
        status, ack = _req(url + "/solve", "POST", {
            "dcop": dcop_yaml(_ring(9, 77)),
            "params": {"max_cycles": 50}})
        assert status == 202, ack
        rid = ack["id"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            code, out = _req(url + f"/result/{rid}", timeout=10)
            if code == 200:
                break
            time.sleep(0.1)
        assert code == 200 and out["status"] == "FINISHED"

        doc, names = {}, set()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            code, doc = _req(url + f"/fleet/forensics/{rid}")
            if code == 200:
                names = set(doc["names"])
                if "serve_dispatch" in names:
                    break
            time.sleep(0.25)
        assert code == 200, doc
        assert doc["request_id"] == rid
        # The ack's trace_id IS the router-minted one: adoption, not
        # coincidence.
        assert doc["trace_id"] == ack["trace_id"]
        assert doc["well_nested"]
        assert doc["lanes"] >= 2, "router + replica lanes expected"
        assert "router_request" in names
        assert "router_route_pick" in names
        assert {"serve_submit", "serve_dispatch"} <= names, names
        picks = [node for node in _tree_nodes(doc["tree"])
                 if node["name"] == "router_route_pick"]
        assert picks and "reason" in picks[0]["args"]
        assert "replica" in picks[0]["args"]

    def test_session_sse_propagation(self, fleet):
        """Open/PATCH/SSE-attach all join the session's trace: one
        forensics tree per session id spanning router and worker
        lanes."""
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        url = fleet.url
        status, ack = _req(url + "/session", "POST", {
            "dcop": dcop_yaml(_ring(6, 91)),
            "params": {"max_cycles": 40}})
        assert status == 201, ack
        sid = ack["session_id"]
        try:
            code, out = _req(url + f"/session/{sid}/events", "PATCH", {
                "events": [{"type": "change_factor", "name": "c0",
                            "table": [[1, 2, 3], [4, 5, 6],
                                      [7, 8, 9]]}],
                "wait": True}, timeout=60)
            assert code == 200 and out["applied"] is True, out
            stream = urllib.request.urlopen(
                url + f"/session/{sid}/events", timeout=10)
            time.sleep(0.3)
            stream.close()

            doc, names = {}, set()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                code, doc = _req(url + f"/fleet/forensics/{sid}")
                if code == 200:
                    names = set(doc["names"])
                    if {"session_open", "session_events",
                            "session_stream_attach"} <= names:
                        break
                time.sleep(0.25)
            assert code == 200, doc
            assert doc["trace_id"] == ack["trace_id"]
            assert doc["well_nested"]
            assert "router_session_open" in names
            assert "router_session_events" in names
            assert "session_open" in names
            assert "session_events" in names
            assert "session_stream_attach" in names
        finally:
            _req(url + f"/session/{sid}", "DELETE")

    def test_fleet_profile_pools_both_replicas(self, fleet):
        status, doc = _req(fleet.url + "/fleet/profile")
        assert status == 200, doc
        assert doc["n_replicas"] == 2
        assert set(doc["replicas"]) == {"replica-0", "replica-1"}
        ledger = doc["ledger"]
        total = max(float(ledger.get("total_s") or 0.0), 1e-9)
        assert abs(float(ledger.get("unaccounted_abs_s") or 0.0)) \
            <= 0.05 * total

    def test_unknown_request_404(self, fleet):
        status, doc = _req(fleet.url + "/fleet/forensics/nosuchid")
        assert status == 404
        assert "nosuchid" in doc["error"]

    def test_offline_forensics_command(self, fleet, tmp_path,
                                       capsys):
        """Save /fleet/trace to disk, then reconstruct the tree with
        ``pydcop fleet forensics --trace FILE`` — same machinery,
        no live router needed."""
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        url = fleet.url
        status, ack = _req(url + "/solve", "POST", {
            "dcop": dcop_yaml(_ring(8, 55)),
            "params": {"max_cycles": 50},
            "wait": True, "timeout": 120})
        assert status == 200, ack
        rid = ack["id"]
        # Wait for the worker's spans to ship before snapshotting.
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            code, doc = _req(url + f"/fleet/forensics/{rid}")
            if code == 200 and "serve_dispatch" in doc["names"]:
                break
            time.sleep(0.25)
        status, trace_doc = _req(url + "/fleet/trace")
        assert status == 200
        assert trace_doc["version"] == 1
        path = tmp_path / "fleet_trace.json"
        path.write_text(json.dumps(trace_doc))

        import argparse

        from pydcop_tpu.commands import fleet as fleet_cmd

        args = argparse.Namespace(
            request_id=rid, url=None, trace=[str(path)],
            timeout=10.0, as_json=True)
        rc = fleet_cmd.run_forensics(args)
        out = capsys.readouterr().out
        assert rc == 0
        offline = json.loads(out)
        assert offline["request_id"] == rid
        assert offline["well_nested"]
        assert "router_route_pick" in offline["names"]

        # The annotated timeline printer: callouts for route picks.
        args = argparse.Namespace(
            request_id=rid, url=None, trace=[str(path)],
            timeout=10.0, as_json=False)
        rc = fleet_cmd.run_forensics(args)
        out = capsys.readouterr().out
        assert rc == 0
        assert "[route-pick]" in out
        assert f"request {rid}" in out

        # Unknown id offline -> exit 1.
        args = argparse.Namespace(
            request_id="nope", url=None, trace=[str(path)],
            timeout=10.0, as_json=False)
        assert fleet_cmd.run_forensics(args) == 1

    def test_live_forensics_command(self, fleet, capsys):
        """--url mode against the running router front end."""
        import argparse

        from pydcop_tpu.commands import fleet as fleet_cmd
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        status, ack = _req(fleet.url + "/solve", "POST", {
            "dcop": dcop_yaml(_ring(8, 56)),
            "params": {"max_cycles": 50},
            "wait": True, "timeout": 120})
        assert status == 200, ack
        args = argparse.Namespace(
            request_id=ack["id"], url=fleet.url, trace=None,
            timeout=10.0, as_json=True)
        rc = fleet_cmd.run_forensics(args)
        out = capsys.readouterr().out
        assert rc == 0
        assert json.loads(out)["trace_id"] == ack["trace_id"]
        # Exactly one of --url/--trace: both or neither is exit 2.
        args = argparse.Namespace(
            request_id="x", url=None, trace=None,
            timeout=10.0, as_json=False)
        assert fleet_cmd.run_forensics(args) == 2


# ------------------------------------------------------------------ #
# the acceptance proof: forensics under injected faults


class TestForensicsUnderFaults:
    def test_retried_request_tree_proves_idempotency(self):
        """A /solve whose response is LOST after execution: the
        router retries, the worker dedupes, and the forensics tree —
        telemetry alone — shows the route pick, the injected fault,
        the retry hop, the dedupe hit, and EXACTLY ONE execute span,
        well-nested, with the winning replica's serve ledger."""
        from pydcop_tpu import api
        from pydcop_tpu.dcop.yamldcop import dcop_yaml
        from pydcop_tpu.serving import netfault

        handle = api.serve(port=0, replicas=2, batch_window_s=0.05,
                           heartbeat_s=0.15)
        try:
            url = handle.url
            netfault.install(
                "seed=20;link=router>replica-*,path=/solve,"
                "lose_response=1.0,times=1")
            status, ack = _req(url + "/solve", "POST", {
                "dcop": dcop_yaml(_ring(10, 20)),
                "params": {"max_cycles": 100},
                "deadline_s": 30.0})
            assert status == 202, ack
            rid = ack["id"]
            assert netfault.counters().get("lose_response") == 1
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                code, out = _req(url + f"/result/{rid}", timeout=10)
                if code == 200:
                    break
                time.sleep(0.1)
            assert code == 200 and out["status"] == "FINISHED"

            doc, names = {}, set()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                code, doc = _req(url + f"/fleet/forensics/{rid}")
                if code == 200:
                    names = set(doc["names"])
                    if {"router_retry", "serve_dedupe",
                            "serve_dispatch",
                            "netfault_injected"} <= names:
                        break
                time.sleep(0.25)
            assert code == 200, doc
            assert doc["well_nested"], sorted(names)
            assert "router_route_pick" in names, sorted(names)
            assert "router_retry" in names, sorted(names)
            assert "netfault_injected" in names, sorted(names)
            assert "serve_dedupe" in names, sorted(names)
            # The winning replica's full serve ledger rode along.
            assert {"serve_submit", "serve_dispatch"} <= names
            flat = list(_tree_nodes(doc["tree"]))
            executes = [n for n in flat
                        if n["name"] == "serve_dispatch"
                        and n["ph"] == "X"]
            assert len(executes) == 1, (
                f"{len(executes)} executions in the tree — "
                "idempotent forwarding demands exactly one")
            retries = [n for n in flat
                       if n["name"] == "router_retry"]
            assert len(retries) >= 1
            assert all(r["args"].get("request") == rid
                       for r in retries)
        finally:
            netfault.clear()
            handle.stop()


# ------------------------------------------------------------------ #
# knob: PYDCOP_FLEET_TRACE=0 disables the plane


class TestFleetTraceKnob:
    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv("PYDCOP_FLEET_TRACE", "0")
        assert not fleettrace.enabled()
        monkeypatch.setenv("PYDCOP_FLEET_TRACE", "off")
        assert not fleettrace.enabled()
        monkeypatch.setenv("PYDCOP_FLEET_TRACE", "1")
        assert fleettrace.enabled()
        monkeypatch.delenv("PYDCOP_FLEET_TRACE")
        assert fleettrace.enabled()

    def test_configure_shipper_respects_knob(self, monkeypatch):
        monkeypatch.setenv("PYDCOP_FLEET_TRACE", "0")
        state = fleettrace.configure_shipper(
            "http://127.0.0.1:1", source="replica-0", enable=True)
        assert state["enabled"] is False
        assert fleettrace.shipper() is None

    def test_disabled_fleet_answers_503_on_trace_surfaces(
            self, monkeypatch):
        """With the knob off the router never attaches a collector:
        the trace surfaces answer 503 (disabled), the serving wire
        keeps working untouched."""
        from pydcop_tpu import api
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        monkeypatch.setenv("PYDCOP_FLEET_TRACE", "0")
        handle = api.serve(port=0, replicas=2,
                           batch_window_s=0.05, heartbeat_s=0.2)
        try:
            url = handle.url
            status, out = _req(url + "/solve", "POST", {
                "dcop": dcop_yaml(_ring(7, 33)),
                "params": {"max_cycles": 50},
                "wait": True, "timeout": 120})
            assert status == 200 and out["status"] == "FINISHED"
            status, _doc = _req(url + "/fleet/trace")
            assert status == 503
            status, _doc = _req(url + "/fleet/forensics/whatever")
            assert status == 503
            # The aggregated metric/profile surfaces stay up — they
            # scrape registries, not spans.
            status, _doc = _req(url + "/fleet/metrics?format=json")
            assert status == 200
        finally:
            handle.stop()

"""``pydcop agent`` — placeholder, implemented later this round.

Reference parity target: pydcop/commands/agent.py.
"""


def set_parser(subparsers):
    parser = subparsers.add_parser("agent", help="agent (not yet implemented)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    print("pydcop agent: not implemented yet in pydcop-tpu")
    return 3

"""Battery over the distribution layer: per-method placement
properties (capacity, hints, completeness), greedy-vs-ILP agreement,
and the Distribution/DistributionHints objects."""

import pytest

from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.distribution.objects import (
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
)

from tests.unit.test_distribution import _import as load_distribution_module

d2 = Domain("d", "", [0, 1])


def build_graph(n_vars=6, ring=True):
    dcop = DCOP("t")
    vs = [Variable(f"v{i}", d2) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n_vars if ring else n_vars - 1):
        j = (i + 1) % n_vars
        dcop.add_constraint(NAryMatrixRelation(
            [vs[i], vs[j]], name=f"c{i}"))
    return chg.build_computation_graph(dcop)


def agents(n, capacity=100, **kw):
    # Non-zero default hosting cost: oilp_cgdp (faithfully to the
    # reference, oilp_cgdp.py:174-185) PINS any computation with a
    # 0-hosting-cost agent onto that agent — the SECP convention where
    # cost 0 marks the actuator's own agent.  All-default agents
    # would pin everything onto a0.
    kw.setdefault("default_hosting_cost", 1)
    return [AgentDef(f"a{i}", capacity=capacity, **kw)
            for i in range(n)]


GENERIC_METHODS = ["adhoc", "heur_comhost", "gh_cgdp", "oilp_cgdp",
                   "ilp_compref"]


class TestDistributionObject:
    def test_agent_for(self):
        d = Distribution({"a1": ["c1"], "a2": ["c2", "c3"]})
        assert d.agent_for("c3") == "a2"

    def test_agent_for_unknown_raises(self):
        d = Distribution({"a1": ["c1"]})
        with pytest.raises(KeyError):
            d.agent_for("ghost")

    def test_computations_hosted_unknown_agent_empty(self):
        d = Distribution({"a1": ["c1"]})
        assert d.computations_hosted("ghost") == []

    def test_host_on_agent(self):
        d = Distribution({"a1": ["c1"]})
        d.host_on_agent("a2", ["c2"])
        assert d.agent_for("c2") == "a2"

    def test_host_on_agent_rejects_already_hosted(self):
        # Reference parity (objects.py:156-175): a silent duplicate
        # would corrupt agent_for.
        d = Distribution({"a1": ["c1"]})
        with pytest.raises(ValueError, match="already hosted"):
            d.host_on_agent("a2", ["c1"])

    def test_host_on_agent_rejects_duplicate_in_call(self):
        d = Distribution({"a1": []})
        with pytest.raises(ValueError, match="already hosted"):
            d.host_on_agent("a1", ["c9", "c9"])

    def test_is_hosted(self):
        d = Distribution({"a1": ["c1", "c2"]})
        assert d.is_hosted("c1")
        assert d.is_hosted(["c1", "c2"])
        assert not d.is_hosted(["c1", "ghost"])

    def test_hints_must_host(self):
        h = DistributionHints(must_host={"a1": ["c1"]})
        assert h.must_host("a1") == ["c1"]
        assert h.must_host("a2") == []

    def test_hints_host_with_symmetric(self):
        h = DistributionHints(host_with={"c1": ["c2"]})
        assert "c2" in h.host_with("c1")
        assert "c1" in h.host_with("c2")


class TestGenericMethods:
    @pytest.mark.parametrize("method", GENERIC_METHODS)
    def test_every_computation_placed_exactly_once(self, method):
        cg = build_graph()
        mod = load_distribution_module(method)
        dist = mod.distribute(
            cg, agents(3),
            computation_memory=chg.computation_memory,
            communication_load=chg.communication_load,
        )
        placed = [c for a in dist.agents
                  for c in dist.computations_hosted(a)]
        assert sorted(placed) == sorted(n.name for n in cg.nodes)
        assert len(placed) == len(set(placed))

    @pytest.mark.parametrize("method", GENERIC_METHODS)
    def test_capacity_respected(self, method):
        cg = build_graph()
        mod = load_distribution_module(method)
        # footprint per variable computation is >0; capacity for at
        # most 2 computations per agent given chg footprints
        fp = chg.computation_memory(cg.nodes[0])
        cap = 2 * fp * 1.01   # room for exactly 2 computations
        dist = mod.distribute(
            cg, agents(3, capacity=cap),
            computation_memory=chg.computation_memory,
            communication_load=chg.communication_load,
        )
        for a in dist.agents:
            used = sum(
                chg.computation_memory(cg.computation(c))
                for c in dist.computations_hosted(a)
            )
            assert used <= cap + 1e-9

    @pytest.mark.parametrize("method", GENERIC_METHODS)
    def test_impossible_when_capacity_too_small(self, method):
        cg = build_graph()
        mod = load_distribution_module(method)
        with pytest.raises(ImpossibleDistributionException):
            mod.distribute(
                cg, agents(3, capacity=0),
                computation_memory=chg.computation_memory,
                communication_load=chg.communication_load,
            )

    @pytest.mark.parametrize("method", GENERIC_METHODS)
    def test_distribution_cost_finite(self, method):
        cg = build_graph()
        mod = load_distribution_module(method)
        dist = mod.distribute(
            cg, agents(3),
            computation_memory=chg.computation_memory,
            communication_load=chg.communication_load,
        )
        cost = mod.distribution_cost(
            dist, cg, agents(3),
            computation_memory=chg.computation_memory,
            communication_load=chg.communication_load,
        )
        value = cost[0] if isinstance(cost, tuple) else cost
        assert value >= 0


class TestOneAgent:
    def test_one_computation_per_agent(self):
        cg = build_graph(4)
        mod = load_distribution_module("oneagent")
        dist = mod.distribute(cg, agents(4))
        for a in dist.agents:
            assert len(dist.computations_hosted(a)) == 1

    def test_too_few_agents_raises(self):
        cg = build_graph(4)
        mod = load_distribution_module("oneagent")
        with pytest.raises(ImpossibleDistributionException):
            mod.distribute(cg, agents(3))

    def test_cost_is_zero(self):
        cg = build_graph(4)
        mod = load_distribution_module("oneagent")
        dist = mod.distribute(cg, agents(4))
        cost = mod.distribution_cost(dist, cg, agents(4))
        assert (cost[0] if isinstance(cost, tuple) else cost) == 0


class TestAdhocHints:
    def test_must_host_honored(self):
        cg = build_graph()
        mod = load_distribution_module("adhoc")
        hints = DistributionHints(must_host={"a2": ["v3"]})
        dist = mod.distribute(
            cg, agents(3), hints=hints,
            computation_memory=chg.computation_memory,
            communication_load=chg.communication_load,
        )
        assert dist.agent_for("v3") == "a2"


class TestOilpPinRule:
    def test_zero_hosting_cost_pins_computation(self):
        """Reference oilp_cgdp.py:174-185: a computation with hosting
        cost 0 on some agent is forced onto that agent (SECP actuator
        convention)."""
        cg = build_graph(4, ring=False)
        ag = [AgentDef(f"a{i}", capacity=100, default_hosting_cost=1,
                       hosting_costs={"v2": 0} if i == 2 else None)
              for i in range(4)]
        mod = load_distribution_module("oilp_cgdp")
        dist = mod.distribute(
            cg, ag,
            computation_memory=chg.computation_memory,
            communication_load=chg.communication_load,
        )
        assert dist.agent_for("v2") == "a2"


class TestOptimalBeatsGreedy:
    def test_ilp_cost_not_worse_than_greedy(self):
        """The optimal ILP placement cost must be <= the greedy one
        under the same cost model (oilp_cgdp vs gh_cgdp)."""
        cg = build_graph()
        ag = agents(3, capacity=1000)
        greedy = load_distribution_module("gh_cgdp")
        ilp = load_distribution_module("oilp_cgdp")
        kw = dict(computation_memory=chg.computation_memory,
                  communication_load=chg.communication_load)
        d_g = greedy.distribute(cg, ag, **kw)
        d_i = ilp.distribute(cg, ag, **kw)

        def cost(dist):
            c = ilp.distribution_cost(dist, cg, ag, **kw)
            return c[0] if isinstance(c, tuple) else c

        assert cost(d_i) <= cost(d_g) + 1e-6

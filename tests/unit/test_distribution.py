"""Distribution-method tests: validity, capacity, hints, ILP optimality."""

import pytest

from pydcop_tpu.algorithms import load_algorithm_module
from pydcop_tpu.computations_graph import constraints_hypergraph, factor_graph
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.distribution import objects as dist_objects
from pydcop_tpu.distribution.objects import (
    DistributionHints,
    ImpossibleDistributionException,
)

METHODS = [
    "oneagent", "adhoc", "heur_comhost", "gh_cgdp", "gh_secp_cgdp",
    "gh_secp_fgdp", "ilp_fgdp", "ilp_compref", "ilp_compref_fg",
    "oilp_cgdp", "oilp_secp_cgdp", "oilp_secp_fgdp",
]


def _problem():
    d = Domain("d", "", [0, 1, 2])
    vs = [Variable(f"v{i}", d) for i in range(4)]
    cs = [
        constraint_from_str("c0", "v0 + v1", vs),
        constraint_from_str("c1", "v1 + v2", vs),
        constraint_from_str("c2", "v2 + v3", vs),
    ]
    return vs, cs


def _import(method):
    import importlib

    return importlib.import_module(f"pydcop_tpu.distribution.{method}")


@pytest.mark.parametrize("method", METHODS)
def test_every_method_produces_valid_distribution(method):
    vs, cs = _problem()
    cg = factor_graph.build_computation_graph(
        variables=vs, constraints=cs)
    agents = [AgentDef(f"a{i}", capacity=1000) for i in range(8)]
    module = _import(method)
    algo = load_algorithm_module("maxsum")
    dist = module.distribute(
        cg, agents, hints=None,
        computation_memory=algo.computation_memory,
        communication_load=algo.communication_load,
    )
    hosted = sorted(dist.computations)
    assert hosted == sorted(n.name for n in cg.nodes)
    cost, comm, hosting = module.distribution_cost(
        dist, cg, agents,
        computation_memory=algo.computation_memory,
        communication_load=algo.communication_load,
    )
    assert cost >= 0 and comm >= 0 and hosting >= 0


def test_greedy_respects_capacity():
    vs, cs = _problem()
    cg = constraints_hypergraph.build_computation_graph(
        variables=vs, constraints=cs)
    # Footprint of each var-computation is its neighbor count (1-2);
    # capacity 2 forces spreading over agents.
    agents = [AgentDef(f"a{i}", capacity=2) for i in range(4)]
    module = _import("heur_comhost")
    algo = load_algorithm_module("dsa")
    dist = module.distribute(
        cg, agents, None, algo.computation_memory,
        algo.communication_load)
    for a in dist.agents:
        used = sum(
            algo.computation_memory(cg.computation(c))
            for c in dist.computations_hosted(a)
        )
        assert used <= 2


def test_greedy_impossible_capacity_raises():
    vs, cs = _problem()
    cg = constraints_hypergraph.build_computation_graph(
        variables=vs, constraints=cs)
    agents = [AgentDef("a0", capacity=0)]
    module = _import("adhoc")
    algo = load_algorithm_module("dsa")
    with pytest.raises(ImpossibleDistributionException):
        module.distribute(
            cg, agents, None, algo.computation_memory,
            algo.communication_load)


def test_must_host_hints_respected():
    vs, cs = _problem()
    cg = constraints_hypergraph.build_computation_graph(
        variables=vs, constraints=cs)
    agents = [AgentDef(f"a{i}", capacity=100) for i in range(4)]
    hints = DistributionHints(must_host={"a2": ["v0"], "a3": ["v3"]})
    for method in ("adhoc", "ilp_compref"):
        module = _import(method)
        dist = module.distribute(cg, agents, hints, None, None)
        assert dist.agent_for("v0") == "a2"
        assert dist.agent_for("v3") == "a3"


def test_ilp_minimizes_communication():
    """Two clusters of tightly-linked computations and two agents with
    free intra-agent routes: the ILP must put each cluster on one
    agent."""
    d = Domain("d", "", [0, 1])
    vs = [Variable(f"v{i}", d) for i in range(4)]
    cs = [
        constraint_from_str("c01", "v0 + v1", vs),
        constraint_from_str("c23", "v2 + v3", vs),
    ]
    cg = constraints_hypergraph.build_computation_graph(
        variables=vs, constraints=cs)
    agents = [
        AgentDef("a0", capacity=100, default_route=10),
        AgentDef("a1", capacity=100, default_route=10),
    ]
    module = _import("ilp_fgdp")
    dist = module.distribute(cg, agents, None, None, lambda s, t: 1)
    assert dist.agent_for("v0") == dist.agent_for("v1")
    assert dist.agent_for("v2") == dist.agent_for("v3")
    cost, comm, hosting = module.distribution_cost(
        dist, cg, agents, None, lambda s, t: 1)
    assert comm == 0  # all communication intra-agent


def test_ilp_hosting_costs_matter():
    d = Domain("d", "", [0, 1])
    v = Variable("v0", d)
    cg = constraints_hypergraph.build_computation_graph(
        variables=[v], constraints=[])
    agents = [
        AgentDef("cheap", default_hosting_cost=1),
        AgentDef("pricey", default_hosting_cost=50),
    ]
    module = _import("ilp_compref")
    dist = module.distribute(cg, agents, None, None, None)
    assert dist.agent_for("v0") == "cheap"


def test_distribution_object_roundtrip():
    from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

    dist = dist_objects.Distribution({"a1": ["v1"], "a2": []})
    assert from_repr(simple_repr(dist)) == dist


def test_host_with_hints_colocate():
    """host_with hints pull computations onto the same agent (reference
    adhoc distribution honors DistributionHints.host_with)."""
    vs, cs = _problem()
    cg = constraints_hypergraph.build_computation_graph(
        variables=vs, constraints=cs)
    agents = [AgentDef(f"a{i}", capacity=1000) for i in range(4)]
    hints = DistributionHints(host_with={"v0": ["v3"]})
    module = _import("adhoc")
    algo = load_algorithm_module("dsa")
    dist = module.distribute(
        cg, agents, hints=hints,
        computation_memory=algo.computation_memory,
        communication_load=algo.communication_load,
    )
    assert dist.agent_for("v0") == dist.agent_for("v3")


def test_distribution_host_on_agent_accumulates():
    dist = dist_objects.Distribution({"a1": ["v1"], "a2": []})
    dist.host_on_agent("a2", ["v2"])
    dist.host_on_agent("a2", ["v3"])
    assert sorted(dist.computations_hosted("a2")) == ["v2", "v3"]
    assert dist.agent_for("v3") == "a2"
    # new agent key created on demand
    dist.host_on_agent("a9", ["v9"])
    assert dist.agent_for("v9") == "a9"


def test_distribution_is_hosted_and_missing_agent_raises():
    dist = dist_objects.Distribution({"a1": ["v1", "v2"]})
    assert dist.is_hosted(["v1", "v2"])
    assert not dist.is_hosted(["v1", "nope"])
    assert dist.has_computation("v1")
    assert not dist.has_computation("zz")
    with pytest.raises(Exception):
        dist.agent_for("zz")


def test_yaml_dist_file_roundtrip(tmp_path):
    """Distribution files written to disk reload identically (the
    `pydcop distribute --output` format)."""
    from pydcop_tpu.dcop.yamldcop import load_dist_from_file, yaml_dist

    dist = dist_objects.Distribution(
        {"a1": ["v1", "c1"], "a2": ["v2"]})
    p = tmp_path / "dist.yaml"
    p.write_text(yaml_dist(dist))
    loaded = load_dist_from_file(str(p))
    assert loaded == dist

"""Battery over commands/batch.py's pure job-expansion machinery
(reference test_batch.py depth): sets, iterations, file globs, option
sweeps, variable expansion, and progress-file resume."""

import os

from pydcop_tpu.commands.batch import (
    _expand,
    _expand_option_combinations,
    _load_progress,
    _register_job,
    iter_jobs,
)


class TestExpand:
    def test_simple_substitution(self):
        assert _expand("run_{set}_{iteration}",
                       {"set": "s1", "iteration": 3}) == "run_s1_3"

    def test_unknown_key_left_verbatim(self):
        assert _expand("{nope}", {}) == "{nope}"

    def test_dict_entry_expansion(self):
        assert _expand("{opts[k]}", {"opts": {"k": "v"}}) == "v"


class TestOptionCombinations:
    def test_scalars_single_combo(self):
        combos = _expand_option_combinations({"a": 1, "b": "x"})
        assert combos == [[("a", 1), ("b", "x")]]

    def test_list_sweeps(self):
        combos = _expand_option_combinations({"algo": ["dsa", "mgm"]})
        assert [dict(c)["algo"] for c in combos] == ["dsa", "mgm"]

    def test_cartesian_product_of_lists(self):
        combos = _expand_option_combinations(
            {"a": [1, 2], "b": ["x", "y"]})
        assert len(combos) == 4
        pairs = {(dict(c)["a"], dict(c)["b"]) for c in combos}
        assert pairs == {(1, "x"), (1, "y"), (2, "x"), (2, "y")}

    def test_dict_value_sweeps_inner_lists(self):
        combos = _expand_option_combinations(
            {"algo_params": {"variant": ["A", "B"], "seed": 0}})
        inner = [dict(c)["algo_params"] for c in combos]
        assert {d["variant"] for d in inner} == {"A", "B"}
        assert all(d["seed"] == 0 for d in inner)

    def test_empty_options(self):
        assert _expand_option_combinations({}) == [[]]


class TestIterJobs:
    def test_iterations_multiply_jobs(self):
        jobs = list(iter_jobs({
            "sets": {"s": {"iterations": 3}},
            "batches": {"b": {"command": "solve"}},
        }))
        assert len(jobs) == 3
        # job ids distinguish iterations
        assert len({j[2] for j in jobs}) == 3

    def test_file_glob_expands(self, tmp_path):
        for n in ("p1.yaml", "p2.yaml"):
            (tmp_path / n).write_text("x")
        jobs = list(iter_jobs({
            "sets": {"s": {"path": str(tmp_path / "*.yaml")}},
            "batches": {"b": {"command": "solve"}},
        }))
        assert len(jobs) == 2
        files = [j[0][-1] for j in jobs]
        assert files == sorted(files)

    def test_directory_path_means_star(self, tmp_path):
        (tmp_path / "p1.yaml").write_text("x")
        jobs = list(iter_jobs({
            "sets": {"s": {"path": str(tmp_path)}},
            "batches": {"b": {"command": "solve"}},
        }))
        assert len(jobs) == 1

    def test_file_context_variables(self, tmp_path):
        (tmp_path / "prob.yaml").write_text("x")
        jobs = list(iter_jobs({
            "sets": {"s": {"path": str(tmp_path / "*.yaml")}},
            "batches": {"b": {
                "command": "solve",
                "command_options": {"output": "{file_name}_out.json"},
            }},
        }))
        args = jobs[0][0]
        assert "prob_out.json" in args

    def test_env_variables_available(self):
        jobs = list(iter_jobs({
            "sets": {"s": {"iterations": 1, "env": {"tag": "v9"}}},
            "batches": {"b": {
                "command": "solve",
                "command_options": {"output": "{tag}.json"},
            }},
        }))
        assert "v9.json" in jobs[0][0]

    def test_global_options_precede_command(self):
        jobs = list(iter_jobs({
            "global_options": {"timeout": 10},
            "sets": {"s": {"iterations": 1}},
            "batches": {"b": {"command": "solve"}},
        }))
        args = jobs[0][0]
        assert args.index("--timeout") < args.index("solve")

    def test_batch_globals_override(self):
        jobs = list(iter_jobs({
            "global_options": {"timeout": 10},
            "sets": {"s": {"iterations": 1}},
            "batches": {"b": {
                "command": "solve",
                "global_options": {"timeout": 99},
            }},
        }))
        args = jobs[0][0]
        assert args[args.index("--timeout") + 1] == "99"

    def test_dict_options_become_name_colon_value(self):
        jobs = list(iter_jobs({
            "sets": {"s": {"iterations": 1}},
            "batches": {"b": {
                "command": "solve",
                "command_options": {
                    "algo_params": {"variant": "A"},
                },
            }},
        }))
        args = jobs[0][0]
        i = args.index("--algo_params")
        assert args[i + 1] == "variant:A"

    def test_multiple_batches_per_set(self):
        jobs = list(iter_jobs({
            "sets": {"s": {"iterations": 2}},
            "batches": {
                "b1": {"command": "solve"},
                "b2": {"command": "graph"},
            },
        }))
        assert len(jobs) == 4

    def test_default_set_when_missing(self):
        jobs = list(iter_jobs({
            "batches": {"b": {"command": "solve"}},
        }))
        assert len(jobs) == 1

    def test_current_dir_expanded(self, tmp_path):
        jobs = list(iter_jobs({
            "sets": {"s": {"iterations": 1, "env": {"d": str(tmp_path)}}},
            "batches": {"b": {
                "command": "solve",
                "current_dir": "{d}",
            }},
        }))
        assert jobs[0][1] == str(tmp_path)


class TestProgress:
    def test_missing_file_empty(self, tmp_path):
        assert _load_progress(str(tmp_path / "nope")) == set()

    def test_register_and_reload(self, tmp_path):
        pf = str(tmp_path / "progress")
        _register_job(pf, "job one")
        _register_job(pf, "job two")
        assert _load_progress(pf) == {"job one", "job two"}

    def test_blank_lines_ignored(self, tmp_path):
        pf = tmp_path / "progress"
        pf.write_text("a\n\n  \nb\n")
        assert _load_progress(str(pf)) == {"a", "b"}

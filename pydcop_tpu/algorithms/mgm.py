"""MGM: Maximum Gain Message — monotone distributed local search.

Reference parity: pydcop/algorithms/mgm.py (params :77-83: break_mode
lexic/random, stop_cycle; semantics :213-609).  Kernels:
pydcop_tpu/ops/mgm.py.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'mgm', max_cycles=30, algo_params={'seed': 1})
    >>> round(res['cost'], 3)
    0.0
"""

from functools import partial
from typing import Optional

import numpy as np

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import compile_dcop, validated_aggregation
from pydcop_tpu.engine.runner import DeviceRunResult, run_device_fn
from pydcop_tpu.ops.mgm import run_mgm

GRAPH_TYPE = "constraints_hypergraph"

HEADER_SIZE = 0
UNIT_SIZE = 1

algo_params = [
    # Variable-aggregation strategy for the shared local-search
    # kernels (ops/localsearch.py): "scatter" is the parity
    # default; "ell" replaces every segment_sum/max/min with
    # compile-time dense-gather edge lists (the TPU HBM-regime
    # candidate, benchmarks/exp_aggregation.py).  Single-device;
    # sharded runs always use scatter.
    AlgoParameterDef(
        "aggregation", "str", ["scatter", "ell"], "scatter"
    ),
    AlgoParameterDef("break_mode", "str", ["lexic", "random"], "lexic"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("seed", "int", None, 0),
]


def computation_memory(node) -> float:
    return chg.computation_memory(node)


def communication_load(src, target: str) -> float:
    return chg.communication_load(src, target)


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("mgm", comp_def)


def lexic_ranks(meta) -> np.ndarray:
    """Rank of each variable in lexical name order ([V+1] float32,
    sentinel +inf) — the reference's sorted-name tie-break (mgm.py:571)."""
    order = {
        name: i for i, name in enumerate(sorted(meta.var_names))
    }
    ranks = np.empty(len(meta.var_names) + 1, dtype=np.float32)
    for i, name in enumerate(meta.var_names):
        ranks[i] = order[name]
    ranks[-1] = np.inf
    return ranks


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    warmup: bool = False,
                    **_) -> DeviceRunResult:
    params = algo_def.params
    pad_to = mesh.size if mesh is not None else (n_devices or 1)
    graph, meta = compile_dcop(
        dcop, pad_to=pad_to,
        aggregation=validated_aggregation(params, pad_to))
    cycles = params.get("stop_cycle") or max_cycles
    fn = partial(
        run_mgm,
        max_cycles=cycles,
        lexic_ranks=lexic_ranks(meta),
        break_mode=params.get("break_mode", "lexic"),
        seed=params.get("seed", 0),
    )
    return run_device_fn(
        graph, meta, fn, mesh=mesh, n_devices=n_devices, warmup=warmup,
        finished=bool(params.get("stop_cycle")),
    )

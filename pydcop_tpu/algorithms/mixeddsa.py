"""MixedDSA: DSA variant for DCOPs mixing hard and soft constraints.

Reference parity: pydcop/algorithms/mixeddsa.py (params :119-124:
variant A/B/C, proba_hard 0.7, proba_soft 0.5; semantics :154-470).
Kernels: pydcop_tpu/ops/mixeddsa.py.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'mixeddsa', max_cycles=30, algo_params={'seed': 1})
    >>> round(res['cost'], 3)
    0.0
"""

from functools import partial
from typing import Optional

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import compile_dcop, validated_aggregation
from pydcop_tpu.engine.runner import DeviceRunResult, run_device_fn
from pydcop_tpu.ops.mixeddsa import run_mixeddsa

GRAPH_TYPE = "constraints_hypergraph"

HEADER_SIZE = 100
UNIT_SIZE = 5

algo_params = [
    # Variable-aggregation strategy for the shared local-search
    # kernels (ops/localsearch.py): "scatter" is the parity
    # default; "ell" replaces every segment_sum/max/min with
    # compile-time dense-gather edge lists (the TPU HBM-regime
    # candidate, benchmarks/exp_aggregation.py).  Single-device;
    # sharded runs always use scatter.
    AlgoParameterDef(
        "aggregation", "str", ["scatter", "ell"], "scatter"
    ),
    AlgoParameterDef("proba_hard", "float", None, 0.7),
    AlgoParameterDef("proba_soft", "float", None, 0.5),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("seed", "int", None, 0),
]


def computation_memory(node) -> float:
    # One value per neighbor (reference mixeddsa.py:92).
    return len(node.neighbors) * UNIT_SIZE


def communication_load(src, target: str) -> float:
    # Value messages carry a single value (reference mixeddsa.py:116).
    return UNIT_SIZE + HEADER_SIZE


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("mixeddsa", comp_def)


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    warmup: bool = False,
                    **_) -> DeviceRunResult:
    params = algo_def.params
    pad_to = mesh.size if mesh is not None else (n_devices or 1)
    graph, meta = compile_dcop(
        dcop, pad_to=pad_to,
        aggregation=validated_aggregation(params, pad_to))
    cycles = params.get("stop_cycle") or max_cycles
    fn = partial(
        run_mixeddsa,
        max_cycles=cycles,
        variant=params.get("variant", "B"),
        proba_hard=float(params.get("proba_hard", 0.7)),
        proba_soft=float(params.get("proba_soft", 0.5)),
        seed=params.get("seed", 0),
    )
    return run_device_fn(
        graph, meta, fn, mesh=mesh, n_devices=n_devices, warmup=warmup,
        finished=bool(params.get("stop_cycle")),
    )

"""Seeded device-vs-thread quality parity for every local-search
algorithm (VERDICT weak #8) and the mgm2 statistical equivalence check
(VERDICT weak #5).

Local search is stochastic and the two runtimes draw their randomness
differently (jax PRNG on device, python random in agent mode), so the
assertions are quality-level, not bit-level:

- on an easy instance with a known optimum, both backends must find a
  feasible (violation-free / low-cost) solution;
- across a batch of seeded random instances, the device kernel's mean
  final cost must be within a band of the thread runtime's mean
  (statistical solution-quality equivalence — the device kernels may
  diverge from the reference protocol in documented scheduling details
  but must not be systematically worse).
"""

import numpy as np
import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

from fixtures_paths import local

FIXTURE = local("coloring_chain.yaml")
LOCAL_SEARCH = ["dsa", "mgm", "mgm2", "dba", "gdba", "mixeddsa"]


# Both runtimes must color the 4-chain properly; costs then span
# [-0.6, 0.6] depending on which preference-tie the run lands on (the
# device kernels fold unary preferences in, agent mode is unary-blind
# like the reference, so only feasibility is runtime-invariant).
def _acceptable(res) -> bool:
    a = res["assignment"]
    proper = all(
        a[left] != a[right]
        for left, right in [("w1", "w2"), ("w2", "w3"), ("w3", "w4")]
    )
    return proper and -0.6 - 1e-6 <= res["cost"] <= 0.6 + 1e-6


def _random_coloring(n_vars: int, n_colors: int, seed: int,
                     n_agents: int = 4) -> DCOP:
    rng = np.random.default_rng(seed)
    dom = Domain("colors", "color", list(range(n_colors)))
    dcop = DCOP(f"gc{n_vars}_{seed}", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    eq = np.eye(n_colors, dtype=np.float64)
    seen, k = set(), 0
    while k < int(n_vars * 1.8):
        i, j = rng.choice(n_vars, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], eq, f"c{k}"))
        k += 1
    dcop.add_agents(
        [AgentDef(f"a{i}", capacity=10_000) for i in range(n_agents)])
    return dcop


def _pack_distribution(dcop, algo):
    """Round-robin Distribution over the dcop's agents (capacity-free
    packing for parity runs)."""
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.distribution.objects import Distribution

    module = load_algorithm_module(algo)
    cg = load_graph_module(
        module.GRAPH_TYPE).build_computation_graph(dcop)
    agents = sorted(dcop.agents)
    mapping = {a: [] for a in agents}
    for i, node in enumerate(cg.nodes):
        mapping[agents[i % len(agents)]].append(node.name)
    return Distribution(mapping)


@pytest.mark.parametrize("algo", ["dsa", "mgm", "mgm2", "mixeddsa"])
def test_device_and_thread_both_feasible_on_fixture(algo):
    d1 = load_dcop_from_file(FIXTURE)
    r_dev = solve(d1, algo, backend="device", max_cycles=100)
    assert _acceptable(r_dev), f"device {algo}: {r_dev['cost']}"
    d2 = load_dcop_from_file(FIXTURE)
    r_thr = solve(d2, algo, backend="thread", timeout=4)
    assert _acceptable(r_thr), f"thread {algo}: {r_thr['cost']}"


def _hard_csp(n_vars=8, seed=0):
    """Ring coloring with hard (10000) difference constraints — the
    problem class dba/gdba target (violation count, reference dba.py
    'CSP-flavored')."""
    rng = np.random.default_rng(seed)
    dom = Domain("colors", "color", [0, 1, 2])
    dcop = DCOP(f"csp{n_vars}", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    hard = 10000.0 * np.eye(3)
    for i in range(n_vars):
        j = (i + 1) % n_vars
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], hard, f"c{i}"))
    dcop.add_agents(
        [AgentDef(f"a{i}", capacity=10_000) for i in range(4)])
    return dcop


@pytest.mark.parametrize("algo", ["dba", "gdba"])
def test_breakout_solves_csp_on_both_backends(algo):
    d1 = _hard_csp()
    r_dev = solve(d1, algo, backend="device", max_cycles=300)
    assert r_dev["cost"] == 0, f"device {algo}: {r_dev['cost']}"
    d2 = _hard_csp()
    r_thr = solve(
        d2, algo, backend="thread", timeout=6,
        distribution=_pack_distribution(d2, algo),
    )
    assert r_thr["cost"] == 0, f"thread {algo}: {r_thr['cost']}"


class TestMgm2StatisticalEquivalence:
    """Device mgm2 diverges from the reference protocol in partner
    selection and shared-gain accounting (documented, ops/mgm2.py);
    this pins the consequence: solution quality must be statistically
    equivalent to the agent-mode protocol."""

    SEEDS = [0, 1, 2, 3]
    N_VARS, N_COLORS = 24, 3

    def _run(self, backend, seed):
        dcop = _random_coloring(self.N_VARS, self.N_COLORS, seed)
        if backend == "thread":
            res = solve(
                dcop, "mgm2", backend="thread", timeout=6,
                distribution=_pack_distribution(dcop, "mgm2"),
                algo_params={"stop_cycle": 60},
            )
        else:
            res = solve(dcop, "mgm2", backend="device", max_cycles=60)
        return float(res["cost"])

    def test_mean_quality_within_band(self):
        dev = [self._run("device", s) for s in self.SEEDS]
        thr = [self._run("thread", s) for s in self.SEEDS]
        mean_dev, mean_thr = np.mean(dev), np.mean(thr)
        n_constraints = int(self.N_VARS * 1.8)
        # Equivalence band: 10% of the constraint count (each conflict
        # costs 1).  Catches any systematic quality regression while
        # tolerating per-seed local-optimum noise.
        assert abs(mean_dev - mean_thr) <= 0.10 * n_constraints, (
            f"device {dev} vs thread {thr}"
        )


@pytest.mark.parametrize("algo", ["dsa", "mgm"])
def test_seeded_random_instances_quality(algo):
    """Device local search on seeded 30-var instances ends close to the
    thread runtime's quality (mean gap <= 10% of constraints)."""
    dev, thr = [], []
    for seed in (0, 1):
        dcop = _random_coloring(30, 3, seed)
        r_dev = solve(dcop, algo, backend="device", max_cycles=80)
        dev.append(float(r_dev["cost"]))
        dcop2 = _random_coloring(30, 3, seed)
        r_thr = solve(
            dcop2, algo, backend="thread", timeout=5,
            distribution=_pack_distribution(dcop2, algo),
            algo_params={"stop_cycle": 80},
        )
        thr.append(float(r_thr["cost"]))
    assert abs(np.mean(dev) - np.mean(thr)) <= 0.10 * 30 * 1.8, (
        f"device {dev} vs thread {thr}"
    )
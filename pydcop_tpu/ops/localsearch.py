"""Batched local-search kernels over the compiled factor-graph arrays.

These power the constraints-hypergraph algorithm family (dsa, adsa, mgm,
mgm2, dba, gdba, mixeddsa...).  One BSP cycle = every variable evaluates
its candidate values against its neighbors' *previous-cycle* values —
exactly the reference's cycle bookkeeping (dsa.py:266-268 current/next
cycle maps), but as dense tensor ops:

- `candidate_costs`: for each variable and candidate value, the cost of
  its local view (own unary cost + every incident constraint evaluated
  with the other variables fixed at their current values).  Implemented
  by fixing, per bucket and per position, all other axes of the cost
  hypercube via take_along_axis gathers, then segment-summing into
  [V, D] (reference analogue: find_optimal / compute_best_value loops,
  relations.py:1554, mgm.py:445).
- `neighbor_max` / `neighbor_min_rank_where`: neighborhood reductions
  (excluding self) used by MGM's gain comparison and tie-breaking
  (mgm.py:515-590).
- `assignment_cost`: total cost of the current assignment (padding rows
  contribute 0 by construction).

All kernels assume the `CompiledFactorGraph` layout (see engine.compile):
BIG on invalid domain slots keeps padded candidates from ever winning an
argmin; sentinel rows absorb padding contributions.
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import BIG, CompiledFactorGraph
from pydcop_tpu.ops.ell import gather_reduce

INT_MAX = jnp.iinfo(jnp.int32).max


def _fix_other_axes(costs: jnp.ndarray, var_ids: jnp.ndarray,
                    values: jnp.ndarray, keep: int) -> jnp.ndarray:
    """Reduce a bucket cost tensor [F, D, ..., D] to [F, D] by indexing
    every axis except `keep` with the current value of its variable."""
    arity = var_ids.shape[1]
    out = costs
    # Fix axes from the last to the first: squeezing axis q+1 never
    # shifts the axes below it, so axis numbers stay valid.
    for q in range(arity - 1, -1, -1):
        if q == keep:
            continue
        vq = values[var_ids[:, q]]  # [F]
        idx = vq.reshape((-1,) + (1,) * (out.ndim - 1))
        out = jnp.squeeze(
            jnp.take_along_axis(out, idx, axis=q + 1), axis=q + 1
        )
    return out  # [F, D]


def positional_sum(graph: CompiledFactorGraph, per_bucket,
                   init: jnp.ndarray) -> jnp.ndarray:
    """``init`` [V+1, D] plus, per variable, the sum of its incident
    (bucket, factor, position) contributions.  ``per_bucket`` is one
    [F, arity, D] array per bucket — the same flattened edge order the
    compile-time ell lists index, so with ``graph.agg_ell`` set the
    sums are a dense gather + K-way masked sum (no scatter); otherwise
    one segment_sum per position (identical addition order, so the two
    backends of every caller stay float-comparable)."""
    if not per_bucket:
        return init
    if graph.agg_ell is not None:
        d = init.shape[1]
        flats = [v.reshape(-1, d) for v in per_bucket]
        flat = flats[0] if len(flats) == 1 else jnp.concatenate(
            flats, axis=0)
        return init + gather_reduce(graph.agg_ell, flat, 0.0, jnp.sum)
    out = init
    n_segments = init.shape[0]
    for bucket, vals in zip(graph.buckets, per_bucket):
        for p in range(bucket.var_ids.shape[1]):
            out = out + jax.ops.segment_sum(
                vals[:, p], bucket.var_ids[:, p],
                num_segments=n_segments,
            )
    return out


def positional_max(graph: CompiledFactorGraph, per_bucket,
                   fill) -> jnp.ndarray:
    """[V+1]: per variable, max over its incident (bucket, factor,
    position) slots of per-edge scalars (``per_bucket``: one
    [F, arity] array per bucket); ``fill`` for variables with no
    incident slots."""
    n_segments = graph.var_costs.shape[0]
    if not per_bucket:
        return jnp.full((n_segments,), fill)
    if graph.agg_ell is not None:
        return gather_reduce(
            graph.agg_ell, _edge_flat(per_bucket), fill, jnp.max)
    out = jnp.full((n_segments,), fill, dtype=per_bucket[0].dtype)
    for bucket, vals in zip(graph.buckets, per_bucket):
        for p in range(bucket.var_ids.shape[1]):
            out = jnp.maximum(out, jax.ops.segment_max(
                vals[:, p], bucket.var_ids[:, p],
                num_segments=n_segments,
            ))
    return out


def candidate_costs(graph: CompiledFactorGraph,
                    values: jnp.ndarray) -> jnp.ndarray:
    """[V+1, D]: cost of each candidate value per variable, given all
    other variables at `values` (includes own unary costs).

    Routed through :func:`positional_sum`, so with
    ``graph.agg_ell`` set (compile_dcop(aggregation='ell')) the sums
    use the same dense-gather edge lists as MaxSum's
    aggregate_beliefs instead of scatter-adds."""
    per_bucket = []
    for bucket in graph.buckets:
        arity = bucket.var_ids.shape[1]
        per_bucket.append(jnp.stack([
            _fix_other_axes(bucket.costs, bucket.var_ids, values, p)
            for p in range(arity)
        ], axis=1))
    return positional_sum(graph, per_bucket, graph.var_costs)


def factor_current_costs(graph: CompiledFactorGraph,
                         values: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """Per bucket: [F] cost of each factor at the current assignment."""
    out = []
    for bucket in graph.buckets:
        fixed = _fix_other_axes(bucket.costs, bucket.var_ids, values, 0)
        v0 = values[bucket.var_ids[:, 0]]
        out.append(jnp.take_along_axis(
            fixed, v0[:, None], axis=1
        ).squeeze(1))
    return tuple(out)


def assignment_cost(graph: CompiledFactorGraph,
                    values: jnp.ndarray) -> jnp.ndarray:
    """Scalar total cost (constraints + unary) of the assignment.
    `values` is the full [V+1] array (sentinel row excluded from unary
    costs; padding factors cost 0 by construction)."""
    total = jnp.sum(
        jnp.take_along_axis(
            graph.var_costs[:-1], values[:-1, None], axis=1
        )
    )
    for costs in factor_current_costs(graph, values):
        total = total + jnp.sum(costs)
    return total


def _edge_flat(per_bucket) -> jnp.ndarray:
    """Concatenate per-bucket [F, arity] edge values into the flat [E]
    order build_aggregation_arrays indexes."""
    flats = [v.reshape(-1) for v in per_bucket]
    return flats[0] if len(flats) == 1 else jnp.concatenate(flats)


def neighbor_max(graph: CompiledFactorGraph,
                 per_var: jnp.ndarray) -> jnp.ndarray:
    """[V+1]: max of `per_var` over each variable's neighbors (variables
    sharing a constraint), excluding the variable itself.

    With ``graph.agg_ell`` set, the per-edge co-variable maxima are
    computed densely in edge space and reduced through the ell lists
    (no segment_max scatter)."""
    n_segments = graph.var_costs.shape[0]
    if graph.agg_ell is not None:
        per_bucket = []
        for bucket in graph.buckets:
            arity = bucket.var_ids.shape[1]
            vals = per_var[bucket.var_ids]          # [F, arity]
            cols = []
            for p in range(arity):
                # Unary factors have no co-variable: identity element.
                m = jnp.full(vals.shape[:1], -jnp.inf, vals.dtype)
                for q in range(arity):
                    if q == p:
                        continue
                    m = jnp.maximum(m, vals[:, q])
                cols.append(m)
            per_bucket.append(jnp.stack(cols, axis=1))
        return gather_reduce(
            graph.agg_ell, _edge_flat(per_bucket), -jnp.inf, jnp.max)
    out = jnp.full((n_segments,), -jnp.inf, dtype=per_var.dtype)
    for bucket in graph.buckets:
        arity = bucket.var_ids.shape[1]
        for p in range(arity):
            for q in range(arity):
                if p == q:
                    continue
                vals_q = per_var[bucket.var_ids[:, q]]
                out = jnp.maximum(out, jax.ops.segment_max(
                    vals_q, bucket.var_ids[:, p],
                    num_segments=n_segments,
                ))
    return out


def neighbor_min_rank_where(graph: CompiledFactorGraph,
                            per_var: jnp.ndarray,
                            target: jnp.ndarray,
                            ranks: jnp.ndarray) -> jnp.ndarray:
    """[V+1]: min rank among neighbors whose `per_var` equals the
    variable's `target` value (+inf when none) — MGM tie-breaking.
    `ranks` is float (lexical index or per-cycle random draws)."""
    n_segments = graph.var_costs.shape[0]
    ranks = jnp.asarray(ranks, dtype=jnp.float32)
    if graph.agg_ell is not None:
        per_bucket = []
        for bucket in graph.buckets:
            arity = bucket.var_ids.shape[1]
            pv = per_var[bucket.var_ids]            # [F, arity]
            rk = ranks[bucket.var_ids]
            tgt = target[bucket.var_ids]
            cols = []
            for p in range(arity):
                # Unary factors have no co-variable: identity element.
                m = jnp.full(pv.shape[:1], jnp.inf, jnp.float32)
                for q in range(arity):
                    if q == p:
                        continue
                    cand = jnp.where(
                        pv[:, q] == tgt[:, p], rk[:, q], jnp.inf)
                    m = jnp.minimum(m, cand)
                cols.append(m)
            per_bucket.append(jnp.stack(cols, axis=1))
        return gather_reduce(
            graph.agg_ell, _edge_flat(per_bucket), jnp.inf, jnp.min)
    out = jnp.full((n_segments,), jnp.inf, dtype=jnp.float32)
    for bucket in graph.buckets:
        arity = bucket.var_ids.shape[1]
        for p in range(arity):
            tgt_p = target[bucket.var_ids[:, p]]
            for q in range(arity):
                if p == q:
                    continue
                vq = bucket.var_ids[:, q]
                eligible = per_var[vq] == tgt_p
                cand_rank = jnp.where(eligible, ranks[vq], jnp.inf)
                out = jnp.minimum(out, jax.ops.segment_min(
                    cand_rank, bucket.var_ids[:, p],
                    num_segments=n_segments,
                ))
    return out


def factor_valid_masks(graph: CompiledFactorGraph
                       ) -> Tuple[jnp.ndarray, ...]:
    """Per bucket: [F, D^arity] bool — the valid region of each factor's
    cost table (outer product of its variables' valid domain slots).
    Padding rows point at the all-invalid sentinel row, so their region
    is empty."""
    out = []
    for bucket in graph.buckets:
        arity = bucket.var_ids.shape[1]
        valid = jnp.ones((bucket.n_factors,), dtype=bool)
        for q in range(arity):
            vq = graph.var_valid[bucket.var_ids[:, q]]  # [F, D]
            shape = (bucket.n_factors,) + (1,) * q + (vq.shape[1],)
            valid = valid[..., None] & vq.reshape(shape)
        out.append(valid)
    return tuple(out)


def factor_min_over_valid(bucket, valid: jnp.ndarray) -> jnp.ndarray:
    """[F]: each factor's min cost over its valid region (+inf when
    empty — padding rows)."""
    axes = tuple(range(1, bucket.costs.ndim))
    return jnp.min(jnp.where(valid, bucket.costs, jnp.inf), axis=axes)


def factor_max_over_valid(bucket, valid: jnp.ndarray) -> jnp.ndarray:
    """[F]: each factor's max cost over its valid region (-inf when
    empty)."""
    axes = tuple(range(1, bucket.costs.ndim))
    return jnp.max(jnp.where(valid, bucket.costs, -jnp.inf), axis=axes)


def neighborhood_winners(graph: CompiledFactorGraph, cand: jnp.ndarray,
                         values: jnp.ndarray, key: jnp.ndarray,
                         ranks: jnp.ndarray
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                    jnp.ndarray]:
    """Shared evaluate → propose → tie-break step of the breakout/MGM
    family (mgm.py:515-590, dba.py:507-517, gdba.py:505-527).

    Given per-candidate costs `cand` [V+1, D], returns
    (improve, proposed, nmax, wins):
    - improve [V+1]: current cost minus best candidate cost (>= 0);
    - proposed [V+1]: uniform-random choice among best candidates;
    - nmax [V+1]: max improvement among neighbors;
    - wins [V+1]: strictly-largest improvement in the neighborhood,
      lexically-smallest `ranks` winning ties.
    """
    cur = jnp.take_along_axis(cand, values[:, None], axis=1).squeeze(1)
    best, is_best = best_candidates(graph, cand)
    improve = cur - best
    proposed = random_best_choice(key, is_best)
    nmax = neighbor_max(graph, improve)
    nrank = neighbor_min_rank_where(graph, improve, improve, ranks)
    wins = (improve > nmax) | ((improve == nmax) & (ranks < nrank))
    return improve, proposed, nmax, wins


def best_candidates(graph: CompiledFactorGraph, cand: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(best_cost [V+1], is_best [V+1, D]) over valid domain slots."""
    masked = jnp.where(graph.var_valid, cand, jnp.inf)
    best = jnp.min(masked, axis=1)
    return best, masked == best[:, None]


def random_best_choice(key: jnp.ndarray, is_best: jnp.ndarray
                       ) -> jnp.ndarray:
    """Uniform random choice among True slots per row ([N] int32) —
    reference's random.choice(best_values) (dsa.py:411)."""
    u = jax.random.uniform(key, is_best.shape)
    return jnp.argmax(jnp.where(is_best, u, -1.0), axis=1).astype(jnp.int32)


def random_initial_values(key: jnp.ndarray,
                          graph: CompiledFactorGraph) -> jnp.ndarray:
    """Random valid value per variable ([V+1] int32, sentinel row 0) —
    the reference's random_value_selection at start (dsa.py:293)."""
    u = jax.random.uniform(key, graph.var_valid.shape)
    return jnp.argmax(
        jnp.where(graph.var_valid, u, -1.0), axis=1
    ).astype(jnp.int32)

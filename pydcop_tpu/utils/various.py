"""Small shared helpers.

Reference parity: pydcop/utils/various.py (func_args :34).
"""

import inspect
from typing import Callable, List


def func_args(f: Callable) -> List[str]:
    """Positional argument names of a callable (reference various.py:34).

    >>> func_args(lambda x, y: x + y)
    ['x', 'y']
    """
    try:
        signature = inspect.signature(f)
    except (TypeError, ValueError):
        return []
    return [
        name
        for name, p in signature.parameters.items()
        if p.kind in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        )
    ]

"""Battery over SynchronousComputationMixin's BSP machinery
(infrastructure/computations.py) beyond the basics test_infrastructure
covers: filler emission, next-cycle buffering, out-of-band mgt
dispatch, outbox returns, and cycle bookkeeping (reference
test_infra_synchronous_computation.py depth)."""

from typing import Dict, List, Optional, Tuple
from unittest.mock import MagicMock

import pytest

from pydcop_tpu.infrastructure.computations import (
    ComputationException,
    Message,
    MessagePassingComputation,
    SynchronousComputationMixin,
    message_type,
    register,
)

PingMessage = message_type("ping", ["n"])


class SyncProbe(SynchronousComputationMixin, MessagePassingComputation):
    """Minimal synchronous computation with two neighbors."""

    def __init__(self, name="c1", neighbors=("n1", "n2")):
        super().__init__(name)
        self._neighbors = list(neighbors)
        self.cycles_seen: List[Tuple[int, Dict]] = []
        self.outbox: Optional[List] = None
        self._msg_sender = MagicMock()

    @property
    def neighbors(self):
        return self._neighbors

    @register("ping")
    def _on_ping(self, sender, msg, t):
        pass

    def on_new_cycle(self, messages, cycle_id):
        self.cycles_seen.append((cycle_id, dict(messages)))
        out, self.outbox = self.outbox, None
        return out


def cycle_msg(cycle, inner):
    return Message("_cycle", (cycle, inner))


def sent_messages(comp):
    return [
        (c[0][1], c[0][2]) for c in comp._msg_sender.call_args_list
    ]


class TestFillers:
    def test_start_sends_fillers_to_silent_neighbors(self):
        comp = SyncProbe()
        comp.start()
        sent = sent_messages(comp)
        targets = {t for t, _ in sent}
        assert targets == {"n1", "n2"}
        for _, m in sent:
            assert m.type == "_cycle"
            cycle, inner = m.content
            assert cycle == 0 and inner is None

    def test_algo_message_suppresses_filler(self):
        comp = SyncProbe()
        comp.on_start = lambda: comp.post_msg("n1", PingMessage(1))
        comp.start()
        by_target = {}
        for t, m in sent_messages(comp):
            by_target.setdefault(t, []).append(m)
        assert len(by_target["n1"]) == 1
        assert by_target["n1"][0].content[1].type == "ping"
        # n2 still gets exactly one filler
        assert len(by_target["n2"]) == 1
        assert by_target["n2"][0].content[1] is None


class TestCycleAdvance:
    def test_cycle_fires_once_all_neighbors_reported(self):
        comp = SyncProbe()
        comp.start()
        comp.on_message("n1", cycle_msg(0, PingMessage(1)), 0)
        assert comp.cycles_seen == []
        comp.on_message("n2", cycle_msg(0, PingMessage(2)), 0)
        assert len(comp.cycles_seen) == 1
        cycle_id, msgs = comp.cycles_seen[0]
        assert cycle_id == 0
        assert msgs["n1"][0].n == 1 and msgs["n2"][0].n == 2

    def test_fillers_excluded_from_cycle_messages(self):
        comp = SyncProbe()
        comp.start()
        comp.on_message("n1", cycle_msg(0, PingMessage(1)), 0)
        comp.on_message("n2", cycle_msg(0, None), 0)
        _, msgs = comp.cycles_seen[0]
        assert "n2" not in msgs

    def test_next_cycle_message_buffered(self):
        comp = SyncProbe()
        comp.start()
        # n1 races ahead: its cycle-1 message arrives first.
        comp.on_message("n1", cycle_msg(1, PingMessage(10)), 0)
        assert comp.cycles_seen == []
        comp.on_message("n1", cycle_msg(0, PingMessage(1)), 0)
        comp.on_message("n2", cycle_msg(0, None), 0)
        assert len(comp.cycles_seen) == 1
        # cycle 1 completes with n2's report alone.
        comp.on_message("n2", cycle_msg(1, None), 0)
        assert len(comp.cycles_seen) == 2
        assert comp.cycles_seen[1][1]["n1"][0].n == 10

    def test_cycle_id_increments(self):
        comp = SyncProbe()
        comp.start()
        for cycle in range(3):
            comp.on_message("n1", cycle_msg(cycle, None), 0)
            comp.on_message("n2", cycle_msg(cycle, None), 0)
        assert [cid for cid, _ in comp.cycles_seen] == [0, 1, 2]
        assert comp.cycle_id == 3

    def test_neighborless_computation_never_cycles(self):
        comp = SyncProbe(neighbors=())
        comp.start()
        assert comp.cycles_seen == []
        assert comp.cycle_id == 0


class TestProtocolViolations:
    def test_duplicate_current_cycle_raises(self):
        comp = SyncProbe()
        comp.start()
        comp.on_message("n1", cycle_msg(0, PingMessage(1)), 0)
        with pytest.raises(ComputationException, match="duplicate"):
            comp.on_message("n1", cycle_msg(0, PingMessage(2)), 0)

    def test_duplicate_next_cycle_raises(self):
        comp = SyncProbe()
        comp.start()
        comp.on_message("n1", cycle_msg(1, PingMessage(1)), 0)
        with pytest.raises(ComputationException, match="duplicate"):
            comp.on_message("n1", cycle_msg(1, PingMessage(2)), 0)

    def test_skew_beyond_one_cycle_raises(self):
        comp = SyncProbe()
        comp.start()
        with pytest.raises(ComputationException, match="skew"):
            comp.on_message("n1", cycle_msg(2, PingMessage(1)), 0)


class TestOutboxAndMgt:
    def test_on_new_cycle_returned_messages_are_posted(self):
        comp = SyncProbe()
        comp.start()
        comp.outbox = [("n1", PingMessage(7))]
        comp._msg_sender.reset_mock()
        comp.on_message("n1", cycle_msg(0, None), 0)
        comp.on_message("n2", cycle_msg(0, None), 0)
        by_target = {}
        for t, m in sent_messages(comp):
            by_target.setdefault(t, []).append(m)
        inner = by_target["n1"][0].content[1]
        assert inner.type == "ping" and inner.n == 7
        # Returned messages are stamped with the NEW cycle id.
        assert by_target["n1"][0].content[0] == 1

    def test_non_cycle_message_dispatches_directly(self):
        comp = SyncProbe()
        comp.start()
        hits = []
        # Per-instance copy: _decorated_handlers is class-level, and
        # mutating it in place would leak into every other SyncProbe.
        comp._decorated_handlers = dict(comp._decorated_handlers)
        comp._decorated_handlers["mgt_probe"] = (
            lambda self, s, m, t: hits.append(s))
        comp.on_message("orch", Message("mgt_probe", None), 0)
        assert hits == ["orch"]
        # No cycle advanced.
        assert comp.cycles_seen == []

    def test_pause_buffers_cycle_messages(self):
        comp = SyncProbe()
        comp.start()
        comp.pause()
        comp.on_message("n1", cycle_msg(0, PingMessage(1)), 0)
        comp.on_message("n2", cycle_msg(0, None), 0)
        assert comp.cycles_seen == []
        comp.pause(False)
        assert len(comp.cycles_seen) == 1

    def test_paused_posts_not_double_wrapped_on_resume(self):
        """A message posted while paused is wrapped in its '_cycle'
        envelope ONCE: the resume flush must resend it through the
        base post_msg, not re-wrap it through the mixin's."""
        comp = SyncProbe()
        comp.start()
        comp.pause()
        comp.post_msg("n1", PingMessage(5))
        comp._msg_sender.reset_mock()
        comp.pause(False)
        sent = sent_messages(comp)
        (target, wire), = [(t, m) for t, m in sent if t == "n1"]
        assert wire.type == "_cycle"
        cycle, inner = wire.content
        assert inner.type == "ping" and inner.n == 5  # single wrap

    def test_recv_flush_delivers_past_poisoned_entry(self):
        """A buffered message that raises during the resume flush must
        not strand the messages after it: they are delivered anyway
        (a lost message stalls a neighbor's cycle barrier forever)
        and the first error is re-raised afterwards."""
        comp = SyncProbe()
        comp.start()
        comp.on_message("n1", cycle_msg(0, PingMessage(1)), 0)
        comp.pause()
        # duplicate from n1 (raises on flush), then a valid one.
        comp.on_message("n1", cycle_msg(0, PingMessage(2)), 0)
        comp.on_message("n2", cycle_msg(0, PingMessage(3)), 0)
        with pytest.raises(ComputationException, match="duplicate"):
            comp.pause(False)
        # n2's message was delivered: the cycle completed.
        assert len(comp.cycles_seen) == 1
        assert comp._paused_messages_recv == []
        assert not comp.is_paused  # resumed despite the error

    def test_posts_flushed_even_when_recv_flush_errors(self):
        """A poisoned reception must not abort the resume before the
        buffered POSTS are drained — the posts would be stranded on a
        now-unpaused computation forever."""
        comp = SyncProbe()
        comp.start()
        comp.on_message("n1", cycle_msg(0, PingMessage(1)), 0)
        comp.pause()
        comp.on_message("n1", cycle_msg(0, PingMessage(2)), 0)  # dup
        comp.post_msg("n1", PingMessage(9))
        comp._msg_sender.reset_mock()
        with pytest.raises(ComputationException, match="duplicate"):
            comp.pause(False)
        flushed = [m for t, m in sent_messages(comp) if t == "n1"]
        assert any(
            m.type == "_cycle" and m.content[1] is not None
            and m.content[1].n == 9
            for m in flushed
        )
        assert comp._paused_messages_post == []

    def test_recv_flush_keeps_entry_on_non_protocol_error(self):
        """Only protocol violations (ComputationException, e.g. a
        duplicate cycle message) are dropped by the resume flush.  A
        reception that fails for any other reason is kept for a later
        flush — dropping it would permanently stall the sender's cycle
        barrier (ADVICE r4)."""
        boom = {"armed": True}

        class FlakyComp(MessagePassingComputation):
            def __init__(self):
                super().__init__("flaky")
                self._msg_sender = MagicMock()
                self.delivered = []

            @register("ping")
            def _on_ping(self, sender, msg, t):
                if boom["armed"]:
                    raise RuntimeError("transient handler failure")
                self.delivered.append(msg.n)

        comp = FlakyComp()
        comp.start()
        comp.pause()
        comp.on_message("n1", PingMessage(7), 0)
        with pytest.raises(RuntimeError, match="transient"):
            comp.pause(False)
        # The entry survived the failed flush (unlike a protocol
        # violation, which test_recv_flush_delivers_past_poisoned_entry
        # shows is dropped).
        assert len(comp._paused_messages_recv) == 1
        # Next pause/resume round delivers it.
        boom["armed"] = False
        comp.pause()
        comp.pause(False)
        assert comp._paused_messages_recv == []
        assert comp.delivered == [7]

    def test_recv_flush_retry_is_bounded(self):
        """A kept entry whose handler fails DETERMINISTICALLY is dropped
        after MAX_FLUSH_RETRIES failed flushes — it must not poison
        every future pause/resume round forever (review r5)."""

        class AlwaysBroken(MessagePassingComputation):
            def __init__(self):
                super().__init__("broken")
                self._msg_sender = MagicMock()

            @register("ping")
            def _on_ping(self, sender, msg, t):
                raise RuntimeError("deterministic handler bug")

        comp = AlwaysBroken()
        comp.start()
        comp.pause()
        comp.on_message("n1", PingMessage(1), 0)
        retries = MessagePassingComputation.MAX_FLUSH_RETRIES
        for i in range(retries):
            assert len(comp._paused_messages_recv) == 1
            with pytest.raises(RuntimeError):
                comp.pause(False)
            comp.pause()
        # Dropped after the cap: the next resume is clean.
        assert comp._paused_messages_recv == []
        comp.pause(False)

    def test_post_flush_retry_is_unbounded(self):
        """The retry cap applies only to the RECV path: a post that
        keeps failing environmentally (no sender attached) must survive
        arbitrarily many pause/resume rounds — dropping it would lose a
        message and stall the neighbor's cycle barrier (review r5)."""
        comp = SyncProbe()
        comp._msg_sender = None
        comp.start = lambda: None
        comp._running = True
        comp.pause()
        comp.post_msg("n1", PingMessage(1))
        rounds = MessagePassingComputation.MAX_FLUSH_RETRIES + 2
        for _ in range(rounds):
            with pytest.raises(ComputationException, match="not attached"):
                comp.pause(False)
            assert len(comp._paused_messages_post) == 1
            comp.pause()
        comp._msg_sender = MagicMock()
        comp.pause(False)
        assert comp._paused_messages_post == []
        assert comp._msg_sender.call_args_list

    def test_retried_recv_emits_message_rcv_once(self):
        """A kept recv entry that takes several flush attempts to
        deliver emits computations.message_rcv exactly once (the
        single-emission invariant; review r5)."""
        from pydcop_tpu.infrastructure.events import event_bus

        boom = {"armed": True}

        class Flaky(MessagePassingComputation):
            def __init__(self):
                super().__init__("flaky_emit")
                self._msg_sender = MagicMock()

            @register("ping")
            def _on_ping(self, sender, msg, t):
                if boom["armed"]:
                    raise RuntimeError("transient")

        comp = Flaky()
        comp.start()
        comp.pause()
        comp.on_message("n1", PingMessage(1), 0)
        emitted = []
        handle = event_bus.subscribe(
            "computations.message_rcv.flaky_emit",
            lambda topic, data: emitted.append(data),
        )
        enabled = event_bus.enabled
        event_bus.enabled = True
        try:
            with pytest.raises(RuntimeError):
                comp.pause(False)  # attempt 1: emits, handler fails
            boom["armed"] = False
            comp.pause()
            comp.pause(False)      # attempt 2: delivers, NO re-emit
        finally:
            event_bus.unsubscribe(handle)
            event_bus.enabled = enabled
        assert len(emitted) == 1

    def test_post_flush_keeps_failed_entry_for_retry(self):
        """Posts that fail environmentally (here: no sender attached)
        stay buffered — unlike poisoned receptions they are expected
        to succeed later."""
        comp = SyncProbe()
        comp._msg_sender = None
        comp.start = lambda: None  # avoid start-time fillers
        comp._running = True
        comp.pause()
        comp.post_msg("n1", PingMessage(1))
        with pytest.raises(ComputationException, match="not attached"):
            comp.pause(False)
        assert len(comp._paused_messages_post) == 1
        # Once attached, a pause/resume round delivers it.
        comp._msg_sender = MagicMock()
        comp.pause()
        comp.pause(False)
        assert comp._paused_messages_post == []
        assert comp._msg_sender.call_args_list

    def test_paused_send_emitted_once_on_event_bus(self):
        from pydcop_tpu.infrastructure.events import event_bus

        comp = SyncProbe()
        comp.start()
        events = []
        handle = event_bus.subscribe(
            "computations.message_snd.*",
            lambda topic, data: events.append(topic))
        enabled = event_bus.enabled
        event_bus.enabled = True
        try:
            comp.pause()
            comp.post_msg("n1", PingMessage(1))
            comp.pause(False)
        finally:
            event_bus.enabled = enabled
            event_bus.unsubscribe(handle)
        assert len(events) == 1

"""A-DSA: asynchronous DSA, clock-driven.

Reference parity: pydcop/algorithms/adsa.py (:121-131: params variant,
probability, period 0.5) — each variable re-evaluates on a periodic
clock tick using whatever neighbor values it has seen, instead of
waiting for a full cycle of value messages.

Device path: two schedules.

- ``schedule=lockstep`` (default): the engine evaluates every variable
  each superstep, i.e. the `period` is one superstep for everyone;
  `period` is accepted for compatibility and used by the agent-mode
  runtime (periodic actions on the agent clock).
- ``schedule=staggered``: the variable graph is greedily colored
  (ops/dsa.py greedy_classes) and each superstep only ONE color class
  may flip, so neighbors never flip simultaneously — emulating the
  clock skew that saves the true-async runtime from simultaneous-flip
  thrash.  One adsa *cycle* is a full sweep over the classes (every
  variable gets one update opportunity, like one async period), so
  stop_cycle/max_cycles are scaled by n_classes internally and budgets
  stay comparable.

Measured semantics cost of the lockstep substitution (20-seed paired
CI, tests/api/test_async_equivalence.py): at MATCHED cycle budgets
lockstep solution quality is slightly worse than the clock-driven
async runtime (mean gap ~3% of the constraint count — simultaneous
neighbor flips thrash where async's skewed updates do not); at native
budgets the gap vanishes, because device supersteps are ~free and the
engine simply runs more of them.

Staggered-schedule finding (round 5, recorded negative result): the
graph-colored schedule does NOT measurably change matched-budget
quality on the equivalence battery's family — the deterministic
device-device pairing measures staggered - lockstep = +1.45 mean cost
(~0.9% of constraints, statistically flat), and repeated thread-paired
batteries wander inside the thread-side noise floor (per-seed sd ~15).
Mechanism: at p=0.7 flip probability on sparse graphs (~3.9 avg
degree) simultaneous-neighbor flips are too rare for schedule skew to
matter — which also bounds the round-4 "+3% lockstep gap" attribution
as measurement noise.  The schedule stays available for denser /
higher-probability regimes where thrash is real.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'adsa', max_cycles=30, algo_params={'seed': 1})
    >>> round(res['cost'], 3)
    0.0
"""

from functools import partial
from typing import Optional

import jax.numpy as jnp

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.algorithms import dsa as _dsa
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import compile_dcop, validated_aggregation
from pydcop_tpu.engine.runner import DeviceRunResult, run_device_fn
from pydcop_tpu.ops.dsa import greedy_classes, run_dsa

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    # Variable-aggregation strategy for the shared local-search
    # kernels (ops/localsearch.py): "scatter" is the parity
    # default; "ell" replaces every segment_sum/max/min with
    # compile-time dense-gather edge lists (the TPU HBM-regime
    # candidate, benchmarks/exp_aggregation.py).  Single-device;
    # sharded runs always use scatter.
    AlgoParameterDef(
        "aggregation", "str", ["scatter", "ell"], "scatter"
    ),
    AlgoParameterDef("probability", "float", None, 0.7),
    AlgoParameterDef("variant", "str", ["A", "B", "C"], "B"),
    AlgoParameterDef("period", "float", None, 0.5),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("seed", "int", None, 0),
    AlgoParameterDef("schedule", "str", ["lockstep", "staggered"],
                     "lockstep"),
]

computation_memory = _dsa.computation_memory
communication_load = _dsa.communication_load


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("adsa", comp_def)


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    warmup: bool = False,
                    **_) -> DeviceRunResult:
    params = algo_def.params
    if params.get("schedule", "lockstep") == "staggered":
        return _solve_staggered(
            dcop, algo_def, max_cycles=max_cycles, mesh=mesh,
            n_devices=n_devices, warmup=warmup,
        )
    inner = AlgorithmDef(
        "dsa",
        {
            "probability": params.get("probability", 0.7),
            "p_mode": "fixed",
            "variant": params.get("variant", "B"),
            "stop_cycle": params.get("stop_cycle", 0),
            "seed": params.get("seed", 0),
        },
        algo_def.mode,
    )
    return _dsa.solve_on_device(
        dcop, inner, max_cycles=max_cycles, mesh=mesh,
        n_devices=n_devices, warmup=warmup,
    )


def _solve_staggered(dcop: DCOP, algo_def: AlgorithmDef, *,
                     max_cycles: int, mesh, n_devices, warmup
                     ) -> DeviceRunResult:
    """Graph-colored schedule: one superstep flips one color class;
    one *cycle* (budget unit) is a full sweep over all classes, so
    every variable keeps one update opportunity per cycle like the
    async runtime's one per period."""
    params = algo_def.params
    pad_to = mesh.size if mesh is not None else (n_devices or 1)
    graph, meta = compile_dcop(
        dcop, pad_to=pad_to,
        aggregation=validated_aggregation(params, pad_to))
    classes_np, n_classes = greedy_classes(graph)
    classes = jnp.asarray(classes_np)
    cycles = params.get("stop_cycle") or max_cycles
    fn = partial(
        run_dsa,
        max_cycles=cycles * n_classes,
        variant=params.get("variant", "B"),
        probability=params.get("probability", 0.7),
        seed=params.get("seed", 0),
        classes=classes,
        n_classes=n_classes,
    )
    res = run_device_fn(
        graph, meta, fn, mesh=mesh, n_devices=n_devices, warmup=warmup,
        finished=bool(params.get("stop_cycle")),
    )
    res.metrics["schedule"] = "staggered"
    res.metrics["n_classes"] = n_classes
    res.metrics["supersteps"] = res.cycles
    # Report budget-comparable cycles (full sweeps).
    res.cycles = res.cycles // n_classes
    return res

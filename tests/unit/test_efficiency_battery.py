"""Battery for the device-efficiency accounting plane (ISSUE 14):
ledger assembly + the components-sum-to-total invariant across solo,
binned, envelope-packed, lane-packed and session dispatch paths;
attainment math on synthetic cost entries; the tracker rollup
(per-backend / per-structure separation, waste by cause); the
``/profile`` endpoint and ``pydcop profile report --json`` schemas;
backend-label propagation into the metrics exposition; the sentinel's
cross-backend refusal; the dynamic engine's deferred-edit batching
(behavior-identical to per-action application, incl. mid-batch
recompile and the failed-batch partial-apply contract); and the
probelog tail + postmortem-bundle sections."""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "tools"))

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine import batch as engine_batch
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.engine.dynamic import DynamicMaxSumEngine
from pydcop_tpu.observability import efficiency
from pydcop_tpu.observability.efficiency import (
    EfficiencyTracker,
    attainment_from_cost,
    ledger_component_sum,
    make_ledger,
    resolved_backend,
    split_device_time,
)
from pydcop_tpu.observability.metrics import registry
from pydcop_tpu.serving.service import SolveService

MAX_CYCLES = 40
PARAMS = {"max_cycles": MAX_CYCLES}
LEDGER_TOL = 0.05


@pytest.fixture(autouse=True)
def _fresh_plane():
    registry.reset()
    efficiency.tracker.clear()
    yield
    registry.reset()
    efficiency.tracker.clear()


def _ring(n: int, seed: int, d: int = 3) -> DCOP:
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP(f"ring{n}_{seed}_{d}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n):
        table = rng.integers(0, 10, size=(d, d)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[(k + 1) % n]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _assert_ledger_sums(ledger, tol=LEDGER_TOL):
    assert isinstance(ledger, dict) and ledger.get("total_s", 0) > 0
    gap = abs(ledger_component_sum(ledger) - ledger["total_s"])
    assert gap <= tol * ledger["total_s"], ledger


# ------------------------------------------------------------------ #
# ledger helpers
# ------------------------------------------------------------------ #

class TestLedger:
    def test_make_ledger_sums_and_rounds(self):
        ledger = make_ledger(1.0, submit=0.1, queue=0.2, plan=0.05,
                             prep=0.05, compile=0.3, execute=0.25,
                             decode=0.05)
        assert ledger["total_s"] == 1.0
        assert abs(ledger_component_sum(ledger) - 1.0) < 1e-9
        assert abs(ledger["unaccounted_s"]) < 1e-9

    def test_unaccounted_is_honest_not_absorbed(self):
        ledger = make_ledger(1.0, execute=0.4)
        assert ledger["unaccounted_s"] == pytest.approx(0.6)

    def test_unknown_component_rejected(self):
        with pytest.raises(ValueError, match="unknown ledger"):
            make_ledger(1.0, warp=0.5)

    def test_negative_components_clamped(self):
        ledger = make_ledger(0.5, queue=-0.1, execute=0.5)
        assert ledger["queue_s"] == 0.0

    def test_split_device_time_cold_and_warm(self):
        # Cold: overlapping-fields convention (compile == time) —
        # the whole interval charges to compile, execute 0.
        cold = split_device_time(0.8, 0.8)
        assert cold == {"compile": 0.8, "execute": 0.0}
        warm = split_device_time(0.8, 0.0)
        assert warm == {"compile": 0.0, "execute": 0.8}
        assert sum(cold.values()) == sum(warm.values()) == 0.8


# ------------------------------------------------------------------ #
# attainment math on synthetic cost entries
# ------------------------------------------------------------------ #

class TestAttainment:
    def test_exact_numbers_against_env_peaks(self, monkeypatch):
        monkeypatch.setenv("PYDCOP_PEAK_FLOPS", "1e9")
        monkeypatch.setenv("PYDCOP_PEAK_BYTES_PER_S", "1e10")
        entry = {"available": True, "flops": 1e5,
                 "bytes_accessed": 2e5}
        att = attainment_from_cost(entry, cycles=100,
                                   execute_s=0.1, backend="cpu")
        # achieved flops/s = 1e5 * 100 / 0.1 = 1e8 -> 0.1 of peak.
        assert att["flop_attainment"] == pytest.approx(0.1)
        # achieved B/s = 2e5 * 100 / 0.1 = 2e8 -> 0.02 of peak.
        assert att["bandwidth_attainment"] == pytest.approx(0.02)
        # Roofline verdict: the binding (better-attained) resource.
        assert att["attainment"] == pytest.approx(0.1)
        assert att["peak_source"] == "env"

    def test_unavailable_entry_is_none_not_zero(self):
        assert attainment_from_cost(
            {"available": False}, 10, 0.1, "cpu") is None
        assert attainment_from_cost(None, 10, 0.1, "cpu") is None

    def test_zero_execute_or_cycles_is_none(self):
        entry = {"available": True, "flops": 1e5}
        assert attainment_from_cost(entry, 0, 0.1, "cpu") is None
        assert attainment_from_cost(entry, 10, 0.0, "cpu") is None

    def test_useful_work_fraction_discounts_waste(self):
        tracker = EfficiencyTracker()
        tracker.enabled = True
        record = tracker.record_dispatch(
            key="k", structure="s", backend="cpu",
            time_s=0.1, compile_s=0.0, cycles=10,
            n_real=2, batch_size=4, pad_fraction=0.5,
            envelope_waste=0.2, packing="envelope",
            cost_entry={"available": True, "flops": 1e6})
        assert record["attainment"] is not None
        assert record["useful_work_fraction"] == pytest.approx(
            record["attainment"] * 0.5 * 0.8)

    def test_disabled_tracker_records_nothing(self):
        tracker = EfficiencyTracker()
        tracker.enabled = False
        assert tracker.record_dispatch(
            key="k", structure="s", backend="cpu", time_s=0.1,
            compile_s=0.0, cycles=10, n_real=1,
            batch_size=1) is None
        assert tracker.rollup()["structures"] == []


# ------------------------------------------------------------------ #
# tracker rollup
# ------------------------------------------------------------------ #

class TestRollup:
    def _tracker(self):
        tracker = EfficiencyTracker()
        tracker.enabled = True
        entry = {"available": True, "flops": 1e6,
                 "bytes_accessed": 1e6}
        # Two backends, two structures on cpu; devices separated.
        for backend, structure, execute in (
                ("cpu", "sA", 0.2), ("cpu", "sA", 0.2),
                ("cpu", "sB", 0.1), ("tpu", "sA", 0.01)):
            tracker.record_dispatch(
                key="k", structure=structure, backend=backend,
                time_s=execute, compile_s=0.0, cycles=50,
                n_real=1, batch_size=1, cost_entry=entry)
        return tracker

    def test_backends_never_share_a_rollup(self):
        roll = self._tracker().rollup()
        assert set(roll["backends"]) == {"cpu", "tpu"}
        assert roll["backends"]["cpu"]["dispatches"] == 3
        assert roll["backends"]["tpu"]["dispatches"] == 1
        # The tpu cell ran the same program 20x faster: attainment
        # must be proportionally higher relative to ITS peak scale.
        assert (roll["backends"]["tpu"]["attainment"]
                != roll["backends"]["cpu"]["attainment"])

    def test_structures_ranked_by_device_time(self):
        roll = self._tracker().rollup()
        assert roll["structures"][0]["structure"] == "sA"
        assert roll["structures"][0]["backend"] == "cpu"
        assert roll["structures_total"] == 3

    def test_waste_by_cause_and_ledger_totals(self):
        tracker = self._tracker()
        tracker.record_jit("k", True, 0.5)
        tracker.record_ledger(make_ledger(
            1.0, queue=0.4, execute=0.6), backend="cpu")
        roll = tracker.rollup()
        assert roll["waste_by_cause"]["compile_s"] == \
            pytest.approx(0.5)
        assert roll["waste_by_cause"]["queue_s"] == \
            pytest.approx(0.4)
        assert roll["ledger"]["components_s"]["execute"] == \
            pytest.approx(0.6)
        assert roll["ledger"]["counts"] == {"request": 1}

    def test_pad_waste_charged_from_execute(self):
        tracker = EfficiencyTracker()
        tracker.enabled = True
        tracker.record_dispatch(
            key="k", structure="s", backend="cpu", time_s=1.0,
            compile_s=0.0, cycles=10, n_real=1, batch_size=2,
            pad_fraction=0.5)
        roll = tracker.rollup()
        assert roll["backends"]["cpu"]["pad_waste_s"] == \
            pytest.approx(0.5)

    def test_summary_is_compact_and_backend_labeled(self):
        summary = self._tracker().summary()
        assert summary["backend"] == resolved_backend()["backend"]
        assert "ledger_components_s" in summary
        assert "waste_by_cause" in summary


# ------------------------------------------------------------------ #
# ledger invariant across the real dispatch paths
# ------------------------------------------------------------------ #

class TestServiceLedgers:
    def _serve_burst(self, dcops, service_kw=None, params=None):
        service = SolveService(batch_window_s=0.05, max_batch=16,
                               **(service_kw or {})).start()
        try:
            ids = [service.submit(d, params=params or PARAMS)
                   for d in dcops]
            results = [service.result(i, wait=60) for i in ids]
        finally:
            service.stop()
        assert all(r is not None and r["status"] == "FINISHED"
                   for r in results), results
        return results

    def test_solo_and_binned_ledgers_sum(self):
        # 3 same-structure (one binned dispatch) + 1 other (solo).
        results = self._serve_burst(
            [_ring(6, s) for s in range(3)] + [_ring(10, 9)])
        for res in results:
            _assert_ledger_sums(res["ledger"])
        kinds = {res["batch"]["packing"] for res in results}
        assert "structure" in kinds

    def test_envelope_packed_ledgers_sum(self):
        # Distinct structures, prune=1 keeps them off the lane path,
        # zero modeled overhead forces the pack.
        results = self._serve_burst(
            [_ring(n, n) for n in (6, 9, 12)],
            service_kw={"envelope_overhead_ms": 1e6, "lane_pack": False},
            params={"max_cycles": MAX_CYCLES})
        for res in results:
            _assert_ledger_sums(res["ledger"])
        assert any(res["batch"]["packing"] == "envelope"
                   for res in results), [
                       r["batch"] for r in results]

    def test_lane_packed_ledgers_sum(self):
        results = self._serve_burst(
            [_ring(n, n) for n in (6, 9, 12)],
            service_kw={"envelope_overhead_ms": 1e6})
        for res in results:
            _assert_ledger_sums(res["ledger"])
        assert any(res["batch"]["packing"] == "lane"
                   for res in results), [
                       r["batch"] for r in results]

    def test_finished_requests_feed_the_rollup(self):
        self._serve_burst([_ring(6, s) for s in range(2)])
        roll = efficiency.tracker.rollup()
        assert roll["ledger"]["counts"].get("request", 0) >= 2
        assert roll["backends"], roll

    def test_session_segment_ledgers_sum(self):
        service = SolveService(batch_window_s=0.01).start()
        try:
            sess = service.sessions.open(
                _ring(8, 3), params={"max_cycles": 120,
                                     "segment_cycles": 30})
            out = service.sessions.apply_events(
                sess.id,
                [{"type": "change_factor", "name": "c0",
                  "variables": ["v0", "v1"],
                  "table": [[0, 5, 5], [5, 0, 5], [5, 5, 0]]}],
                wait=30.0)
            assert out["applied"] is True
            ledger = out["result"]["ledger"]
            _assert_ledger_sums(ledger)
            status = service.sessions.status(sess.id)
            _assert_ledger_sums(status["last"]["ledger"])
        finally:
            service.stop()
        assert efficiency.tracker.rollup()["ledger"]["counts"].get(
            "session", 0) >= 1

    def test_expired_request_still_carries_summing_ledger(self):
        service = SolveService(batch_window_s=0.01).start()
        try:
            req_id = service.submit(_ring(6, 0), params=PARAMS,
                                    deadline_s=1e-9)
            res = service.result(req_id, wait=30)
        finally:
            service.stop()
        if res is not None and res["status"] == "EXPIRED":
            _assert_ledger_sums(res["ledger"], tol=0.5)


# ------------------------------------------------------------------ #
# surfaces: /profile, /metrics labels, profile report
# ------------------------------------------------------------------ #

class TestSurfaces:
    def _burst(self):
        service = SolveService(batch_window_s=0.02).start()
        try:
            for rnd in range(2):  # warm round populates attainment
                ids = [service.submit(_ring(6, s), params=PARAMS)
                       for s in range(2)]
                for i in ids:
                    assert service.result(i, wait=60) is not None
            stats = service.stats()
        finally:
            service.stop()
        return stats

    def test_stats_efficiency_block(self):
        stats = self._burst()
        eff = stats["efficiency"]
        assert eff["backend"] == resolved_backend()["backend"]
        assert eff["useful_work_fraction"] is not None
        assert 0 < eff["useful_work_fraction"] <= 1.5
        assert eff["ledger_components_s"].get("execute", 0) > 0

    def test_metrics_exposition_is_backend_labeled(self):
        self._burst()
        text = registry.to_prometheus()
        backend = resolved_backend()["backend"]
        assert (f'pydcop_useful_work_fraction{{backend='
                f'"{backend}"}}') in text
        assert (f'pydcop_device_execute_seconds_total{{backend='
                f'"{backend}"') in text
        assert 'pydcop_request_ledger_seconds_total{' in text

    def test_profile_endpoint_schema(self):
        import urllib.request

        from pydcop_tpu.observability.server import TelemetryServer

        self._burst()
        server = TelemetryServer(port=0).start()
        try:
            with urllib.request.urlopen(
                    f"{server.url}/profile", timeout=30) as resp:
                doc = json.loads(resp.read())
        finally:
            server.stop()
        assert doc["backend"]["backend"] == \
            resolved_backend()["backend"]
        assert doc["structures"], doc
        assert set(doc["waste_by_cause"]) == {
            "padding_s", "envelope_s", "compile_s", "queue_s"}
        assert "components_s" in doc["ledger"]

    def test_profile_report_json_live(self):
        from pydcop_tpu.commands import profile as profile_cmd
        from pydcop_tpu.dcop_cli import make_parser

        self._burst()
        parser = make_parser()
        args = parser.parse_args(["profile", "report", "--json"])
        import io
        import sys as _sys

        out = io.StringIO()
        stdout, _sys.stdout = _sys.stdout, out
        try:
            rc = profile_cmd.run_report(args)
        finally:
            _sys.stdout = stdout
        assert rc == 0
        doc = json.loads(out.getvalue())
        assert doc["mode"] == ["self"]
        assert doc["live"]["backends"], doc

    def test_profile_report_trace_mode(self, tmp_path):
        from pydcop_tpu.commands.profile import trace_breakdown
        from pydcop_tpu.observability.trace import tracer

        tracer.enable()
        try:
            with tracer.span("serve_dispatch", "serving",
                             bin="v6d3habc"):
                with tracer.span("engine_segment", "engine"):
                    pass
            with tracer.span("jit_compile", "engine", key="k"):
                pass
        finally:
            tracer.disable()
        path = str(tmp_path / "trace.jsonl")
        tracer.export(path, "jsonl")
        doc = trace_breakdown([path])
        spans = {c["span"] for c in doc["components"]}
        assert {"serve_dispatch", "engine_segment",
                "jit_compile"} <= spans
        assert doc["structures"][0]["structure"] == "v6d3habc"

    def test_profile_report_bench_mode(self, tmp_path):
        from pydcop_tpu.commands.profile import bench_backends

        json.dump(
            {"parsed": {"value": 1.0, "backend": "tpu",
                        "leg_backends": {
                            "serve": {"backend": "cpu"},
                            "headline": {"backend": "tpu"}}}},
            open(tmp_path / "BENCH_r01.json", "w"))
        rows = bench_backends(str(tmp_path))
        assert rows[0]["leg_backends"] == {"serve": "cpu",
                                           "headline": "tpu"}


# ------------------------------------------------------------------ #
# sentinel cross-backend refusal
# ------------------------------------------------------------------ #

def _write_round(root, i, serve_value, headline_backend,
                 serve_backend, with_legs=True):
    parsed = {"value": 900, "backend": headline_backend,
              "serve_problems_per_sec": serve_value}
    if with_legs:
        parsed["leg_backends"] = {
            "headline": {"backend": headline_backend},
            "serve": {"backend": serve_backend},
        }
    json.dump({"parsed": parsed},
              open(os.path.join(root, f"BENCH_r{i:02d}.json"), "w"))


class TestSentinelBackendRefusal:
    def _write_round(self, root, i, serve_value, headline_backend,
                     serve_backend, with_legs=True):
        _write_round(root, i, serve_value, headline_backend,
                     serve_backend, with_legs)

    def test_cpu_fallback_leg_never_pads_tpu_baseline(self, tmp_path):
        import bench_sentinel

        root = str(tmp_path)
        # TPU serve history, then a round whose serve leg fell back
        # to CPU with a (for TPU) catastrophic value.
        for i, v in enumerate([500, 510, 505, 498], 1):
            self._write_round(root, i, v, "tpu", "tpu")
        self._write_round(root, 5, 30, "tpu", "cpu")
        report = bench_sentinel.run_check(root)
        # The cpu leg forms its own 1-point series (insufficient),
        # the tpu baseline is NOT judged against (or padded by) it,
        # and the mismatch is named.
        assert report["series"]["serve:cpu"]["verdict"] == \
            "insufficient"
        assert 30 not in report["series"]["serve:tpu"]["values"]
        assert any("SKIPPED" in line and "cpu" in line
                   and "tpu" in line for line in report["lines"])
        assert not report["failed"]

    def test_matching_backend_is_judged(self, tmp_path):
        import bench_sentinel

        root = str(tmp_path)
        for i, v in enumerate([100, 102, 99, 101], 1):
            self._write_round(root, i, v, "cpu", "cpu")
        self._write_round(root, 5, 30, "cpu", "cpu")
        report = bench_sentinel.run_check(root)
        assert report["series"]["serve:cpu"]["verdict"] == \
            "regressed"
        assert report["failed"]

    def test_legacy_rows_without_leg_backends_unchanged(self,
                                                        tmp_path):
        import bench_sentinel

        root = str(tmp_path)
        for i, v in enumerate([100, 102, 99, 101, 100], 1):
            self._write_round(root, i, v, "cpu", "cpu",
                              with_legs=False)
        report = bench_sentinel.run_check(root)
        assert report["series"]["serve:cpu"]["verdict"] == "ok"
        assert not any("SKIPPED" in line for line in report["lines"])


# ------------------------------------------------------------------ #
# deferred-edit batching (the PR-13 efficiency-note fix)
# ------------------------------------------------------------------ #

def _dyn_engine(n=8, seed=4, slack=0.5):
    dcop = _ring(n, seed)
    return DynamicMaxSumEngine(
        list(dcop.variables.values()),
        list(dcop.constraints.values()),
        noise_level=0.01, slack=slack)


def _apply_all(engine, actions, batched):
    from pydcop_tpu.engine.dynamic import apply_action

    import contextlib as _ctx

    ctx = engine.batch_edits() if batched else _ctx.nullcontext()
    errors = []
    with ctx:
        for a in actions:
            args = {k: v for k, v in a.items() if k != "type"}
            try:
                apply_action(engine, a["type"], args)
            except Exception as exc:  # noqa: BLE001
                errors.append(str(exc))
                break
    return errors


def _assert_engines_equal(a, b):
    np.testing.assert_array_equal(
        np.asarray(a.graph.var_costs), np.asarray(b.graph.var_costs))
    assert len(a.graph.buckets) == len(b.graph.buckets)
    for ba, bb in zip(a.graph.buckets, b.graph.buckets):
        np.testing.assert_array_equal(np.asarray(ba.costs),
                                      np.asarray(bb.costs))
        np.testing.assert_array_equal(np.asarray(ba.var_ids),
                                      np.asarray(bb.var_ids))
    assert a.slots == b.slots
    assert sorted(a.factors) == sorted(b.factors)
    if a._state is None or b._state is None:
        assert (a._state is None) == (b._state is None)
        return
    for leaf_a, leaf_b in zip(
            (*a._state.v2f, *a._state.f2v,
             *a._state.v2f_count, *a._state.f2v_count),
            (*b._state.v2f, *b._state.f2v,
             *b._state.v2f_count, *b._state.f2v_count)):
        np.testing.assert_array_equal(np.asarray(leaf_a),
                                      np.asarray(leaf_b))


MUTATION_LADDER = [
    {"type": "change_factor", "name": "c0",
     "variables": ["v0", "v1"],
     "table": [[0, 7, 7], [7, 0, 7], [7, 7, 0]]},
    {"type": "remove_factor", "name": "c3"},
    {"type": "add_factor", "name": "cX",
     "variables": ["v2", "v5"],
     "table": [[1, 2, 3], [4, 5, 6], [7, 8, 9]]},
    {"type": "change_factor", "name": "cX",
     "variables": ["v2", "v5"],
     "table": [[9, 8, 7], [6, 5, 4], [3, 2, 1]]},
    {"type": "remove_factor", "name": "c5"},
    {"type": "add_factor", "name": "cY",
     "variables": ["v6", "v7"],
     "table": [[0, 1, 0], [1, 0, 1], [0, 1, 0]]},
]


class TestBatchEdits:
    def test_batched_equals_sequential_cold(self):
        seq, bat = _dyn_engine(), _dyn_engine()
        assert not _apply_all(seq, MUTATION_LADDER, batched=False)
        assert not _apply_all(bat, MUTATION_LADDER, batched=True)
        _assert_engines_equal(seq, bat)

    def test_batched_equals_sequential_warm_state(self):
        seq, bat = _dyn_engine(), _dyn_engine()
        seq.run(max_cycles=30)
        bat.run(max_cycles=30)
        assert not _apply_all(seq, MUTATION_LADDER, batched=False)
        assert not _apply_all(bat, MUTATION_LADDER, batched=True)
        _assert_engines_equal(seq, bat)
        # And the post-event trajectories agree.
        ra = seq.run(max_cycles=60)
        rb = bat.run(max_cycles=60)
        assert ra.assignment == rb.assignment

    def test_recompile_mid_batch_matches_sequential(self):
        actions = MUTATION_LADDER[:2] + [
            {"type": "add_variable", "name": "w0",
             "domain": [0, 1, 2]},
            {"type": "add_factor", "name": "cW",
             "variables": ["w0", "v0"],
             "table": [[0, 2, 2], [2, 0, 2], [2, 2, 0]]},
        ] + MUTATION_LADDER[2:4]
        seq, bat = _dyn_engine(), _dyn_engine()
        seq.run(max_cycles=30)
        bat.run(max_cycles=30)
        assert not _apply_all(seq, actions, batched=False)
        assert not _apply_all(bat, actions, batched=True)
        _assert_engines_equal(seq, bat)

    def test_failed_batch_partial_apply_matches(self):
        actions = MUTATION_LADDER[:3] + [
            {"type": "remove_factor", "name": "nope"},  # semantic err
        ] + MUTATION_LADDER[4:]
        seq, bat = _dyn_engine(), _dyn_engine()
        seq.run(max_cycles=20)
        bat.run(max_cycles=20)
        err_a = _apply_all(seq, actions, batched=False)
        err_b = _apply_all(bat, actions, batched=True)
        assert err_a and err_b
        # Earlier actions STAND identically: the flush runs on the
        # early-error exit too.
        _assert_engines_equal(seq, bat)

    def test_slack_reuse_remove_then_add_same_row(self):
        actions = [
            {"type": "remove_factor", "name": "c1"},
            {"type": "add_factor", "name": "cZ",
             "variables": ["v1", "v4"],
             "table": [[5, 0, 0], [0, 5, 0], [0, 0, 5]]},
        ]
        seq, bat = _dyn_engine(slack=0.1), _dyn_engine(slack=0.1)
        assert not _apply_all(seq, actions, batched=False)
        assert not _apply_all(bat, actions, batched=True)
        _assert_engines_equal(seq, bat)

    def test_one_copy_per_touched_bucket_per_batch(self):
        engine = _dyn_engine()
        copies = [0]
        original = DynamicMaxSumEngine._materialize_bucket_rows

        def counting(self, costs, var_ids, rows):
            copies[0] += 1
            return original(self, costs, var_ids, rows)

        try:
            DynamicMaxSumEngine._materialize_bucket_rows = counting
            _apply_all(engine, MUTATION_LADDER, batched=True)
        finally:
            DynamicMaxSumEngine._materialize_bucket_rows = original
        # All six actions touch the single binary bucket: one
        # materialization, not six.
        assert copies[0] == 1

    def test_session_apply_event_batch_uses_batching(self):
        from pydcop_tpu.serving.sessions import apply_event_batch

        seq, bat = _dyn_engine(), _dyn_engine()
        seq.run(max_cycles=20)
        bat.run(max_cycles=20)
        _apply_all(seq, MUTATION_LADDER, batched=False)
        applied, _touched, error = apply_event_batch(
            bat, MUTATION_LADDER)
        assert error is None and len(applied) == len(MUTATION_LADDER)
        _assert_engines_equal(seq, bat)


# ------------------------------------------------------------------ #
# probelog tail + bundle sections
# ------------------------------------------------------------------ #

class TestBundleSections:
    def test_probelog_tail_reads_record_diag_format(self, tmp_path,
                                                    monkeypatch):
        from pydcop_tpu.utils.cleanenv import probelog_tail

        path = tmp_path / "probelog.jsonl"
        rows = [{"unix": 1.0 + i, "event": "probe", "ok": i % 2 == 0}
                for i in range(30)]
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
            f.write("not json\n")
        monkeypatch.setenv("PYDCOP_PROBELOG", str(path))
        tail = probelog_tail(5)
        assert len(tail) == 5
        assert tail[-1]["unix"] == 30.0

    def test_probelog_tail_missing_file_is_empty(self, monkeypatch):
        monkeypatch.setenv("PYDCOP_PROBELOG", "/nonexistent/x.jsonl")
        from pydcop_tpu.utils.cleanenv import probelog_tail

        assert probelog_tail() == []

    def test_bundle_carries_efficiency_and_probe_tail(self, tmp_path,
                                                      monkeypatch):
        from pydcop_tpu.observability.flight import FlightRecorder

        path = tmp_path / "probelog.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"unix": 1.0, "event": "probe",
                                "ok": False,
                                "error": "timeout after 20s"}) + "\n")
        monkeypatch.setenv("PYDCOP_PROBELOG", str(path))
        efficiency.tracker.record_dispatch(
            key="k", structure="s", backend="cpu", time_s=0.1,
            compile_s=0.0, cycles=10, n_real=1, batch_size=1)
        doc = FlightRecorder(bundle_dir=str(tmp_path)).make_bundle(
            "test", {})
        assert doc["probe_log_tail"][0]["error"] == \
            "timeout after 20s"
        assert doc["efficiency"]["backend"]["backend"] == \
            resolved_backend()["backend"]
        assert doc["efficiency"]["structures"]


# ------------------------------------------------------------------ #
# real-dispatch attainment end-to-end
# ------------------------------------------------------------------ #

class TestRealDispatchAttainment:
    def test_warm_stacked_dispatch_attains(self):
        from pydcop_tpu.observability.profiler import profiler

        was = profiler.enabled
        profiler.enabled = True
        try:
            graph = compile_dcop(_ring(6, 1), noise_level=0.01)[0]
            engine_batch.run_stacked([graph, graph],
                                     max_cycles=MAX_CYCLES)
            _v, _c, warm = engine_batch.run_stacked(
                [graph, graph], max_cycles=MAX_CYCLES)
        finally:
            profiler.enabled = was
        record = warm.metrics["efficiency"]
        assert record["backend"] == resolved_backend()["backend"]
        assert record["compile_s"] == 0.0
        assert record["attainment"] is not None
        assert 0 < record["attainment"] <= 2.0
        assert record["useful_work_fraction"] == \
            pytest.approx(record["attainment"])

    def test_cold_dispatch_charges_compile_not_execute(self):
        graph = compile_dcop(_ring(7, 2), noise_level=0.01)[0]
        _v, _c, cold = engine_batch.run_stacked(
            [graph], max_cycles=MAX_CYCLES + 1)
        record = cold.metrics["efficiency"]
        assert record["compile_s"] > 0
        assert record["execute_s"] == 0.0
        assert record["attainment"] is None


# ------------------------------------------------------------------ #
# review-hardening regressions
# ------------------------------------------------------------------ #

class TestReviewRegressions:
    def test_restore_syncs_cycle_baseline(self, tmp_path):
        """A checkpoint-restored engine must not account every
        pre-checkpoint cycle to its first post-restore run — that
        inflated attainment by the whole restored history."""
        donor = _dyn_engine()
        donor.run(max_cycles=100)
        path = str(tmp_path / "ck.npz")
        donor.checkpoint(path)
        fresh = _dyn_engine()
        fresh.restore(path)
        assert fresh._cycles_recorded == \
            int(np.asarray(fresh._state.cycle))
        res = fresh.run(max_cycles=30)
        ran = fresh._cycles_recorded - int(
            np.asarray(donor._state.cycle))
        assert 0 <= ran <= 30 + 1, (ran, res.cycles)

    def test_peak_source_mixed_when_half_calibrated(self,
                                                    monkeypatch):
        from pydcop_tpu.observability.efficiency import backend_peaks

        monkeypatch.delenv("PYDCOP_PEAK_FLOPS", raising=False)
        monkeypatch.delenv("PYDCOP_PEAK_BYTES_PER_S", raising=False)
        assert backend_peaks("cpu")["source"] == "default"
        monkeypatch.setenv("PYDCOP_PEAK_FLOPS", "1e9")
        assert backend_peaks("cpu")["source"] == "mixed"
        monkeypatch.setenv("PYDCOP_PEAK_BYTES_PER_S", "1e10")
        assert backend_peaks("cpu")["source"] == "env"

    def test_terminal_ledger_post_dispatch_time_is_prep_not_queue(
            self):
        """A decode/dispatch failure after device work must not
        label the device seconds as queue wait."""
        import time as _time

        service = SolveService(batch_window_s=0.01)
        req = __import__(
            "pydcop_tpu.serving.service",
            fromlist=["SolveRequest"]).SolveRequest(
            id="x", dcop=None, graph=None, meta=None, params={},
            bin=None, t_submit=_time.perf_counter() - 1.0)
        req.t_enqueue = req.t_submit + 0.1
        req.t_dispatch = req.t_submit + 0.3
        ledger = service._terminal_ledger(req)
        assert ledger["queue_s"] == pytest.approx(0.2, abs=0.05)
        assert ledger["prep_s"] >= 0.6
        _assert_ledger_sums(ledger)

    def test_envelope_dispatch_label_is_the_envelope_shape(self):
        """Members of one envelope-packed dispatch share ONE
        structure cell (the padded shape), not the first member's
        pre-padding shape."""
        from pydcop_tpu.serving import binning

        g_small = compile_dcop(_ring(6, 1), noise_level=0.01)[0]
        g_big = compile_dcop(_ring(12, 2), noise_level=0.01)[0]
        env = binning.envelope_key(g_big)
        efficiency.tracker.clear()
        engine_batch.run_stacked([g_small, g_big],
                                 max_cycles=MAX_CYCLES,
                                 envelope=env)
        roll = efficiency.tracker.rollup()
        assert len(roll["structures"]) == 1
        label = roll["structures"][0]["structure"]
        assert label.startswith(f"v{env.v_env}d{env.d_env}")

    def test_malformed_table_fails_its_action_batch_scoped(self):
        """A bad cost table inside a deferred batch must fail at ITS
        action (the sequential contract), not at the flush — and the
        engines must still match afterwards."""
        actions = MUTATION_LADDER[:2] + [
            {"type": "change_factor", "name": "c0",
             "variables": ["v0", "v1"],
             # 5x5 table into a 3x3 domain: _render_row must raise.
             "table": [[1] * 5] * 5},
        ] + MUTATION_LADDER[2:3]
        seq, bat = _dyn_engine(), _dyn_engine()
        seq.run(max_cycles=20)
        bat.run(max_cycles=20)
        err_a = _apply_all(seq, actions, batched=False)
        err_b = _apply_all(bat, actions, batched=True)
        assert err_a and err_b
        _assert_engines_equal(seq, bat)
        assert bat._edit_session is None

    def test_flush_failure_clears_session_and_returns_batch_error(
            self, monkeypatch):
        """Even a flush-time failure must keep apply_event_batch's
        tuple contract AND leave the engine out of deferred mode —
        a stuck session would silently drop every later edit."""
        from pydcop_tpu.serving.sessions import apply_event_batch

        engine = _dyn_engine()

        def boom(self):
            if self._edit_session and self._edit_session["buckets"]:
                raise RuntimeError("synthetic flush failure")

        monkeypatch.setattr(DynamicMaxSumEngine,
                            "_flush_pending_edits", boom)
        applied, _touched, error = apply_event_batch(
            engine, MUTATION_LADDER[:1])
        assert error is not None and "flush" in error
        assert engine._edit_session is None
        monkeypatch.undo()
        # The engine still accepts (and materializes) edits.
        assert not _apply_all(engine, MUTATION_LADDER[:1],
                              batched=False)

    def test_sentinel_newest_is_the_newest_numbered_round(
            self, tmp_path):
        """BENCH_TPU_LAST.json (appended last by load_history) must
        not define which backend the newest ROUND resolved."""
        import bench_sentinel

        root = str(tmp_path)
        for i, v in enumerate([900, 910, 905, 898, 902], 1):
            json.dump({"parsed": {
                "value": v, "backend": "cpu",
                "leg_backends": {"headline": {"backend": "cpu"}}}},
                open(os.path.join(root, f"BENCH_r0{i}.json"), "w"))
        json.dump({"value": 1083.0, "backend": "tpu"},
                  open(os.path.join(root, "BENCH_TPU_LAST.json"),
                       "w"))
        report = bench_sentinel.run_check(root)
        # The newest numbered round resolved cpu: the cpu series is
        # judged normally and NO cpu round is SKIPPED against the
        # stale tpu reference artifact.
        assert report["series"]["cpu"]["verdict"] == "ok"
        assert not any("SKIPPED" in line for line in report["lines"])

    def test_stale_backend_series_reports_but_does_not_gate(
            self, tmp_path):
        """A regression inside a backend series the newest round did
        NOT resolve must not fail CI — the report already says those
        rows were not compared against the round under test."""
        import bench_sentinel

        root = str(tmp_path)
        # A tpu serve history that ends on a (for tpu) catastrophic
        # value, then a newest round whose serve leg resolved cpu.
        for i, v in enumerate([500, 510, 505, 498, 300], 1):
            _write_round(root, i, v, "tpu", "tpu")
        _write_round(root, 6, 120, "tpu", "cpu")
        report = bench_sentinel.run_check(root)
        tpu = report["series"]["serve:tpu"]
        assert tpu["verdict"] == "regressed"
        assert tpu["gating"] is False
        assert any("stale backend — not gating" in line
                   for line in report["lines"])
        assert not report["failed"]

    def test_dynamic_engine_outside_sessions_labels_dynamic(self):
        """A scenario replay / direct dynamic engine is NOT a
        session: its dispatches must not masquerade as session work
        in the rollup's request classes."""
        engine = _dyn_engine()
        engine.run(max_cycles=20)
        engine.run(max_cycles=20)
        classes = set()
        for row in efficiency.tracker.rollup()["structures"]:
            classes |= set(row["by_class"])
        assert classes == {"dynamic"}

    def test_disabled_plane_skips_metrics_entirely(self):
        was = efficiency.tracker.enabled
        efficiency.tracker.enabled = False
        try:
            graph = compile_dcop(_ring(6, 5), noise_level=0.01)[0]
            _v, _c, res = engine_batch.run_stacked(
                [graph], max_cycles=MAX_CYCLES)
        finally:
            efficiency.tracker.enabled = was
        assert "efficiency" not in res.metrics
        assert efficiency.tracker.rollup()["structures"] == []


# ------------------------------------------------------------------ #
# closed-loop hot path (ISSUE 18): pipelined flushes + speculation
# ------------------------------------------------------------------ #

class TestPipelinedFlush:
    """Ledger honesty and terminal ordering when dispatch k+1
    launches before dispatch k decodes (the pipelined flush path)."""

    def _pipelined_burst(self, dcops, service_kw=None):
        """Warm pass (compiles every program synchronously), then the
        measured burst on warm programs — only warm dispatches take
        the pipelined launch/collect path."""
        kw = dict({"pipeline": True}, **(service_kw or {}))
        service = SolveService(batch_window_s=0.05, max_batch=16,
                               **kw).start()
        completions = []
        orig_pub = service._publish_lifecycle

        def pub(event, req):
            if event == "finished":
                completions.append(req.id)
            return orig_pub(event, req)

        service._publish_lifecycle = pub
        try:
            ids = [service.submit(d, params=PARAMS) for d in dcops]
            warm = [service.result(i, wait=60) for i in ids]
            assert all(r["status"] == "FINISHED" for r in warm), warm
            completions.clear()
            ids = [service.submit(d, params=PARAMS) for d in dcops]
            results = [service.result(i, wait=60) for i in ids]
            assert all(r["status"] == "FINISHED"
                       for r in results), results
            reqs = {i: service._requests[i] for i in ids}
            stats = service.stats()
        finally:
            service.stop()
        return ids, results, reqs, stats, completions

    def test_multibin_pipelined_ledgers_and_ordering(self):
        # Two structures x 2 requests: two bins per flush, so the
        # second bin's device call launches while the first bin's
        # arrays are still in flight (scheduler pending depth 2).
        dcops = ([_ring(6, s) for s in range(2)]
                 + [_ring(9, s) for s in range(2)])
        ids, results, reqs, stats, completions = \
            self._pipelined_burst(dcops)
        assert stats["pipeline"]["enabled"]
        assert stats["pipeline"]["pipelined_dispatches"] >= 2, stats
        roll = efficiency.tracker.rollup()
        assert roll["pipeline"]["dispatches"] >= 2
        assert 0.0 <= roll["pipeline_overlap_fraction"] <= 1.0
        for res in results:
            # Sum-to-latency holds on the pipelined path, and decode
            # is attributed to the owning request (its own host
            # post-processing wall, never zeroed by the overlap).
            _assert_ledger_sums(res["ledger"])
            assert res["ledger"]["decode_s"] > 0.0, res["ledger"]
        # Terminal callbacks fire in pickup order: the order the
        # scheduler dispatched (t_dispatch), not decode-completion
        # races.
        pickup = sorted(ids, key=lambda i: reqs[i].t_dispatch)
        assert completions == pickup, (completions, pickup)

    def test_pipelined_envelope_and_lane_ledgers(self):
        mixed = [_ring(5, 0), _ring(6, 1), _ring(7, 2)]
        for kw in ({"envelope_overhead_ms": 1e6, "lane_pack": False},
                   {"envelope_overhead_ms": 1e6}):
            efficiency.tracker.clear()
            _ids, results, _reqs, stats, _comp = \
                self._pipelined_burst(mixed, service_kw=kw)
            assert stats["pipeline"]["pipelined_dispatches"] >= 1
            kinds = {r["batch"]["packing"] for r in results}
            assert kinds <= {"envelope", "lane"}, kinds
            for res in results:
                _assert_ledger_sums(res["ledger"])

    def test_no_pipeline_knob_stays_synchronous(self):
        dcops = [_ring(6, s) for s in range(2)]
        _ids, results, _reqs, stats, _comp = self._pipelined_burst(
            dcops, service_kw={"pipeline": False})
        assert stats["pipeline"]["pipelined_dispatches"] == 0
        for res in results:
            _assert_ledger_sums(res["ledger"])

    def test_stubbed_run_batch_never_pipelines(self):
        # A test double stubbing the device call IS the contract
        # under test for a pile of batteries: the pipelined path must
        # step aside for it.
        service = SolveService(batch_window_s=0.02, pipeline=True)
        calls = []
        orig = SolveService._run_batch

        def stub(reqs, params):
            calls.append(len(reqs))
            return orig(service, reqs, params)

        service._run_batch = stub
        service.start()
        try:
            i = service.submit(_ring(6, 0), params=PARAMS)
            r1 = service.result(i, wait=60)
            i = service.submit(_ring(6, 1), params=PARAMS)
            r2 = service.result(i, wait=60)
        finally:
            stats = service.stats()
            service.stop()
        assert r1["status"] == r2["status"] == "FINISHED"
        assert len(calls) == 2, calls
        assert stats["pipeline"]["pipelined_dispatches"] == 0


class TestSpeculativeCompiles:
    """Tentpole (b) discipline: background compiles never run on the
    device-owning scheduler thread, are compile-only (trace-span
    asserted), and a speculated program's first real dispatch counts
    as a hit."""

    def test_speculation_off_thread_and_hits(self):
        from pydcop_tpu.observability.trace import tracer
        from pydcop_tpu.serving import binning

        tracer.enable()
        service = SolveService(batch_window_s=0.05, max_batch=16,
                               pipeline=True, speculate=True).start()
        try:
            sched_ident = service._scheduler_ident
            assert sched_ident is not None
            # Phase 1: a recurring solo structure seeds the arrival
            # histogram; the speculator AOT-builds the bin rungs its
            # traffic will need (bs=2 among them).  The structure is
            # unique to this test (ring 11) — a key another battery
            # test already dispatched would be live-warm, which the
            # speculator rightly refuses to rebuild (and whose first
            # dispatch here would not be cold, so no hit either).
            for s in range(2):
                i = service.submit(_ring(11, s), params=PARAMS)
                assert service.result(i, wait=60)[
                    "status"] == "FINISHED"
            graph, _ = compile_dcop(_ring(11, 0), pad_to=1,
                                    aggregation="scatter")
            p = binning.normalize_params(PARAMS)
            prep = engine_batch._prepare_stacked(
                [graph, graph], p["max_cycles"], p["damping"],
                p["damping_nodes"], p["stability"],
                service.bin_sizes, False, None)
            expected = str(prep.key)
            import time as _time

            deadline = _time.time() + 120
            spec = service._speculator
            while (_time.time() < deadline
                   and expected not in spec.compiled_keys):
                _time.sleep(0.2)
            assert expected in spec.compiled_keys, spec.stats()
            # Phase 2: the predicted bin-of-2 arrives; its program is
            # cold in the jit cache but speculatively built — a hit.
            ids = [service.submit(_ring(11, s), params=PARAMS)
                   for s in (7, 8)]
            results = [service.result(i, wait=60) for i in ids]
            assert all(r["status"] == "FINISHED" for r in results)
            stats = service.stats()
            assert stats["speculation"]["enabled"]
            assert stats["speculation"][
                "speculative_compiles_total"] >= 1
            assert stats["speculation"]["hits"] >= 1, stats
            # Discipline: every compile ran off the scheduler thread.
            assert spec.records, "no compile records"
            for rec in spec.records:
                assert rec["thread_ident"] != sched_ident, rec
                assert rec["compile_only"], rec
            # Trace-span asserted too: speculative_compile spans
            # carry their thread and the compile-only flag, and none
            # ever ran on the dispatch-owning thread.
            spans = [e for e in tracer.events()
                     if e.get("name") == "speculative_compile"]
            assert spans, "no speculative_compile spans recorded"
            for ev in spans:
                assert ev["args"]["compile_only"] is True
                assert ev["args"]["thread"] != sched_ident
        finally:
            service.stop()
            tracer.disable()
            tracer.clear()

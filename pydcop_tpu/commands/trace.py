"""``pydcop trace``: inspect trace files produced by ``--trace``.

``pydcop trace summary FILE`` prints top-k span aggregates (count,
total/mean/max duration) from a Chrome ``trace_event`` JSON or a JSONL
trace — the quick "where did the time go" answer that does not need a
browser.  Instant events (fault injections, breaker trips, message
sends) aggregate with zero duration; their counts are the point.
"""

import sys


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "trace", help="inspect trace files produced by --trace")
    trace_sub = parser.add_subparsers(
        title="trace commands", dest="trace_command")
    summary = trace_sub.add_parser(
        "summary", help="top-k span aggregates of a trace file")
    summary.add_argument("trace_file", help="chrome-JSON or JSONL "
                                            "trace file")
    summary.add_argument("--top", type=int, default=15,
                         help="rows to print (default 15)")
    summary.add_argument("--by", default="name",
                         choices=["name", "cat"],
                         help="aggregate by span name or category")
    summary.set_defaults(func=run_summary)
    parser.set_defaults(func=_no_subcommand(parser))


def _no_subcommand(parser):
    def run(_args) -> int:
        parser.print_help(sys.stderr)
        return 2

    return run


def run_summary(args) -> int:
    from pydcop_tpu.observability.trace import (
        load_trace_file,
        summarize_spans,
    )

    events = load_trace_file(args.trace_file)
    rows = summarize_spans(events, by=args.by, top=args.top)
    spans = sum(1 for e in events if e.get("ph") == "X")
    instants = sum(1 for e in events if e.get("ph") == "i")
    threads = len({e.get("tid") for e in events})
    print(f"{args.trace_file}: {spans} spans, {instants} instants, "
          f"{threads} threads")
    if not rows:
        print("no span events")
        return 0
    key_width = max(len(str(r[args.by])) for r in rows)
    key_width = max(key_width, len(args.by))
    header = (f"{args.by:<{key_width}}  {'count':>8}  "
              f"{'total_ms':>12}  {'mean_ms':>10}  {'max_ms':>10}")
    print(header)
    print("-" * len(header))
    for r in rows:
        print(f"{str(r[args.by]):<{key_width}}  {r['count']:>8}  "
              f"{r['total_ms']:>12.3f}  {r['mean_ms']:>10.3f}  "
              f"{r['max_ms']:>10.3f}")
    return 0
